# Make targets mirroring the reference UX (reference Makefile:1-58 drives
# docker compose + spark-submit; here every target is the in-process CLI).
#
#   make demo        — full E2E: datagen → CDC envelopes → sinks → scorer
#   make datagen     — generate a transactions table        (≈ datagen)
#   make train       — offline training                     (≈ notebooks)
#   make score       — stream-score through the engine      (≈ make fraud_detection)
#   make run-all     — datagen + train + score              (≈ make run-all)
#   make bench       — benchmark harness (full JSON line + compact headline)
#   make test        — pytest on a virtual 8-device CPU mesh
#   make install     — editable install incl. the `rtfds` console script

PY ?= python
# PLATFORM=cpu pins jax to CPU (e.g. when the TPU tunnel is down; the
# CLI fails fast with rc 3 instead of hanging when it can't come up).
PLATFORM ?=
CLI = $(PY) -m real_time_fraud_detection_system_tpu.cli \
      $(if $(PLATFORM),--platform $(PLATFORM),)
OUT ?= out
CONNECT_URL ?= http://localhost:8083
# Dataset scale: moderate default so `make run-all` finishes in minutes on
# a laptop CPU; reference scale (data_generator.ipynb · cell 34) is
# `make datagen CUSTOMERS=5000 TERMINALS=10000 DAYS=245`.
CUSTOMERS ?= 1000
TERMINALS ?= 2000
DAYS ?= 120

demo:
	@mkdir -p $(OUT)
	$(CLI) demo --out $(OUT)/analyzed

datagen:
	@mkdir -p $(OUT)
	$(CLI) datagen --out $(OUT)/txs.npz --customers $(CUSTOMERS) \
	    --terminals $(TERMINALS) --days $(DAYS)

train:
	$(CLI) train --data $(OUT)/txs.npz --model forest --out-model $(OUT)/model.npz

score:
	$(CLI) score --data $(OUT)/txs.npz --model-file $(OUT)/model.npz \
	    --scorer tpu --mode envelope --out $(OUT)/analyzed \
	    --raw-table $(OUT)/transactions --checkpoint-dir $(OUT)/ck

run-all: datagen train score

query:
	$(CLI) query --data $(OUT)/analyzed --report summary

dashboard:
	$(CLI) dashboard --data $(OUT)/analyzed --out $(OUT)/dashboard.html

connectors:
	$(CLI) connectors --connect-url $(CONNECT_URL)

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# produce a sample span trace on CPU (Chrome-trace JSON for Perfetto +
# the ASCII waterfall) — the zero-hardware tour of the tracing layer
trace-demo:
	@mkdir -p $(OUT)
	JAX_PLATFORMS=cpu $(PY) tools/trace_demo.py --out $(OUT)/trace_demo.json

bench:
	$(PY) bench.py

# fast CPU perf gate: loop-thread sink_write stays enqueue-bounded under
# the async sink, and precompiled serving records ZERO mid-stream XLA
# recompiles across every bucket size (the PR-3 hot-loop invariants)
perf-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_perf_smoke.py -q

# chaos gate: one scripted run with flaky polls, a silent hang, and a
# poison micro-batch must COMPLETE with exact restart/crash-loop counts,
# the DLQ holding exactly the injected rows, and gap/dup-free sink
# lineage (the PR-4 survive-poison-input invariants)
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos_smoke.py -q

# dirty-recovery gate: every durable-state failure mode — kill-during-
# save, byte-flip, truncation, flaky store, torn PUT, broken delta
# chain — across BOTH checkpoint planes (local + object store) must
# recover to a COMPLETE stream with exact corrupt/fallback counters
# from the registry and gap/dup-free sink lineage
recovery-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_recovery_smoke.py -q

# static-analysis gate: the project-native analyzer (tools/rtfdslint)
# must report ZERO unbaselined P0/P1 findings over the whole package —
# recompile hazards, cross-thread races, exception-taxonomy erosion,
# wall-clock durations, metric/config drift, loop-thread blocking. The
# lint pass runs jax-free (pure stdlib ast); the gate then folds in the
# device-contract verifier (verify-static below), so one exit status
# covers both levels. Accept a deliberate finding with an inline
# `# rtfdslint: disable=<rule> (<reason>)` pragma or
# `rtfds lint --update-baseline --reason '...'`.
lint-static:
	$(PY) -m real_time_fraud_detection_system_tpu.cli lint
	$(MAKE) verify-static

# device-contract verification gate (tools/rtfdsverify): build
# weightless template engines, load their dispatch signature
# inventories (the SAME enumeration precompile() compiles), and prove
# on the traced jaxprs — no device, no weights — that (1) every
# reachable dispatch signature is AOT-covered, (2) the int8/bf16
# z-mode exactness contract holds structurally (integer z arithmetic,
# f32-HIGHEST decision/leaf contractions, no laundered downcasts),
# (3) donation is exactly the feature state and off under the
# nan-guard, (4) Pallas VMEM block budgets and tile alignment admit
# every use_pallas signature. Zero unbaselined P0/P1 to pass.
verify-static:
	JAX_PLATFORMS=cpu $(PY) -m real_time_fraud_detection_system_tpu.cli verify-device

# overload-survival gate: under an injected traffic burst the
# hysteresis ladder must climb rung-by-rung (shed optional work ->
# largest AOT bucket + alerts-only -> whole-batch deferral to the
# durable spill), descend fully once pressure subsides, replay every
# deferred batch in order with gap/dup-free sink lineage, pay zero
# mid-stream recompiles across the whole cycle, and finish with scores
# bit-identical to an unthrottled control run (scored + deferred ==
# polled, asserted from the registry)
overload-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_overload_smoke.py -q

# tiered-feature-store gate: a Zipf stream over a key universe 100x the
# hot tier must complete under --precompile with ZERO mid-stream
# recompiles (compaction + sketch-tier overflow active, both enumerated
# in dispatch_inventory), exact tier counters from the registry
# (dense + cms == rows x keyspaces), compaction firing AND reclaiming,
# and gap/dup-free sink lineage — on the single-chip engine AND the
# sharded cell (4 virtual devices: per-shard directories, shard-exact
# tier counters, compaction reclaiming on EVERY shard, per-shard
# /healthz breakdown)
state-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_state_smoke.py -q

# multi-host gate: 2 REAL serving processes (own interpreters, a real
# jax.distributed coordination barrier, partition-affine ingest, per-
# process checkpoints/sinks/registries) complete a scripted stream
# under --precompile beside a single-process 2-device sharded control —
# zero mid-stream recompiles in EVERY worker (from each worker's own
# registry dump), gap/dup-free per-process sink batch_index lineage
# covering the stream exactly once globally, global shard ids + process
# labels on the per-shard gauges, and scores + all 15 feature columns
# BIT-identical to the control
multihost-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_multihost_smoke.py -q

# elastic-fleet gate: the autoscaler grows a live 1-process fleet to 2
# under sustained rung-2 pressure (drain -> exact merge -> committed
# topology -> relaunch) with the stream covered exactly once across
# the resize, shrinks 2 -> 1 on sustained idle through the same seam,
# rolls back to the pre-resize fleet under injected chaos (worker
# SIGKILL mid-drain; crash-pre-relaunch and torn-manifest cells run
# with -m slow), zero mid-stream recompiles in every generation, and
# ownership floors provably drop already-scored rows on re-poll
elastic-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_elastic_smoke.py -q

# continuous-learning gate: champion serves, the streaming learner
# trains a candidate on injected labeled feedback, the shadow's live
# recall overtakes the champion's, promotion fires, an injected
# regression rolls it back — zero mid-stream recompiles under
# precompile, every claim asserted from rtfds_* registry metrics, and
# a corrupt candidate artifact can never be promoted
learn-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_learn_smoke.py -q

test:
	$(PY) -m pytest tests/ -q

# wire-level boundary tests against real services (skip cleanly when the
# dependency/service is absent — see tests/integration/README.md)
integration:
	$(PY) -m pytest tests/integration/ -v || [ $$? -eq 5 ]  # 5 = all skipped (deps absent)

# one-command wire-level verification: boot the deploy/ stack (where
# docker exists), then run the integration suite against it with the
# matching env. `make integration-down` tears the stack down.
integration-up:
	@command -v docker >/dev/null 2>&1 || { \
	  echo "docker not found: boot deploy/docker-compose.yml on a docker" \
	       "host, or run 'make integration' with services you provide"; \
	  exit 2; }
# createbuckets is a one-shot: run it in the foreground (older compose
# v2 releases mis-handle exited services under --wait)
	cd deploy && docker compose up -d --wait postgres kafka connect minio \
	  && docker compose up createbuckets
	RTFDS_KAFKA_BOOTSTRAP=localhost:9092 \
	RTFDS_PG_DSN="dbname=payment user=payment password=payment host=localhost" \
	RTFDS_S3_BUCKET=commerce RTFDS_S3_ENDPOINT=http://localhost:9000 \
	AWS_ACCESS_KEY_ID=minio AWS_SECRET_ACCESS_KEY=minio123 \
	$(PY) -m pytest tests/integration/ -v

integration-down:
	cd deploy && docker compose down -v

# prove the analyzed Parquet output serves the dashboard queries as SQL
# (DuckDB when installed, else pyarrow+sqlite), cross-checked vs io/query
sqlcheck:
	JAX_PLATFORMS=cpu $(PY) tools/parquet_sql_check.py

install:
	$(PY) -m pip install -e .

clean:
	rm -rf $(OUT)

.PHONY: demo datagen train score run-all query dashboard connectors dryrun trace-demo bench perf-smoke chaos-smoke recovery-smoke overload-smoke state-smoke learn-smoke multihost-smoke elastic-smoke lint-static verify-static test integration integration-up integration-down sqlcheck install clean
