"""Synthetic transaction generator (benchmark load source + training data).

Capability match for the reference simulator
(``fraud_detection_model/data_generator.ipynb``, Fraud-Detection-Handbook
style), with identical distributions and fraud-scenario semantics:

- customer profiles: location ~ U(0,100)^2, ``mean_amount`` ~ U(5,100),
  ``std_amount = mean/2``, ``mean_nb_tx_per_day`` ~ U(0,4)  (· "cell 4");
- terminal profiles: location ~ U(0,100)^2  (· "cell 8");
- customer↔terminal association by Euclidean radius ``r``  (· "cell 12");
- per (customer, day): Poisson(mean_nb_tx) transactions, time ~
  Normal(noon, 20000 s) kept iff within the day, amount ~ Normal(mean, std)
  with negative redraw ~ U(0, 2·mean), terminal uniform over the customer's
  in-radius set  (· "cell 24");
- fraud scenarios (· "cell 42"):
  1. amount > 220 ⇒ fraud;
  2. each day, 2 random terminals compromised for the next 28 days;
  3. each day, 3 random customers compromised for 14 days, ⅓ of their
     transactions get amount ×5 and are marked fraud.

The implementation is brand new and columnar: one vectorized NumPy pass
instead of the reference's per-customer/per-day Python loops, so generating
the full 5000×10000×245-day dataset takes seconds and can feed the benchmark
harness at line rate. Amounts are kept as **int64 cents** end-to-end
(DECIMAL(10,2) fidelity — never silently f32 money).

RNG note: we use ``np.random.default_rng`` streams (PCG64) rather than the
reference's legacy per-customer ``np.random.seed`` — draws are reproducible
under our own seeds but not bit-identical to the reference (the reference
publishes no dataset artifact to match anyway).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from real_time_fraud_detection_system_tpu.config import DataConfig

SECONDS_PER_DAY = 86400
NOON = SECONDS_PER_DAY // 2
TIME_STD = 20000.0


@dataclass
class CustomerProfiles:
    customer_id: np.ndarray  # int64 [C]
    x: np.ndarray  # float64 [C]
    y: np.ndarray  # float64 [C]
    mean_amount: np.ndarray  # float64 [C]
    std_amount: np.ndarray  # float64 [C]
    mean_nb_tx_per_day: np.ndarray  # float64 [C]
    # CSR layout of the in-radius terminal sets
    available_terminals: np.ndarray  # int64 flat indices
    available_offsets: np.ndarray  # int64 [C+1]

    @property
    def n(self) -> int:
        return int(self.customer_id.shape[0])

    def n_terminals_of(self, c: int) -> int:
        return int(self.available_offsets[c + 1] - self.available_offsets[c])


@dataclass
class TerminalProfiles:
    terminal_id: np.ndarray  # int64 [T]
    x: np.ndarray  # float64 [T]
    y: np.ndarray  # float64 [T]

    @property
    def n(self) -> int:
        return int(self.terminal_id.shape[0])


@dataclass
class Transactions:
    """Columnar transaction table, sorted chronologically.

    ``tx_id`` is the row index after the chronological sort, exactly like the
    reference's ``TRANSACTION_ID`` (· generate_dataset).
    """

    tx_id: np.ndarray  # int64 [N]
    tx_time_seconds: np.ndarray  # int64 [N], seconds since start_date
    tx_time_days: np.ndarray  # int32 [N]
    customer_id: np.ndarray  # int64 [N]
    terminal_id: np.ndarray  # int64 [N]
    amount_cents: np.ndarray  # int64 [N]
    tx_fraud: np.ndarray  # int8 [N]
    tx_fraud_scenario: np.ndarray  # int8 [N]

    @property
    def n(self) -> int:
        return int(self.tx_id.shape[0])

    @property
    def amount(self) -> np.ndarray:
        """Amounts as float64 dollars (for model features / metrics only)."""
        return self.amount_cents.astype(np.float64) / 100.0

    def epoch_us(self, start_epoch_s: int) -> np.ndarray:
        """µs-since-unix-epoch timestamps (the Debezium wire unit)."""
        return (start_epoch_s + self.tx_time_seconds) * 1_000_000

    def slice(self, mask_or_idx) -> "Transactions":
        return Transactions(*[getattr(self, f)[mask_or_idx]
                              for f in ("tx_id", "tx_time_seconds", "tx_time_days",
                                        "customer_id", "terminal_id", "amount_cents",
                                        "tx_fraud", "tx_fraud_scenario")])

    def to_pandas(self, start_date: str = "2025-04-01"):
        import pandas as pd

        ts = pd.to_datetime(self.tx_time_seconds, unit="s", origin=start_date)
        return pd.DataFrame(
            {
                "TRANSACTION_ID": self.tx_id,
                "TX_DATETIME": ts,
                "CUSTOMER_ID": self.customer_id,
                "TERMINAL_ID": self.terminal_id,
                "TX_AMOUNT": self.amount,
                "TX_TIME_SECONDS": self.tx_time_seconds,
                "TX_TIME_DAYS": self.tx_time_days,
                "TX_FRAUD": self.tx_fraud.astype(np.int64),
                "TX_FRAUD_SCENARIO": self.tx_fraud_scenario.astype(np.int64),
            }
        )


def generate_customer_profiles(n_customers: int, seed: int = 0) -> CustomerProfiles:
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xC057]))
    x = rng.uniform(0, 100, n_customers)
    y = rng.uniform(0, 100, n_customers)
    mean_amount = rng.uniform(5, 100, n_customers)
    return CustomerProfiles(
        customer_id=np.arange(n_customers, dtype=np.int64),
        x=x,
        y=y,
        mean_amount=mean_amount,
        std_amount=mean_amount / 2.0,
        mean_nb_tx_per_day=rng.uniform(0, 4, n_customers),
        available_terminals=np.zeros(0, dtype=np.int64),
        available_offsets=np.zeros(n_customers + 1, dtype=np.int64),
    )


def generate_terminal_profiles(n_terminals: int, seed: int = 0) -> TerminalProfiles:
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x7E12]))
    return TerminalProfiles(
        terminal_id=np.arange(n_terminals, dtype=np.int64),
        x=rng.uniform(0, 100, n_terminals),
        y=rng.uniform(0, 100, n_terminals),
    )


def associate_terminals(
    customers: CustomerProfiles, terminals: TerminalProfiles, radius: float,
    block: int = 1024,
) -> CustomerProfiles:
    """Fill the CSR (available_terminals, available_offsets) in-radius sets.

    Blocked distance computation keeps peak memory at block×T instead of C×T.
    """
    tx = terminals.x
    ty = terminals.y
    counts = np.zeros(customers.n, dtype=np.int64)
    chunks = []
    for s in range(0, customers.n, block):
        e = min(s + block, customers.n)
        d2 = (customers.x[s:e, None] - tx[None, :]) ** 2 + (
            customers.y[s:e, None] - ty[None, :]
        ) ** 2
        within = d2 < radius * radius
        counts[s:e] = within.sum(axis=1)
        rows, cols = np.nonzero(within)
        # rows are already sorted, so cols are grouped per customer in order
        chunks.append(cols.astype(np.int64))
    offsets = np.zeros(customers.n + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    customers.available_terminals = (
        np.concatenate(chunks) if chunks else np.zeros(0, dtype=np.int64)
    )
    customers.available_offsets = offsets
    return customers


def generate_transactions(
    customers: CustomerProfiles, n_days: int, seed: int = 0
) -> Transactions:
    """Vectorized transaction synthesis over all (customer, day) pairs."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0x7A3B]))
    C = customers.n

    # Number of txs per (customer, day): Poisson(mean_nb_tx_per_day).
    lam = np.broadcast_to(customers.mean_nb_tx_per_day[:, None], (C, n_days))
    nb_tx = rng.poisson(lam)  # [C, D]
    # Customers with no in-radius terminal produce no transactions
    # (reference keeps a tx only when available_terminals is non-empty).
    n_avail = np.diff(customers.available_offsets)
    nb_tx[n_avail == 0, :] = 0

    per_pair = nb_tx.ravel()  # [C*D]
    total = int(per_pair.sum())
    cust = np.repeat(np.arange(C, dtype=np.int64), nb_tx.sum(axis=1))
    day = np.repeat(
        np.broadcast_to(np.arange(n_days, dtype=np.int32), (C, n_days)).ravel(),
        per_pair,
    )

    # Time of day ~ Normal(noon, 20000 s); out-of-day draws are DISCARDED
    # (reference filters, not clips — keeps the same diurnal shape).
    tod = rng.normal(NOON, TIME_STD, total)
    keep = (tod > 0) & (tod < SECONDS_PER_DAY)

    cust = cust[keep]
    day = day[keep]
    tod = tod[keep].astype(np.int64)
    total = cust.shape[0]

    # Amount ~ Normal(mean, std) per customer; negatives redrawn U(0, 2*mean).
    mean = customers.mean_amount[cust]
    amount = rng.normal(mean, customers.std_amount[cust])
    neg = amount < 0
    amount[neg] = rng.uniform(0.0, 2.0 * mean[neg])
    amount_cents = np.round(amount * 100.0).astype(np.int64)

    # Terminal: uniform over the customer's in-radius CSR slice.
    lo = customers.available_offsets[cust]
    hi = customers.available_offsets[cust + 1]
    pick = lo + rng.integers(0, np.maximum(hi - lo, 1))
    terminal = customers.available_terminals[pick] if total else np.zeros(0, np.int64)

    t_seconds = day.astype(np.int64) * SECONDS_PER_DAY + tod
    order = np.argsort(t_seconds, kind="stable")
    return Transactions(
        tx_id=np.arange(total, dtype=np.int64),
        tx_time_seconds=t_seconds[order],
        tx_time_days=day[order].astype(np.int32),
        customer_id=cust[order],
        terminal_id=terminal[order],
        amount_cents=amount_cents[order],
        tx_fraud=np.zeros(total, dtype=np.int8),
        tx_fraud_scenario=np.zeros(total, dtype=np.int8),
    )


def add_frauds(
    customers: CustomerProfiles,
    terminals: TerminalProfiles,
    txs: Transactions,
    cfg: DataConfig = DataConfig(),
) -> Transactions:
    """Apply the three fraud scenarios in-place (same precedence as reference:
    later scenarios overwrite earlier labels on overlapping rows)."""
    rng = np.random.default_rng(np.random.SeedSequence([cfg.seed, 0xF4A0]))
    n_days = int(txs.tx_time_days.max()) + 1 if txs.n else 0

    # Scenario 1: amount > threshold.
    thresh_cents = int(round(cfg.scenario1_amount_threshold * 100))
    s1 = txs.amount_cents > thresh_cents
    txs.tx_fraud[s1] = 1
    txs.tx_fraud_scenario[s1] = 1

    # Scenario 2: per start-day compromised terminals for a 28-day span.
    # Vectorized: build per-terminal compromise intervals, then interval test.
    # terminal_compromised[t] holds start days; a tx at (t, d) is fraud iff
    # some start s satisfies s <= d < s + span.
    span2 = cfg.scenario2_compromise_days
    starts2 = np.empty((n_days, cfg.scenario2_terminals_per_day), dtype=np.int64)
    for d in range(n_days):
        starts2[d] = rng.choice(terminals.n, cfg.scenario2_terminals_per_day,
                                replace=False)
    # Map terminal -> sorted list of compromise start days.
    comp_term = starts2.ravel()
    comp_day = np.repeat(np.arange(n_days, dtype=np.int64),
                         cfg.scenario2_terminals_per_day)
    s2_mask = _interval_membership(txs.terminal_id, txs.tx_time_days,
                                   comp_term, comp_day, span2)
    txs.tx_fraud[s2_mask] = 1
    txs.tx_fraud_scenario[s2_mask] = 2

    # Scenario 3: per start-day compromised customers for a 14-day span;
    # a random third of their txs in the window get amount x5 + fraud.
    span3 = cfg.scenario3_compromise_days
    mult = cfg.scenario3_amount_multiplier
    for d in range(n_days):
        comp_cust = rng.choice(customers.n, cfg.scenario3_customers_per_day,
                               replace=False)
        in_window = (
            (txs.tx_time_days >= d)
            & (txs.tx_time_days < d + span3)
            & np.isin(txs.customer_id, comp_cust)
        )
        idx = np.nonzero(in_window)[0]
        k = int(len(idx) * cfg.scenario3_fraction)
        if k == 0:
            continue
        chosen = rng.choice(idx, size=k, replace=False)
        txs.amount_cents[chosen] = (txs.amount_cents[chosen] * mult).astype(np.int64)
        txs.tx_fraud[chosen] = 1
        txs.tx_fraud_scenario[chosen] = 3
    return txs


def _interval_membership(
    keys: np.ndarray, days: np.ndarray,
    comp_keys: np.ndarray, comp_starts: np.ndarray, span: int,
) -> np.ndarray:
    """mask[i] = any(comp_keys==keys[i] and comp_starts<=days[i]<comp_starts+span).

    Sort compromises by (key, start) and for each tx binary-search the key's
    slice, then check whether any start falls in (day-span, day].
    """
    order = np.lexsort((comp_starts, comp_keys))
    ck = comp_keys[order]
    cs = comp_starts[order]
    # Slice boundaries per key value
    left = np.searchsorted(ck, keys, side="left")
    right = np.searchsorted(ck, keys, side="right")
    # Within [left, right), starts are sorted: need any start in (day-span, day]
    lo = np.empty_like(left)
    hi = np.empty_like(left)
    # Positions of the bounds inside the global sorted starts restricted to the
    # key slice: since cs is sorted within each key slice, use per-row search.
    # Vectorized via searchsorted on the full array with offsets is incorrect
    # across slice boundaries, so clamp results into [left, right).
    # Number of starts <= day within slice:
    hi = _searchsorted_within(cs, keys_left=left, keys_right=right,
                              values=days, side="right")
    lo = _searchsorted_within(cs, keys_left=left, keys_right=right,
                              values=days - span, side="right")
    return hi > lo


def _searchsorted_within(
    sorted_vals: np.ndarray, keys_left: np.ndarray, keys_right: np.ndarray,
    values: np.ndarray, side: str,
) -> np.ndarray:
    """Per-row searchsorted of values[i] into sorted_vals[keys_left[i]:keys_right[i]].

    Implemented as a branchless vectorized binary search (≈log2(max slice)
    iterations over all rows at once).
    """
    lo = keys_left.astype(np.int64).copy()
    hi = keys_right.astype(np.int64).copy()
    max_len = int(np.max(keys_right - keys_left)) if len(keys_left) else 0
    iters = max(1, int(np.ceil(np.log2(max_len + 1))) + 1)
    for _ in range(iters):
        mid = (lo + hi) // 2
        active = lo < hi
        mv = sorted_vals[np.minimum(mid, len(sorted_vals) - 1)]
        if side == "right":
            go_right = mv <= values
        else:
            go_right = mv < values
        lo = np.where(active & go_right, mid + 1, lo)
        hi = np.where(active & ~go_right, mid, hi)
    return lo


class ZipfKeySampler:
    """Bounded Zipf(s) key sampler over ``[0, n_keys)`` — the 10M-key
    skewed-corpus mode for feature-state scale benchmarks.

    Real traffic over millions of customers is heavy-tailed: a small hot
    set produces most rows while the long tail trickles. ``P(rank k) ∝
    1/k^skew`` with exact inverse-CDF sampling (one float64 cumsum built
    once, ``searchsorted`` per draw — ~80 MB at 10M keys, no rejection
    distortion like clipped ``np.random.zipf``). ``skew=0`` degenerates
    to uniform. Rank r maps to key ``(r * STRIDE) % n_keys`` (an odd
    stride coprime to any pow2-adjacent universe), so the hot set is
    scattered across the id space instead of sitting in the low ids a
    ``direct``-mode table would accidentally favor.

    Universes past ``_EXACT_MAX`` (16.7M) keep the exact CDF for the
    head ranks only (where essentially all per-rank mass sits) and draw
    tail ranks from the continuous power-law inverse CDF — the 100M-key
    cold-tier benchmark would otherwise pay an 800 MB float64 cumsum
    for ranks whose individual probabilities are < 1e-9.
    """

    _STRIDE = 2654435761  # Knuth multiplicative-hash constant (odd)
    _EXACT_MAX = 1 << 24

    def __init__(self, n_keys: int, skew: float = 1.1):
        if n_keys < 1:
            raise ValueError(f"n_keys must be >= 1, got {n_keys}")
        if skew < 0:
            raise ValueError(f"skew must be >= 0, got {skew}")
        self.n_keys = int(n_keys)
        self.skew = float(skew)
        head = min(self.n_keys, self._EXACT_MAX)
        self._head = head
        w = 1.0 / np.power(np.arange(1, head + 1, dtype=np.float64),
                           skew)
        cdf = np.cumsum(w)
        if self.n_keys > head:
            # tail mass via the continuous integral of x^-skew over
            # (head+1/2, n_keys+1/2] — the midpoint-corrected analogue
            # of the discrete sum
            a, b = head + 0.5, self.n_keys + 0.5
            if abs(skew - 1.0) < 1e-12:
                tail = np.log(b) - np.log(a)
            else:
                e = 1.0 - skew
                tail = (b ** e - a ** e) / e
            total = cdf[-1] + tail
            self._head_frac = cdf[-1] / total
            cdf = cdf / total
        else:
            self._head_frac = 1.0
            cdf = cdf / cdf[-1]
        self._cdf = cdf

    def _tail_ranks(self, u: np.ndarray) -> np.ndarray:
        """Continuous inverse CDF over the tail ranks: ``u`` uniform in
        [0, 1) → 0-based ranks in [head, n_keys)."""
        a, b = self._head + 0.5, self.n_keys + 0.5
        if abs(self.skew - 1.0) < 1e-12:
            x = a * np.power(b / a, u)
        else:
            e = 1.0 - self.skew
            x = np.power(a ** e + u * (b ** e - a ** e), 1.0 / e)
        return np.clip(x.astype(np.int64), self._head, self.n_keys - 1)

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        """Draw ``n`` keys (int64 [n]) in ``[0, n_keys)``."""
        u = rng.random(n)
        ranks = np.searchsorted(self._cdf, u, side="left")
        if self._head_frac < 1.0:
            in_tail = u >= self._head_frac
            if in_tail.any():
                v = (u[in_tail] - self._head_frac) \
                    / (1.0 - self._head_frac)
                ranks[in_tail] = self._tail_ranks(v)
        return (ranks.astype(np.int64) * self._STRIDE) % self.n_keys


def zipf_stream_cols(
    rng: np.random.Generator,
    n: int,
    customers: ZipfKeySampler,
    n_terminals: int,
    day: int,
    tx_id_start: int = 0,
) -> dict:
    """One micro-batch of engine-ready columns from a Zipf-skewed key
    universe (the ``bench.py detail.state_scale`` load shape): customer
    keys from ``customers``, terminals Zipf-skewed over ``n_terminals``
    with the same exponent, timestamps uniform inside ``day``."""
    cust = customers.sample(rng, n)
    term = (cust * 1_000_003 + rng.integers(0, max(n_terminals // 16, 1),
                                            n)) % n_terminals
    us = ((day * SECONDS_PER_DAY
           + rng.integers(0, SECONDS_PER_DAY, n)).astype(np.int64)
          * 1_000_000)
    return {
        "tx_id": np.arange(tx_id_start, tx_id_start + n, dtype=np.int64),
        "tx_datetime_us": us,
        "customer_id": cust,
        "terminal_id": term.astype(np.int64),
        "tx_amount_cents": rng.integers(100, 50000, n).astype(np.int64),
        "kafka_ts_ms": us // 1000,
    }


def generate_dataset(cfg: DataConfig = DataConfig()):
    """Full pipeline: profiles → association → transactions → frauds.

    Returns ``(customers, terminals, transactions)`` — the same triple as the
    reference's ``generate_dataset`` (· data_generator.ipynb).
    """
    customers = generate_customer_profiles(cfg.n_customers, cfg.seed)
    terminals = generate_terminal_profiles(cfg.n_terminals, cfg.seed)
    associate_terminals(customers, terminals, cfg.radius)
    txs = generate_transactions(customers, cfg.n_days, cfg.seed)
    txs = add_frauds(customers, terminals, txs, cfg)
    return customers, terminals, txs
