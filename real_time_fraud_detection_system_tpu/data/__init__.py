from real_time_fraud_detection_system_tpu.data.generator import (  # noqa: F401
    CustomerProfiles,
    TerminalProfiles,
    Transactions,
    add_frauds,
    generate_customer_profiles,
    generate_dataset,
    generate_terminal_profiles,
    generate_transactions,
)
