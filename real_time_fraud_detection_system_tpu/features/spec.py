"""The 15-feature model input vector — single source of truth.

Order and names match the reference's serving feature list
(``pyspark/scripts/fraud_detection.py:126-132``); window/flag semantics
follow the canonical definitions in :mod:`..config` (the offline-training
definitions — the reference's online SQL disagreed with its own training
pipeline; see ``config.py`` docstring).

Tier provenance (``key_mode="exact"``, README § Feature-state playbook):
the window columns keep this spec under the tiered store, but their
SOURCE varies per row — a key holding a hot-tier slot reads its exact
private windows, a key that missed admission reads count-min sketch
estimates (counts/amounts overestimate-only; terminal risk a ratio of
two overestimates). ``rtfds_feature_tier_rows_total{tier=…}`` records
the serving mix; flag/amount columns are tier-independent.
"""

from __future__ import annotations

FEATURE_NAMES = (
    "TX_AMOUNT",
    "TX_DURING_WEEKEND",
    "TX_DURING_NIGHT",
    "CUSTOMER_ID_NB_TX_1DAY_WINDOW",
    "CUSTOMER_ID_AVG_AMOUNT_1DAY_WINDOW",
    "CUSTOMER_ID_NB_TX_7DAY_WINDOW",
    "CUSTOMER_ID_AVG_AMOUNT_7DAY_WINDOW",
    "CUSTOMER_ID_NB_TX_30DAY_WINDOW",
    "CUSTOMER_ID_AVG_AMOUNT_30DAY_WINDOW",
    "TERMINAL_ID_NB_TX_1DAY_WINDOW",
    "TERMINAL_ID_RISK_1DAY_WINDOW",
    "TERMINAL_ID_NB_TX_7DAY_WINDOW",
    "TERMINAL_ID_RISK_7DAY_WINDOW",
    "TERMINAL_ID_NB_TX_30DAY_WINDOW",
    "TERMINAL_ID_RISK_30DAY_WINDOW",
)

N_FEATURES = len(FEATURE_NAMES)
