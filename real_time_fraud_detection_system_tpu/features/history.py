"""HBM-resident per-customer event histories — long-context serving state.

The sequence family (``models/sequence.py``, the live successor of the
reference's dormant seq2seq fraud model, ``shared_functions.py:
1312-1707``) scores a transaction from its card's event history. Offline
that history comes from ``build_sequences`` over a full table; ONLINE it
must live on-device and update per micro-batch, exactly like the window
state. (The tiered ``key_mode="exact"`` store applies to the WINDOWS
plane only — histories keep their direct/hash slotting, and the engine
refuses the combination rather than serve a half-tiered state; growing
this ring a directory + sketch-summary tier is the natural follow-up
once the windows-plane tiering is sharded.) This module is that state:

- a ring buffer of the last K event-feature vectors per customer slot
  (``events [C+1, K, 8]``), with each cell's absolute event index
  (``pos``) so partially-overwritten histories are detected, not
  silently mixed;
- one fused, fully-vectorized ``update_and_score``: sort the batch into
  per-customer time order, scatter the new events, gather every row's
  own causal history (events strictly up to and including itself — later
  same-batch events are excluded by position), and score the row at its
  own sequence position with the causal transformer.

Event features mirror :func:`..models.sequence.event_features` channel
for channel (amount, Δt, time-of-day/weekday phases, presence), so a
transformer trained offline on ``build_sequences`` serves unchanged.

Row ``C`` of every array is a write sink: padding rows route their
scatters there, keeping scatter indices unique without host-side
filtering.

Key→slot follows the window state's contract (``features/online._slot``):
``direct`` mode is collision-free while ids < capacity; past capacity
(or in ``hash`` mode) colliding customers MERGE into one interleaved
history — same degradation mode as the window tables, size capacity
accordingly. Exactly-once across restarts also mirrors the window
state: the ring buffers live in the checkpointed engine state, so a
crash replay restores the snapshot and re-applies rows once.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.config import FeatureConfig
from real_time_fraud_detection_system_tpu.core.batch import TxBatch
from real_time_fraud_detection_system_tpu.features.online import _slot
from real_time_fraud_detection_system_tpu.models.sequence import (
    N_EVENT_FEATURES,
    transformer_last_logit,
)


def _attn_fn_for(cfg: FeatureConfig, k: int):
    """Serving attention policy (see FeatureConfig.seq_attn).

    None → transformer_logits' naive causal attention ([B, H, K, K]
    scores — fine for short rings, 137 GB at K=512/B=64k); blockwise →
    the flash recurrence from parallel/ring_attention.py, whose score
    memory is [B, H, K, block] (linear in K at fixed block), exact same
    math (online softmax), so long histories serve on one chip."""
    mode = cfg.seq_attn
    if mode == "naive" or (mode == "auto" and k <= cfg.seq_attn_block):
        return None
    from real_time_fraud_detection_system_tpu.parallel.ring_attention import (
        blockwise_attention,
    )

    block = max(16, min(cfg.seq_attn_block, k))
    return lambda q, kk, v: blockwise_attention(
        q, kk, v, block_size=block, causal=True)


class HistoryState(NamedTuple):
    """Per-customer event ring buffers (+1 sink row for padded writes)."""

    events: jnp.ndarray  # f32 [C+1, K, N_EVENT_FEATURES]
    pos: jnp.ndarray  # int32 [C+1, K] — absolute event index in cell, -1 empty
    count: jnp.ndarray  # int32 [C+1] — events written per slot
    last_t: jnp.ndarray  # int32 [C+1] — epoch-seconds of newest event

    @property
    def capacity(self) -> int:
        return int(self.events.shape[0]) - 1

    @property
    def history_len(self) -> int:
        return int(self.events.shape[1])


def init_history_state(cfg: FeatureConfig) -> HistoryState:
    c, k = cfg.customer_capacity, cfg.history_len
    return HistoryState(
        events=jnp.zeros((c + 1, k, N_EVENT_FEATURES), jnp.float32),
        pos=jnp.full((c + 1, k), -1, jnp.int32),
        count=jnp.zeros(c + 1, jnp.int32),
        last_t=jnp.zeros(c + 1, jnp.int32),
    )


def _event_features_dev(
    amount: jnp.ndarray,  # f32 [B] dollars
    day: jnp.ndarray,  # int32 [B]
    tod_s: jnp.ndarray,  # int32 [B]
    dt_s: jnp.ndarray,  # f32 [B] seconds since the previous event (0 first)
) -> jnp.ndarray:
    """[B, 8] — must match models.sequence.event_features bit-for-bit in
    semantics (that fn computes dt via diff with first=0; here dt is
    supplied because the previous event may live in state)."""
    tod = tod_s.astype(jnp.float32) / 86400.0
    weekday = ((day + 3) % 7).astype(jnp.float32) / 7.0
    two_pi = 2.0 * np.pi
    return jnp.stack(
        [
            jnp.log1p(jnp.maximum(amount, 0.0)),
            amount / 100.0,
            jnp.log1p(jnp.maximum(dt_s, 0.0)) / 10.0,
            jnp.sin(two_pi * tod),
            jnp.cos(two_pi * tod),
            jnp.sin(two_pi * weekday),
            jnp.cos(two_pi * weekday),
            jnp.ones_like(tod),
        ],
        axis=1,
    )


def update_and_score(
    state: HistoryState,
    params,
    batch: TxBatch,
    cfg: FeatureConfig,
    slot_fn=None,
    order_key: "jnp.ndarray | None" = None,
) -> Tuple[HistoryState, jnp.ndarray]:
    """One fused history-update + causal-score step (jit-safe).

    Returns ``(new_state, probs [B])`` in the BATCH's row order, with
    padded rows scored 0. Each row is scored from events strictly before
    it plus itself — same-batch later events never leak in (their
    absolute positions exceed the row's own).

    ``slot_fn(customer_key) -> slot`` overrides the key→slot mapping
    (the sharded layout addresses a device-local block: owner shard
    already selected, local slot = key // n_dev).

    ``order_key`` [B] int32 breaks same-second timestamp ties (default:
    the row index). The routed sharded path passes each row's ORIGINAL
    chunk position, because the all_to_all regroups rows source-device-
    major — without it, same-second events of one customer could land in
    the ring in a different order than the single-chip engine's.
    """
    c, k = state.capacity, state.history_len
    b = batch.size
    valid = batch.valid
    if slot_fn is None:
        slot = _slot(batch.customer_key, c, cfg.key_mode).astype(jnp.int32)
    else:
        slot = slot_fn(batch.customer_key).astype(jnp.int32)
    slot = jnp.where(valid, slot, c)  # padding → sink row
    t_s = batch.day * 86400 + batch.tod_s  # int32, ok until 2038

    # --- sort into (slot, time, tie) order so same-customer rows form
    # contiguous time-ordered groups
    idx = jnp.arange(b, dtype=jnp.int32)
    tie = idx if order_key is None else order_key.astype(jnp.int32)
    order = jnp.lexsort((tie, t_s, slot))
    s_slot = slot[order]
    s_t = t_s[order]
    s_valid = valid[order]

    first = jnp.concatenate(
        [jnp.ones(1, bool), s_slot[1:] != s_slot[:-1]])
    last = jnp.concatenate([s_slot[1:] != s_slot[:-1], jnp.ones(1, bool)])
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, idx, 0))
    seg_end = jax.lax.associative_scan(
        jnp.minimum, jnp.where(last, idx, b - 1), reverse=True)
    rank = idx - seg_start
    gsize = seg_end - seg_start + 1

    # --- Δt: rank 0 reaches back into state (0 for a brand-new customer)
    prev_in_batch = jnp.concatenate([s_t[:1], s_t[:-1]])
    has_state = state.count[s_slot] > 0
    dt_state = jnp.where(has_state, s_t - state.last_t[s_slot], 0)
    dt = jnp.where(rank == 0, dt_state, s_t - prev_in_batch)
    f = _event_features_dev(
        batch.amount[order],
        batch.day[order],
        batch.tod_s[order],
        dt.astype(jnp.float32),
    )

    # --- scatter the new events at their absolute positions
    p = state.count[s_slot] + rank  # absolute event index [B]
    cell = p % k
    # only the last K of an oversized group materialize (earlier ones
    # would be overwritten anyway); keeps (slot, cell) pairs unique
    write = s_valid & (rank >= gsize - k)
    w_slot = jnp.where(write, s_slot, c)
    events = state.events.at[w_slot, cell].set(f)
    pos = state.pos.at[w_slot, cell].set(p)
    count = state.count.at[w_slot].add(
        jnp.where(s_valid & last, gsize, 0))
    last_t = state.last_t.at[
        jnp.where(s_valid & last, s_slot, c)].set(s_t)
    new_state = HistoryState(
        events=events, pos=pos, count=count, last_t=last_t)

    # --- gather each row's causal history, left-aligned, own event last.
    # Two sources: positions q >= count_old come from THIS batch's
    # feature rows (only the newest K were scattered, and later same-
    # batch events may already occupy ring cells); positions q <
    # count_old come from the PRE-scatter buffer, where every position
    # in (p - K, count_old) is guaranteed still present.
    count_old = state.count[s_slot]  # [B] (pre-update)
    length = jnp.minimum(p + 1, k)  # [B]
    j = jnp.arange(k, dtype=jnp.int32)[None, :]
    q = p[:, None] - (length[:, None] - 1) + j  # [B, K] absolute positions
    in_batch = q >= count_old[:, None]
    bidx = jnp.clip(seg_start[:, None] + (q - count_old[:, None]), 0, b - 1)
    ev_batch = f[bidx]  # [B, K, F]
    cellq = q % k
    ev_old = state.events[s_slot[:, None], cellq]
    pos_old = state.pos[s_slot[:, None], cellq]
    ev = jnp.where(in_batch[..., None], ev_batch, ev_old)
    ok = (q >= 0) & (q <= p[:, None]) & (in_batch | (pos_old == q))
    hist = jnp.where(ok[..., None], ev, 0.0)
    # Training semantics (build_sequences → event_features on the
    # truncated window): the FIRST event of a window always has Δt = 0 —
    # its true predecessor fell outside the window. Stored features keep
    # the true Δt (correct for every other window position); patch the
    # Δt channel of position 0 at gather time.
    hist = hist.at[:, 0, 2].set(0.0)

    # Serving consumes only each row's own-event logit, so the last
    # transformer block + head run single-query (models/sequence.py::
    # transformer_last_logit) — exact vs the full [B, K] form, with the
    # last block's score tensor [B, H, K] instead of [B, H, K, K]
    # (measured ~time-neutral on v5e; the win is serving memory at long K).
    own = transformer_last_logit(
        params, hist, length - 1, attn_fn=_attn_fn_for(cfg, k))
    probs = jnp.where(s_valid, jax.nn.sigmoid(own), 0.0)

    # --- back to the batch's original row order
    return new_state, jnp.zeros(b, jnp.float32).at[order].set(probs)
