"""Offline feature computation.

Two implementations with different purposes:

- :func:`compute_features_replay` — the framework's canonical offline path:
  replay the historical stream chronologically through the SAME jitted online
  kernel (:func:`..features.online.update_and_featurize`). Training therefore
  sees byte-identical feature semantics to serving — eliminating the
  train/serve skew the reference shipped (offline pandas rolling vs online
  static-table join with different flag definitions,
  ``feature_transformation.ipynb · cells 8-25`` vs ``fraud_detection.py:104``).

- :func:`pandas_rolling_features` — a reference-semantics oracle mirroring the
  handbook's trailing wall-clock windows
  (``get_customer_spending_behaviour_features`` /
  ``get_count_risk_rolling_window``, · cells 17,25) for parity tests: the
  day-bucket approximation must track these closely enough to preserve AUC.
"""

from __future__ import annotations

import jax
import numpy as np

from real_time_fraud_detection_system_tpu.config import FeatureConfig
from real_time_fraud_detection_system_tpu.core.batch import make_batch
from real_time_fraud_detection_system_tpu.data.generator import (
    SECONDS_PER_DAY,
    Transactions,
)
from real_time_fraud_detection_system_tpu.features.online import (
    init_feature_state,
    update_and_featurize,
)
from real_time_fraud_detection_system_tpu.features.spec import N_FEATURES


def _epoch_day0(start_date: str) -> int:
    import datetime as _dt

    d = _dt.date.fromisoformat(start_date)
    return (d - _dt.date(1970, 1, 1)).days


def _replay(
    txs: Transactions,
    cfg: FeatureConfig,
    start_date: str,
    chunk: int,
    with_cms: bool,
    collect_features: bool,
):
    """Shared chronological replay loop. Returns (features|None, state)."""
    assert np.all(np.diff(txs.tx_time_seconds) >= 0), "txs must be chronological"
    day0 = _epoch_day0(start_date)
    start_epoch_us = day0 * SECONDS_PER_DAY * 1_000_000

    state = init_feature_state(cfg, with_cms=with_cms)
    step = jax.jit(lambda s, b: update_and_featurize(s, b, cfg))

    n = txs.n
    out = np.zeros((n, N_FEATURES), dtype=np.float32) if collect_features else None
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        part = txs.slice(slice(s, e))
        batch = make_batch(
            customer_id=part.customer_id,
            terminal_id=part.terminal_id,
            tx_datetime_us=start_epoch_us + part.tx_time_seconds * 1_000_000,
            amount_cents=part.amount_cents,
            label=part.tx_fraud.astype(np.int32),
            pad_to=chunk,
        )
        state, feats = step(state, jax.tree.map(jax.numpy.asarray, batch))
        if out is not None:
            out[s:e] = np.asarray(feats)[: e - s]
    return out, state


def compute_features_replay(
    txs: Transactions,
    cfg: FeatureConfig,
    start_date: str = "2025-04-01",
    chunk: int = 8192,
    with_cms: bool = False,
) -> np.ndarray:
    """Replay the transaction history through the online kernel.

    Returns features [N, 15] aligned with ``txs`` rows (chronological order).
    Labels are fed with each transaction — equivalent to production where
    feedback arrives within ``cfg.delay_days`` (risk windows are delay-
    shifted, so earlier label arrival is unobservable to queries).
    """
    out, _ = _replay(txs, cfg, start_date, chunk, with_cms,
                     collect_features=True)
    return out


def warm_start_state(
    txs: Transactions,
    cfg: FeatureConfig,
    start_date: str = "2025-04-01",
    chunk: int = 8192,
    with_cms: bool = False,
):
    """Bootstrap the online feature state from a historical table.

    The reference bootstraps serving by MERGE-loading precomputed
    ``feature_customer``/``feature_terminal`` tables
    (``load_initial_data.py:289-487``). Here the equivalent is a replay of
    the history through the online kernel, returning the resulting
    :class:`FeatureState` for the engine to continue from — the same code
    path as serving (shared with :func:`compute_features_replay`), so the
    warm state is exactly what streaming from day 0 would have produced.
    """
    _, state = _replay(txs, cfg, start_date, chunk, with_cms,
                       collect_features=False)
    return state


def pandas_rolling_features(
    txs: Transactions,
    windows=(1, 7, 30),
    delay_days: int = 7,
    start_date: str = "2025-04-01",
    night_end_hour: int = 6,
    weekend_start_weekday: int = 5,
) -> np.ndarray:
    """Reference-semantics oracle: trailing wall-clock rolling windows.

    Customer windows: count + mean amount over trailing ``w`` days including
    the current row. Terminal windows: count + fraud risk over
    [t-delay-w, t-delay] (undefined risk → 0). Exactly the handbook
    computation, vectorized with groupby-rolling instead of per-group apply.
    """
    import pandas as pd

    df = txs.to_pandas(start_date)
    df = df.sort_values("TX_DATETIME", kind="stable").reset_index(drop=True)
    ts = df["TX_DATETIME"]

    weekday = ts.dt.weekday
    hour = ts.dt.hour
    out = {
        "TX_AMOUNT": df["TX_AMOUNT"].to_numpy(),
        "TX_DURING_WEEKEND": (weekday >= weekend_start_weekday).astype(np.float64).to_numpy(),
        "TX_DURING_NIGHT": (hour <= night_end_hour).astype(np.float64).to_numpy(),
    }

    # Roll over the TX_DATETIME *column* (``on=``) so the frame keeps its
    # unique RangeIndex; groupby-rolling then returns a
    # (key, original_row) MultiIndex and results join back by an explicit
    # index — no assumption about the traversal order of pandas' output.
    n = len(df)
    gc = df.groupby("CUSTOMER_ID")[["TX_DATETIME", "TX_AMOUNT"]]
    for w in windows:
        r = gc.rolling(f"{w}D", on="TX_DATETIME")
        cnt = _realign(r.count()["TX_AMOUNT"], n)
        s = _realign(r.sum()["TX_AMOUNT"], n)
        out[f"CUSTOMER_ID_NB_TX_{w}DAY_WINDOW"] = cnt
        out[f"CUSTOMER_ID_AVG_AMOUNT_{w}DAY_WINDOW"] = s / cnt

    gt = df.groupby("TERMINAL_ID")[["TX_DATETIME", "TX_FRAUD"]]

    def _roll_ct(days: int):
        r = gt.rolling(f"{days}D", on="TX_DATETIME")
        return (_realign(r.count()["TX_FRAUD"], n),
                _realign(r.sum()["TX_FRAUD"], n))

    nb_delay, fr_delay = _roll_ct(delay_days)
    for w in windows:
        nb_dw, fr_dw = _roll_ct(delay_days + w)
        nb_w = nb_dw - nb_delay
        risk = np.where(nb_w > 0,
                        (fr_dw - fr_delay) / np.maximum(nb_w, 1.0), 0.0)
        out[f"TERMINAL_ID_NB_TX_{w}DAY_WINDOW"] = nb_w
        out[f"TERMINAL_ID_RISK_{w}DAY_WINDOW"] = risk

    from real_time_fraud_detection_system_tpu.features.spec import FEATURE_NAMES

    return np.stack([np.asarray(out[name], dtype=np.float64) for name in FEATURE_NAMES], axis=1)


def _realign(series, n: int) -> np.ndarray:
    """Groupby-rolling result → chronological row order, by index join.

    ``series`` carries a (group_key, original_row) MultiIndex; dropping the
    group level leaves the frame's unique RangeIndex, so ``reindex`` is an
    exact join regardless of how pandas ordered the output rows.
    """
    flat = series.reset_index(level=0, drop=True)
    return flat.reindex(np.arange(n)).to_numpy(dtype=np.float64)
