from real_time_fraud_detection_system_tpu.features.spec import (  # noqa: F401
    FEATURE_NAMES,
    N_FEATURES,
)
from real_time_fraud_detection_system_tpu.features.online import (  # noqa: F401
    FeatureState,
    init_feature_state,
    update_and_featurize,
)
from real_time_fraud_detection_system_tpu.features.offline import (  # noqa: F401
    compute_features_replay,
    pandas_rolling_features,
)
