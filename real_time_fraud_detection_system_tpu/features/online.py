"""Online feature computation: update HBM state, emit the 15-feature matrix.

One call per micro-batch does what the reference needed three systems for
(Spark SQL join of precomputed feature tables + weekend/night SQL flags +
pandas UDF, ``fraud_detection.py:100-132``): scatter the batch into the
rolling-window state, then gather the feature vector for every row — all
inside jit, state resident in HBM across batches.

Terminal fraud labels arrive *delayed* (feedback events); risk windows are
delay-shifted (``feature_transformation.ipynb · cell 25``), so current-batch
label updates never contaminate the queried window.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from real_time_fraud_detection_system_tpu.config import FeatureConfig
from real_time_fraud_detection_system_tpu.core.batch import TxBatch
from real_time_fraud_detection_system_tpu.ops.cms import (
    CountMinSketch,
    cms_init,
    cms_update,
)
from real_time_fraud_detection_system_tpu.ops.hashing import slot_of
from real_time_fraud_detection_system_tpu.ops.windows import (
    WindowState,
    init_window_state,
    query_windows,
    update_windows,
)


class FeatureState(NamedTuple):
    """All HBM-resident feature state (a pytree; shard over the mesh)."""

    customer: WindowState
    terminal: WindowState
    cms: Optional[CountMinSketch]


def init_feature_state(
    cfg: FeatureConfig, with_cms: Optional[bool] = None
) -> FeatureState:
    if with_cms is None:
        with_cms = cfg.customer_source == "cms"
    return FeatureState(
        customer=init_window_state(cfg.customer_capacity, cfg.n_day_buckets),
        terminal=init_window_state(cfg.terminal_capacity, cfg.n_day_buckets),
        cms=cms_init(cfg.cms_depth, cfg.cms_width, cfg.n_day_buckets)
        if with_cms
        else None,
    )


def _slot(key: jnp.ndarray, capacity: int, mode: str) -> jnp.ndarray:
    """Key → table slot. 'direct' is exact for dense serial ids (< capacity);
    'hash' mixes for sparse key universes."""
    if mode == "direct":
        return (key & jnp.uint32(capacity - 1)).astype(jnp.int32)
    return slot_of(key, capacity)


def _flags(batch: TxBatch, cfg: FeatureConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(is_weekend, is_night) float32 flags from (day, tod_s).

    Unix day 0 (1970-01-01) was a Thursday → weekday(Mon=0) = (day+3) % 7.
    """
    weekday = jnp.remainder(batch.day + 3, 7)
    is_weekend = (weekday >= cfg.weekend_start_weekday).astype(jnp.float32)
    hour = batch.tod_s // 3600
    is_night = (hour <= cfg.night_end_hour).astype(jnp.float32)
    return is_weekend, is_night


def _update_state(
    state: FeatureState, batch: TxBatch, cfg: FeatureConfig
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Shared scatter-update half of both scoring paths.

    Returns (new_state, cust_slot, term_slot). Labeled rows
    (``batch.label >= 0``) also scatter fraud counts into the terminal state
    (the feedback path); unlabeled rows contribute 0.
    """
    cust_slot = _slot(batch.customer_key, cfg.customer_capacity, cfg.key_mode)
    term_slot = _slot(batch.terminal_key, cfg.terminal_capacity, cfg.key_mode)
    fraud = jnp.maximum(batch.label, 0).astype(jnp.float32)
    if cfg.customer_source == "cms":
        customer = state.customer  # unused in cms mode: skip the scatter
    else:
        # track_fraud=False: no feature reads customer fraud sums (spec is
        # count+avg for customers) — one fewer 1M-update scatter (~7 ms).
        customer = update_windows(
            state.customer, cust_slot, batch.day, batch.amount, fraud,
            batch.valid, track_fraud=False,
        )
    # track_amount=False symmetrically: terminal features are count+risk.
    terminal = update_windows(
        state.terminal, term_slot, batch.day, batch.amount, fraud,
        batch.valid, track_amount=False,
    )
    cms = state.cms
    if cms is not None:
        cms = cms_update(cms, batch.customer_key, batch.amount, batch.day, batch.valid)
    return FeatureState(customer=customer, terminal=terminal, cms=cms), cust_slot, term_slot


def update_and_featurize(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
) -> Tuple[FeatureState, jnp.ndarray]:
    """Returns (new_state, features [B, 15]).

    Update-then-query: a row's windows include the current transaction and
    its batch-mates of the same key/day — matching the offline pandas
    ``rolling(...).count()`` which includes the current row
    (``feature_transformation.ipynb · cell 17``), at micro-batch granularity.
    """
    windows = tuple(cfg.windows)
    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    customer, terminal = state.customer, state.terminal

    if cfg.customer_source == "cms":
        if state.cms is None:
            raise ValueError(
                "customer_source='cms' but the feature state has no sketch "
                "(init_feature_state must be built from the same config)"
            )
        from real_time_fraud_detection_system_tpu.ops.cms import cms_query

        c_count, c_amount = cms_query(
            state.cms, batch.customer_key, batch.day, windows
        )
    else:
        c_count, c_amount, _ = query_windows(
            customer, cust_slot, batch.day, windows
        )
    t_count, _, t_fraud = query_windows(
        terminal, term_slot, batch.day, windows, delay=cfg.delay_days
    )
    c_avg = jnp.where(c_count > 0, c_amount / jnp.maximum(c_count, 1.0), 0.0)
    t_risk = jnp.where(t_count > 0, t_fraud / jnp.maximum(t_count, 1.0), 0.0)

    is_weekend, is_night = _flags(batch, cfg)

    # Feature order must match features/spec.py::FEATURE_NAMES.
    cols = [batch.amount, is_weekend, is_night]
    for i in range(len(windows)):
        cols.append(c_count[:, i])
        cols.append(c_avg[:, i])
    for i in range(len(windows)):
        cols.append(t_count[:, i])
        cols.append(t_risk[:, i])
    features = jnp.stack(cols, axis=1)

    return state, features


def update_and_score_pallas(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
    scaler_mean: jnp.ndarray,
    scaler_scale: jnp.ndarray,
    w: jnp.ndarray,
    b: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Scatter-update state, then run the fused Pallas featurize+score
    kernel (``ops/pallas_kernels.py``) on the gathered state rows.

    Returns (new_state, probs [B], features [B, 15]) — the linear-model
    equivalent of :func:`update_and_featurize` + scale + logreg in ONE
    device kernel after the updates.
    """
    from real_time_fraud_detection_system_tpu.ops.pallas_kernels import (
        fused_featurize_score,
    )
    from real_time_fraud_detection_system_tpu.ops.windows import (
        gather_state_rows,
    )

    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    c_bd, c_cnt, c_amt, _ = gather_state_rows(state.customer, cust_slot)
    t_bd, t_cnt, _, t_frd = gather_state_rows(state.terminal, term_slot)
    probs, feats = fused_featurize_score(
        (c_bd, c_cnt, c_amt),
        (t_bd, t_cnt, t_frd),
        batch.day,
        batch.tod_s,
        batch.amount,
        batch.valid,
        scaler_mean, scaler_scale, w, b,
        windows=tuple(cfg.windows),
        delay=cfg.delay_days,
        weekend_start=cfg.weekend_start_weekday,
        night_end=cfg.night_end_hour,
        interpret=interpret,
    )
    return state, probs, feats


def update_and_score_pallas_forest(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
    scaler_mean: jnp.ndarray,
    scaler_scale: jnp.ndarray,
    pf,  # ops.pallas_forest.PallasForest (tables in the serving z_mode)
    interpret: Optional[bool] = None,
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Scatter-update state, then run the fused forest featurize→score
    kernel (``ops/pallas_forest.py::fused_forest_leaf_sum``) on the
    gathered state rows.

    Returns (new_state, leaf_sum [B], features [B, 15]) — the
    tree-ensemble equivalent of :func:`update_and_featurize` + scale +
    ``gemm_leaf_sum`` with the feature block VMEM-resident end-to-end
    (the scatter/gather boundary XLA cannot fuse through stays in XLA,
    whose TPU gather emitter wins). The caller divides by ``pf.n_trees``
    (bagging) or adds the base logit (boosting) and masks invalid rows.
    """
    from real_time_fraud_detection_system_tpu.ops.pallas_forest import (
        fused_forest_leaf_sum,
    )
    from real_time_fraud_detection_system_tpu.ops.windows import (
        gather_state_rows,
    )

    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    c_bd, c_cnt, c_amt, _ = gather_state_rows(state.customer, cust_slot)
    t_bd, t_cnt, _, t_frd = gather_state_rows(state.terminal, term_slot)
    leaf_sum, feats = fused_forest_leaf_sum(
        pf,
        (c_bd, c_cnt, c_amt),
        (t_bd, t_cnt, t_frd),
        batch.day,
        batch.tod_s,
        batch.amount,
        scaler_mean, scaler_scale,
        windows=tuple(cfg.windows),
        delay=cfg.delay_days,
        weekend_start=cfg.weekend_start_weekday,
        night_end=cfg.night_end_hour,
        interpret=interpret,
    )
    return state, leaf_sum, feats


def apply_feedback(
    state: FeatureState,
    terminal_key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B] — the day of the original transaction
    label: jnp.ndarray,  # int32 [B] 0/1
    valid: jnp.ndarray,  # bool [B]
    cfg: FeatureConfig,
) -> FeatureState:
    """Late fraud-label feedback: scatter fraud counts into past day buckets.

    The ingest path calls this for the labeled-feedback topic (BASELINE.json
    config 4). Counts are NOT incremented (the transaction was already
    counted when it streamed through); only the fraud sums change, which the
    delay-shifted risk windows will pick up.
    """
    term_slot = _slot(terminal_key, cfg.terminal_capacity, cfg.key_mode)
    return apply_feedback_at_slot(state, term_slot, day, label, valid)


def apply_feedback_at_slot(
    state: FeatureState,
    term_slot: jnp.ndarray,  # int32 [B] — row into the terminal table
    day: jnp.ndarray,
    label: jnp.ndarray,
    valid: jnp.ndarray,
) -> FeatureState:
    """Slot-addressed core of :func:`apply_feedback`.

    Separated so layouts with a different key→slot mapping (the sharded
    engine's owner-partitioned terminal table, ``parallel/step.py``) can
    land labels without re-deriving the single-chip mapping."""
    nb = state.terminal.n_buckets
    bucket = jnp.remainder(day, nb)
    flat = term_slot * nb + bucket
    # Only land the label if the bucket still holds that day (ring not wrapped).
    live = valid & (state.terminal.bucket_day.reshape(-1)[flat] == day)
    frd = state.terminal.fraud.reshape(-1).at[flat].add(
        label.astype(jnp.float32) * live.astype(jnp.float32)
    )
    terminal = state.terminal._replace(
        fraud=frd.reshape(state.terminal.fraud.shape)
    )
    return state._replace(terminal=terminal)
