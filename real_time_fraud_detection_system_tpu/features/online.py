"""Online feature computation: update HBM state, emit the 15-feature matrix.

One call per micro-batch does what the reference needed three systems for
(Spark SQL join of precomputed feature tables + weekend/night SQL flags +
pandas UDF, ``fraud_detection.py:100-132``): scatter the batch into the
rolling-window state, then gather the feature vector for every row — all
inside jit, state resident in HBM across batches.

Terminal fraud labels arrive *delayed* (feedback events); risk windows are
delay-shifted (``feature_transformation.ipynb · cell 25``), so current-batch
label updates never contaminate the queried window.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from real_time_fraud_detection_system_tpu.config import FeatureConfig
from real_time_fraud_detection_system_tpu.core.batch import TxBatch
from real_time_fraud_detection_system_tpu.ops.cms import (
    CountMinSketch,
    cms_add_fraud,
    cms_init,
    cms_query_fraud,
    cms_update,
)
from real_time_fraud_detection_system_tpu.ops.hashing import slot_of
from real_time_fraud_detection_system_tpu.ops.keydir import (
    EMPTY_KEY,
    KeyDirectory,
    admit_slots,
    init_keydir,
    lookup_slots,
    reclaim_entries,
)
from real_time_fraud_detection_system_tpu.ops.windows import (
    WindowState,
    init_window_state,
    query_windows,
    update_windows,
)


class FeatureState(NamedTuple):
    """All HBM-resident feature state (a pytree; shard over the mesh).

    The three trailing fields exist only under ``key_mode="exact"`` (the
    tiered feature store): exact key→slot directories for both hot-tier
    tables and a fraud-tracking terminal sketch for graceful overflow.
    ``None`` defaults keep the pytree leaf structure — and therefore
    every existing checkpoint — identical for direct/hash configs."""

    customer: WindowState
    terminal: WindowState
    cms: Optional[CountMinSketch]
    customer_dir: Optional[KeyDirectory] = None
    terminal_dir: Optional[KeyDirectory] = None
    terminal_cms: Optional[CountMinSketch] = None


def init_feature_state(
    cfg: FeatureConfig, with_cms: Optional[bool] = None,
    n_shards: int = 1,
) -> FeatureState:
    """``n_shards > 1`` builds the SHARDED exact layout: the window
    tables stay flat ``[capacity, NB]`` (placed ``P(axis, None)``, so
    shard s owns rows ``[s*cap/n, (s+1)*cap/n)``), but each shard gets
    its OWN key directory over its local slot range — stacked
    ``[n_shards, ...]`` leaves (:func:`~..ops.keydir.
    init_stacked_keydir`). Sketches keep the single-chip layout here;
    :func:`~..parallel.mesh.shard_feature_state` expands them
    per-device at placement time. Non-exact key modes ignore
    ``n_shards`` (their layouts are width-independent)."""
    exact = cfg.key_mode == "exact"
    if with_cms is None:
        # exact mode always carries the customer sketch: it is the
        # overflow tier for rows that miss hot-tier admission
        with_cms = cfg.customer_source == "cms" or exact
    customer_dir = terminal_dir = terminal_cms = None
    if exact:
        # Directory at 2x the slot capacity: load factor <= 0.5 keeps
        # fixed-depth probing effectively lossless until the free-slot
        # list itself runs dry (THE admission bound).
        def _dir(cap: int):
            if n_shards > 1:
                if cap % n_shards:
                    raise ValueError(
                        f"capacity {cap} must divide by n_shards "
                        f"{n_shards}")
                from real_time_fraud_detection_system_tpu.ops.keydir \
                    import init_stacked_keydir

                local = cap // n_shards
                return init_stacked_keydir(2 * local, local, n_shards)
            return init_keydir(2 * cap, cap)

        if cfg.customer_source != "cms":
            customer_dir = _dir(cfg.customer_capacity)
        terminal_dir = _dir(cfg.terminal_capacity)
        terminal_cms = cms_init(cfg.cms_depth, cfg.cms_width,
                                cfg.n_day_buckets, track_fraud=True)
    return FeatureState(
        customer=init_window_state(cfg.customer_capacity, cfg.n_day_buckets),
        terminal=init_window_state(cfg.terminal_capacity, cfg.n_day_buckets),
        cms=cms_init(cfg.cms_depth, cfg.cms_width, cfg.n_day_buckets)
        if with_cms
        else None,
        customer_dir=customer_dir,
        terminal_dir=terminal_dir,
        terminal_cms=terminal_cms,
    )


def _slot(key: jnp.ndarray, capacity: int, mode: str) -> jnp.ndarray:
    """Key → table slot. 'direct' is exact for dense serial ids (< capacity);
    'hash' mixes for sparse key universes. 'exact' never comes through
    here — it routes through the key directory (admit_slots)."""
    if mode == "exact":
        raise ValueError(
            "key_mode='exact' routes through the key directory "
            "(ops/keydir.admit_slots), not the static slot map")
    if mode == "direct":
        return (key & jnp.uint32(capacity - 1)).astype(jnp.int32)
    return slot_of(key, capacity)


def state_bytes(cfg: FeatureConfig, n_shards: int = 1) -> dict:
    """Static per-tier HBM accounting for the feature state a config
    would build (init_feature_state shapes × dtype bytes; no device
    access, no allocation). Keys: ``dense`` (window tables),
    ``directory`` (key directories + free lists), ``cms`` (all
    sketches), ``total``. The ``--state-hbm-budget-mb`` engine-build
    check and bench's ``detail.state_scale`` both read this, so the
    budget the operator sets and the bytes the bench reports cannot
    drift. ``n_shards``: the sharded engine passes its width — window
    tables and directories partition (same total bytes, plus one
    free_top scalar per shard), but each shard carries its OWN sketch
    replica, so the cms tier multiplies."""
    exact = cfg.key_mode == "exact"
    nb = cfg.n_day_buckets
    # WindowState: bucket_day i32 + count/amount/fraud f32 = 16 B/bucket.
    dense = (cfg.customer_capacity + cfg.terminal_capacity) * nb * 16
    directory = 0
    cms = 0
    n_sketches = 0
    if cfg.customer_source == "cms" or exact:
        n_sketches += 1  # customer count+amount sketch
    if exact:
        n_sketches += 1  # terminal sketch...
    sketch_cols = 2
    cms = n_sketches * (nb * 4  # slice_day
                        + sketch_cols * nb * cfg.cms_depth * cfg.cms_width * 4)
    if exact:
        # ...whose fraud column is a third table on the terminal sketch
        cms += nb * cfg.cms_depth * cfg.cms_width * 4
        # KeyDirectory: keys u32 + slots i32 over 2x slots, free i32 +
        # free_top i32 per table (one free_top per shard).
        for cap, present in ((cfg.customer_capacity,
                              cfg.customer_source != "cms"),
                             (cfg.terminal_capacity, True)):
            if present:
                directory += 2 * cap * 8 + cap * 4 + 4 * max(n_shards, 1)
    # per-device sketch replicas over the mesh (disjoint key partitions:
    # each device sketches only its owners' traffic)
    cms *= max(n_shards, 1)
    return {
        "dense": int(dense),
        "directory": int(directory),
        "cms": int(cms),
        "total": int(dense + directory + cms),
    }


def _flags(batch: TxBatch, cfg: FeatureConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(is_weekend, is_night) float32 flags from (day, tod_s).

    Unix day 0 (1970-01-01) was a Thursday → weekday(Mon=0) = (day+3) % 7.
    """
    weekday = jnp.remainder(batch.day + 3, 7)
    is_weekend = (weekday >= cfg.weekend_start_weekday).astype(jnp.float32)
    hour = batch.tod_s // 3600
    is_night = (hour <= cfg.night_end_hour).astype(jnp.float32)
    return is_weekend, is_night


def _update_state_exact(
    state: FeatureState, batch: TxBatch, cfg: FeatureConfig
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray]:
    """Tiered scatter-update half (``key_mode="exact"``).

    Returns (new_state, cust_slot, c_adm, term_slot, t_adm): slots route
    through the exact key directories; rows that miss admission carry
    ``*_adm=False``, stay OUT of the dense scatters, and are served from
    the sketch tier by the caller. The sketches are updated with EVERY
    row (they shadow the full stream), so a key's sketch estimate stays
    a valid overestimate whether or not it currently holds a hot slot.
    """
    fraud = jnp.maximum(batch.label, 0).astype(jnp.float32)
    probes = cfg.keydir_probes
    if cfg.customer_source == "cms":
        customer, customer_dir = state.customer, None
        cust_slot = jnp.zeros_like(batch.day)
        c_adm = jnp.zeros_like(batch.valid)
    else:
        customer_dir, cust_slot, c_adm = admit_slots(
            state.customer_dir, batch.customer_key, batch.valid,
            n_probes=probes)
        customer = update_windows(
            state.customer, cust_slot, batch.day, batch.amount, fraud,
            batch.valid & c_adm, track_fraud=False,
        )
    terminal_dir, term_slot, t_adm = admit_slots(
        state.terminal_dir, batch.terminal_key, batch.valid,
        n_probes=probes)
    terminal = update_windows(
        state.terminal, term_slot, batch.day, batch.amount, fraud,
        batch.valid & t_adm, track_amount=False,
    )
    cms = cms_update(state.cms, batch.customer_key, batch.amount,
                     batch.day, batch.valid)
    terminal_cms = cms_update(state.terminal_cms, batch.terminal_key,
                              batch.amount, batch.day, batch.valid,
                              fraud=fraud)
    new_state = FeatureState(
        customer=customer, terminal=terminal, cms=cms,
        customer_dir=customer_dir, terminal_dir=terminal_dir,
        terminal_cms=terminal_cms,
    )
    return new_state, cust_slot, c_adm, term_slot, t_adm


def _update_state(
    state: FeatureState, batch: TxBatch, cfg: FeatureConfig
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Shared scatter-update half of both scoring paths.

    Returns (new_state, cust_slot, term_slot). Labeled rows
    (``batch.label >= 0``) also scatter fraud counts into the terminal state
    (the feedback path); unlabeled rows contribute 0.
    """
    cust_slot = _slot(batch.customer_key, cfg.customer_capacity, cfg.key_mode)
    term_slot = _slot(batch.terminal_key, cfg.terminal_capacity, cfg.key_mode)
    fraud = jnp.maximum(batch.label, 0).astype(jnp.float32)
    if cfg.customer_source == "cms":
        customer = state.customer  # unused in cms mode: skip the scatter
    else:
        # track_fraud=False: no feature reads customer fraud sums (spec is
        # count+avg for customers) — one fewer 1M-update scatter (~7 ms).
        customer = update_windows(
            state.customer, cust_slot, batch.day, batch.amount, fraud,
            batch.valid, track_fraud=False,
        )
    # track_amount=False symmetrically: terminal features are count+risk.
    terminal = update_windows(
        state.terminal, term_slot, batch.day, batch.amount, fraud,
        batch.valid, track_amount=False,
    )
    cms = state.cms
    if cms is not None:
        cms = cms_update(cms, batch.customer_key, batch.amount, batch.day, batch.valid)
    return FeatureState(customer=customer, terminal=terminal, cms=cms), cust_slot, term_slot


def update_and_featurize(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
) -> Tuple[FeatureState, jnp.ndarray]:
    """Returns (new_state, features [B, 15]).

    Update-then-query: a row's windows include the current transaction and
    its batch-mates of the same key/day — matching the offline pandas
    ``rolling(...).count()`` which includes the current row
    (``feature_transformation.ipynb · cell 17``), at micro-batch granularity.
    """
    windows = tuple(cfg.windows)
    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    customer, terminal = state.customer, state.terminal

    if cfg.customer_source == "cms":
        if state.cms is None:
            raise ValueError(
                "customer_source='cms' but the feature state has no sketch "
                "(init_feature_state must be built from the same config)"
            )
        from real_time_fraud_detection_system_tpu.ops.cms import cms_query

        c_count, c_amount = cms_query(
            state.cms, batch.customer_key, batch.day, windows
        )
    else:
        c_count, c_amount, _ = query_windows(
            customer, cust_slot, batch.day, windows
        )
    t_count, _, t_fraud = query_windows(
        terminal, term_slot, batch.day, windows, delay=cfg.delay_days
    )
    c_avg = jnp.where(c_count > 0, c_amount / jnp.maximum(c_count, 1.0), 0.0)
    t_risk = jnp.where(t_count > 0, t_fraud / jnp.maximum(t_count, 1.0), 0.0)

    is_weekend, is_night = _flags(batch, cfg)
    features = _assemble(batch, cfg, c_count, c_avg, t_count, t_risk,
                         is_weekend, is_night)
    return state, features


def _assemble(batch, cfg, c_count, c_avg, t_count, t_risk,
              is_weekend, is_night) -> jnp.ndarray:
    # Feature order must match features/spec.py::FEATURE_NAMES.
    windows = tuple(cfg.windows)
    cols = [batch.amount, is_weekend, is_night]
    for i in range(len(windows)):
        cols.append(c_count[:, i])
        cols.append(c_avg[:, i])
    for i in range(len(windows)):
        cols.append(t_count[:, i])
        cols.append(t_risk[:, i])
    return jnp.stack(cols, axis=1)


def update_and_featurize_exact(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Tiered twin of :func:`update_and_featurize` (``key_mode="exact"``).

    Returns (new_state, features [B, 15], tier_rows [2] float32) where
    ``tier_rows = [dense, cms]`` counts (row × keyspace) admissions this
    batch — the device-side source of
    ``rtfds_feature_tier_rows_total{tier=…}``.

    Per row and keyspace: an admitted key reads its private hot-tier
    window row (collision-exact — with the hot tier sized to hold every
    key this path is bit-identical to ``direct`` mode); a row that
    missed admission reads the count-min sketch instead
    (overestimate-only counts/amounts; terminal risk becomes a ratio of
    two overestimates — an estimate, not a bound).
    """
    windows = tuple(cfg.windows)
    state, cust_slot, c_adm, term_slot, t_adm = _update_state_exact(
        state, batch, cfg)

    if cfg.customer_source == "cms":
        from real_time_fraud_detection_system_tpu.ops.cms import cms_query

        c_count, c_amount = cms_query(
            state.cms, batch.customer_key, batch.day, windows)
        c_tier_rows = jnp.zeros((), jnp.float32)  # no dense customer tier
        c_miss_rows = jnp.zeros((), jnp.float32)
    else:
        from real_time_fraud_detection_system_tpu.ops.cms import cms_query

        cc_t, ca_t, _ = query_windows(
            state.customer, cust_slot, batch.day, windows)
        cc_s, ca_s = cms_query(
            state.cms, batch.customer_key, batch.day, windows)
        c_count = jnp.where(c_adm[:, None], cc_t, cc_s)
        c_amount = jnp.where(c_adm[:, None], ca_t, ca_s)
        c_tier_rows = jnp.sum((batch.valid & c_adm).astype(jnp.float32))
        c_miss_rows = jnp.sum((batch.valid & ~c_adm).astype(jnp.float32))

    tc_t, _, tf_t = query_windows(
        state.terminal, term_slot, batch.day, windows, delay=cfg.delay_days)
    tc_s, _, tf_s = cms_query_fraud(
        state.terminal_cms, batch.terminal_key, batch.day, windows,
        delay=cfg.delay_days)
    t_count = jnp.where(t_adm[:, None], tc_t, tc_s)
    t_fraud = jnp.where(t_adm[:, None], tf_t, tf_s)

    c_avg = jnp.where(c_count > 0, c_amount / jnp.maximum(c_count, 1.0), 0.0)
    t_risk = jnp.where(t_count > 0, t_fraud / jnp.maximum(t_count, 1.0), 0.0)
    is_weekend, is_night = _flags(batch, cfg)
    features = _assemble(batch, cfg, c_count, c_avg, t_count, t_risk,
                         is_weekend, is_night)
    dense = c_tier_rows + jnp.sum((batch.valid & t_adm).astype(jnp.float32))
    cms_rows = c_miss_rows + jnp.sum(
        (batch.valid & ~t_adm).astype(jnp.float32))
    return state, features, jnp.stack([dense, cms_rows])


def update_and_score_pallas(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
    scaler_mean: jnp.ndarray,
    scaler_scale: jnp.ndarray,
    w: jnp.ndarray,
    b: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Scatter-update state, then run the fused Pallas featurize+score
    kernel (``ops/pallas_kernels.py``) on the gathered state rows.

    Returns (new_state, probs [B], features [B, 15]) — the linear-model
    equivalent of :func:`update_and_featurize` + scale + logreg in ONE
    device kernel after the updates.
    """
    from real_time_fraud_detection_system_tpu.ops.pallas_kernels import (
        fused_featurize_score,
    )
    from real_time_fraud_detection_system_tpu.ops.windows import (
        gather_state_rows,
    )

    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    c_bd, c_cnt, c_amt, _ = gather_state_rows(state.customer, cust_slot)
    t_bd, t_cnt, _, t_frd = gather_state_rows(state.terminal, term_slot)
    probs, feats = fused_featurize_score(
        (c_bd, c_cnt, c_amt),
        (t_bd, t_cnt, t_frd),
        batch.day,
        batch.tod_s,
        batch.amount,
        batch.valid,
        scaler_mean, scaler_scale, w, b,
        windows=tuple(cfg.windows),
        delay=cfg.delay_days,
        weekend_start=cfg.weekend_start_weekday,
        night_end=cfg.night_end_hour,
        interpret=interpret,
    )
    return state, probs, feats


def update_and_score_pallas_forest(
    state: FeatureState,
    batch: TxBatch,
    cfg: FeatureConfig,
    scaler_mean: jnp.ndarray,
    scaler_scale: jnp.ndarray,
    pf,  # ops.pallas_forest.PallasForest (tables in the serving z_mode)
    interpret: Optional[bool] = None,
) -> Tuple[FeatureState, jnp.ndarray, jnp.ndarray]:
    """Scatter-update state, then run the fused forest featurize→score
    kernel (``ops/pallas_forest.py::fused_forest_leaf_sum``) on the
    gathered state rows.

    Returns (new_state, leaf_sum [B], features [B, 15]) — the
    tree-ensemble equivalent of :func:`update_and_featurize` + scale +
    ``gemm_leaf_sum`` with the feature block VMEM-resident end-to-end
    (the scatter/gather boundary XLA cannot fuse through stays in XLA,
    whose TPU gather emitter wins). The caller divides by ``pf.n_trees``
    (bagging) or adds the base logit (boosting) and masks invalid rows.
    """
    from real_time_fraud_detection_system_tpu.ops.pallas_forest import (
        fused_forest_leaf_sum,
    )
    from real_time_fraud_detection_system_tpu.ops.windows import (
        gather_state_rows,
    )

    state, cust_slot, term_slot = _update_state(state, batch, cfg)
    c_bd, c_cnt, c_amt, _ = gather_state_rows(state.customer, cust_slot)
    t_bd, t_cnt, _, t_frd = gather_state_rows(state.terminal, term_slot)
    leaf_sum, feats = fused_forest_leaf_sum(
        pf,
        (c_bd, c_cnt, c_amt),
        (t_bd, t_cnt, t_frd),
        batch.day,
        batch.tod_s,
        batch.amount,
        scaler_mean, scaler_scale,
        windows=tuple(cfg.windows),
        delay=cfg.delay_days,
        weekend_start=cfg.weekend_start_weekday,
        night_end=cfg.night_end_hour,
        interpret=interpret,
    )
    return state, leaf_sum, feats


def compact_feature_state(
    state: FeatureState,
    now_day: jnp.ndarray,  # int32 [] — newest day the stream has seen
    cfg: FeatureConfig,
    demote_slots: int = 0,
):
    """Recency compaction (``key_mode="exact"``): one full-table vector
    pass reclaiming hot-tier slots that hold only dead history.

    A slot whose NEWEST ``bucket_day`` is older than
    ``now_day - (delay_days + max(windows))`` can never contribute to
    any window query again (the age mask already excludes every bucket
    it holds) — its directory entry is vacated, the slot returns to the
    free list, and its window row is reset so a later grant starts
    clean. Returns (new_state, reclaimed [2] int32 = [customer,
    terminal]). Fixed shapes throughout: this is a ``DispatchSignature``
    variant of the compiled step family, not a recompile.

    ``demote_slots > 0`` adds the cold tier's PRESSURE eviction behind
    the dead reclaim: when a table still sits above
    ``cold_highwater * slot_capacity`` occupied slots, the oldest
    (strictly pre-``now_day``) entries — up to ``demote_slots`` per
    table, a static ``top_k`` width — are DEMOTED: their exact window
    rows are gathered into a fixed-shape payload BEFORE the slots are
    vacated, and the return becomes ``(state, reclaimed[2], payload)``
    where ``payload[table] = (keys u32 [K], bucket_day i32 [K, NB],
    count/amount/fraud f32 [K, NB])`` with unselected lanes masked to
    ``EMPTY_KEY``/empty rows. The host appends the payload to
    ``io/coldstore.py`` — demote, don't discard.
    """
    horizon = jnp.int32(cfg.delay_days + max(cfg.windows))
    cutoff = now_day.astype(jnp.int32) - horizon
    now = now_day.astype(jnp.int32)
    demote = int(demote_slots)
    out = {}
    counts = []
    payload = {}
    for dir_name, ws_name in (("customer_dir", "customer"),
                              ("terminal_dir", "terminal")):
        kd = getattr(state, dir_name)
        ws = getattr(state, ws_name)
        if kd is None:
            out[dir_name], out[ws_name] = kd, ws
            counts.append(jnp.int32(0))
            payload[ws_name] = None
            continue
        newest = jnp.max(ws.bucket_day, axis=1)  # [slot_cap]
        slot_idx = jnp.clip(kd.slots, 0, ws.capacity - 1)
        live = kd.slots >= 0
        newest_e = newest[slot_idx]
        dead_entry = live & (newest_e < cutoff)
        if demote > 0:
            # Pressure eviction EXTENDS the dead mask (payload gathered
            # before any vacate), so the demote variant pays ONE
            # combined reclaim + window-table sweep — not a second
            # full-table pass on top of the dead reclaim.
            kd, ws, n, pay = _demote_oldest(
                kd, ws, dead_entry, newest_e, live, now,
                int(cfg.delay_days + max(cfg.windows)), demote,
                cfg.cold_highwater)
            payload[ws_name] = pay
        else:
            old_slots = kd.slots  # pre-clear ids (reclaim vacates them)
            kd, dead, n = reclaim_entries(kd, dead_entry)
            tgt = jnp.where(dead, old_slots, ws.capacity)
            ws = WindowState(
                bucket_day=ws.bucket_day.at[tgt].set(-1, mode="drop"),
                count=ws.count.at[tgt].set(0.0, mode="drop"),
                amount=ws.amount.at[tgt].set(0.0, mode="drop"),
                fraud=ws.fraud.at[tgt].set(0.0, mode="drop"),
            )
            payload[ws_name] = None
        out[dir_name] = kd
        out[ws_name] = ws
        counts.append(n)
    new_state = state._replace(
        customer=out["customer"], terminal=out["terminal"],
        customer_dir=out["customer_dir"],
        terminal_dir=out["terminal_dir"],
    )
    if demote > 0:
        return new_state, jnp.stack(counts), payload
    return new_state, jnp.stack(counts)


def _demote_oldest(
    kd: KeyDirectory,
    ws: WindowState,
    dead_entry: jnp.ndarray,  # bool [dir_cap] — the dead-history mask
    newest_e: jnp.ndarray,  # int32 [dir_cap] — newest bucket per entry
    live: jnp.ndarray,  # bool [dir_cap]
    now_day: jnp.ndarray,  # int32 []
    horizon: int,  # days — dead-history cutoff distance (static)
    demote_slots: int,
    highwater: float,
):
    """Pressure eviction for one table, FUSED with the dead-history
    reclaim: pick the ``demote_slots`` oldest live directory entries
    (strictly pre-``now_day`` newest bucket; an entry touched today is
    never evicted under the feet of the batch that just wrote it), but
    only as many as POST-dead-reclaim occupancy sits above the
    ``highwater`` target. The evicted rows are gathered into a
    fixed-shape payload first, then the dead mask and the demote
    selection vacate in ONE ``reclaim_entries`` + window sweep (the
    fused pass costs one table rewrite, not two — the selection and the
    resulting state are identical to running the passes sequentially;
    only the internal free-stack push order differs, which no feature
    value depends on).

    Oldest-``n_evict`` selection runs WITHOUT a ``top_k`` sort:
    eligible ages live in ``[1, horizon]`` (anything older is already
    in the dead mask), so an age histogram + suffix sum finds the
    threshold age and a cumsum rank breaks the tie at the threshold by
    lowest index — the exact set ``lax.top_k`` would pick (its ties
    also resolve to the lowest index), at O(n) scatter cost instead of
    an O(n log k) sort over the whole directory. Returns
    ``(kd, ws, n_reclaimed_total, (keys, bd, cnt, amt, frd))``.
    """
    slot_cap = int(ws.capacity)
    dir_cap = int(kd.keys.shape[0])
    k = min(int(demote_slots), dir_cap)
    hzn = max(int(horizon), 1)
    n_dead = jnp.sum((dead_entry & live).astype(jnp.int32))
    occ = (jnp.int32(kd.free.shape[0]) - kd.free_top.astype(jnp.int32)
           - n_dead)
    target = jnp.int32(int(highwater * slot_cap))
    n_evict = jnp.clip(occ - target, 0, k)
    eligible = live & ~dead_entry & (newest_e < now_day)
    # Age histogram over [1, hzn] (bucket 0 holds the ineligible mass
    # and is never selectable; eligible entries have age >= 1 because
    # newest_e < now_day, and age <= hzn because older is dead).
    age = jnp.clip(jnp.where(eligible, now_day - newest_e, 0),
                   0, hzn).astype(jnp.int32)
    hist = jnp.zeros((hzn + 3,), jnp.int32).at[age].add(1)
    incl = jnp.cumsum(hist[::-1])[::-1]  # incl[a] = #entries age >= a
    # Threshold t* = max age with incl >= n_evict (monotone, so a count
    # of satisfied ages IS the argmax); floor 1 covers the
    # n_evict > #eligible case, where every eligible entry is taken.
    thresh = jnp.maximum(
        jnp.sum((incl >= n_evict)[1:hzn + 2].astype(jnp.int32)),
        jnp.int32(1))
    quota = n_evict - incl[thresh + 1]  # lanes left for age == t*
    at_t = age == thresh
    rank_t = jnp.cumsum(at_t.astype(jnp.int32)) - 1
    sel = (age > thresh) | (at_t & (rank_t < quota))
    # Pack selected entry indices into the fixed k payload lanes in
    # index order (payload lane order is semantically irrelevant — the
    # cold store treats rows independently).
    lane = jnp.where(sel, jnp.cumsum(sel.astype(jnp.int32)) - 1, k)
    eidx = jnp.full((k,), dir_cap, jnp.int32).at[lane].set(
        jnp.arange(dir_cap, dtype=jnp.int32), mode="drop")
    lane_live = (jnp.arange(k, dtype=jnp.int32)
                 < jnp.sum(sel.astype(jnp.int32)))
    eidx_c = jnp.clip(eidx, 0, dir_cap - 1)
    # Gather the payload BEFORE vacating: keys + full window rows.
    keys = jnp.where(lane_live, kd.keys[eidx_c], jnp.uint32(EMPTY_KEY))
    slot_g = jnp.clip(kd.slots[eidx_c], 0, slot_cap - 1)
    m = lane_live[:, None]
    bd = jnp.where(m, ws.bucket_day[slot_g], jnp.int32(-1))
    cnt = jnp.where(m, ws.count[slot_g], 0.0)
    amt = jnp.where(m, ws.amount[slot_g], 0.0)
    frd = jnp.where(m, ws.fraud[slot_g], 0.0)
    # One combined vacate: dead history + demoted entries.
    old_slots = kd.slots
    kd, dead, n = reclaim_entries(kd, dead_entry | sel)
    tgt = jnp.where(dead, old_slots, slot_cap)
    ws = WindowState(
        bucket_day=ws.bucket_day.at[tgt].set(-1, mode="drop"),
        count=ws.count.at[tgt].set(0.0, mode="drop"),
        amount=ws.amount.at[tgt].set(0.0, mode="drop"),
        fraud=ws.fraud.at[tgt].set(0.0, mode="drop"),
    )
    return kd, ws, n, (keys, bd, cnt, amt, frd)


def promote_rows(
    state: FeatureState,
    payload: dict,  # {"customer": (keys, bd, cnt, amt, frd)|None, ...}
    cfg: FeatureConfig,
) -> Tuple[FeatureState, jnp.ndarray]:
    """Async promotion landing: merge cold-store rows back into the hot
    tier between device steps.

    Per table: ``admit_slots`` grants (or finds) a slot for every
    non-``EMPTY_KEY`` payload lane, then a per-bucket DAY-DOMINANCE
    merge takes the cold bucket only where its ``bucket_day`` is
    strictly newer than the resident one — never a float add, so
    promotion is IDEMPOTENT (re-promoting a resident key is a no-op)
    and a key that accrued fresh hot rows while its promotion was in
    flight converges to exactly the never-evicted state: eviction
    required every cold bucket to be strictly pre-eviction-day, and
    post-return writes land on days >= the return day, so cold and hot
    buckets never contend for the same day. Returns ``(state,
    stats [2, 2] int32)`` = per-table ``[admitted, dropped]`` (dropped:
    the free list ran dry — the host re-enqueues on the key's next
    touch). The caller guarantees unique keys per dispatch.
    """
    out = {}
    stats = []
    for dir_name, ws_name in (("customer_dir", "customer"),
                              ("terminal_dir", "terminal")):
        kd = getattr(state, dir_name)
        ws = getattr(state, ws_name)
        pay = payload.get(ws_name)
        if kd is None or pay is None:
            out[dir_name], out[ws_name] = kd, ws
            stats.append(jnp.zeros((2,), jnp.int32))
            continue
        keys, bd, cnt, amt, frd = pay
        valid = keys != jnp.uint32(EMPTY_KEY)
        kd, slot, adm = admit_slots(kd, keys, valid,
                                    n_probes=cfg.keydir_probes)
        slot_c = jnp.clip(slot, 0, ws.capacity - 1)
        take = adm[:, None] & (bd > ws.bucket_day[slot_c])
        new_bd = jnp.where(take, bd, ws.bucket_day[slot_c])
        new_cnt = jnp.where(take, cnt, ws.count[slot_c])
        new_amt = jnp.where(take, amt, ws.amount[slot_c])
        new_frd = jnp.where(take, frd, ws.fraud[slot_c])
        tgt = jnp.where(adm, slot, ws.capacity)
        out[dir_name] = kd
        out[ws_name] = WindowState(
            bucket_day=ws.bucket_day.at[tgt].set(new_bd, mode="drop"),
            count=ws.count.at[tgt].set(new_cnt, mode="drop"),
            amount=ws.amount.at[tgt].set(new_amt, mode="drop"),
            fraud=ws.fraud.at[tgt].set(new_frd, mode="drop"),
        )
        adm_n = jnp.sum(adm.astype(jnp.int32))
        drop_n = jnp.sum((valid & ~adm).astype(jnp.int32))
        stats.append(jnp.stack([adm_n, drop_n]))
    return (
        state._replace(
            customer=out["customer"], terminal=out["terminal"],
            customer_dir=out["customer_dir"],
            terminal_dir=out["terminal_dir"],
        ),
        jnp.stack(stats),
    )


def apply_feedback(
    state: FeatureState,
    terminal_key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B] — the day of the original transaction
    label: jnp.ndarray,  # int32 [B] 0/1
    valid: jnp.ndarray,  # bool [B]
    cfg: FeatureConfig,
) -> FeatureState:
    """Late fraud-label feedback: scatter fraud counts into past day buckets.

    The ingest path calls this for the labeled-feedback topic (BASELINE.json
    config 4). Counts are NOT incremented (the transaction was already
    counted when it streamed through); only the fraud sums change, which the
    delay-shifted risk windows will pick up.

    ``key_mode="exact"``: labels route by directory LOOKUP (never an
    insert — feedback must not evict live traffic's slots). Hits land in
    the dense terminal windows exactly as before; misses (the key was
    never admitted, or its slot was compacted away) land in the terminal
    sketch's fraud column so the sketch-tier risk estimate still learns.
    """
    if cfg.key_mode == "exact":
        term_slot, hit = lookup_slots(
            state.terminal_dir, terminal_key, valid,
            n_probes=cfg.keydir_probes)
        state = apply_feedback_at_slot(state, term_slot, day, label,
                                       valid & hit)
        return state._replace(terminal_cms=cms_add_fraud(
            state.terminal_cms, terminal_key, day, label, valid & ~hit))
    term_slot = _slot(terminal_key, cfg.terminal_capacity, cfg.key_mode)
    return apply_feedback_at_slot(state, term_slot, day, label, valid)


def apply_feedback_sharded_exact(
    state: FeatureState,
    terminal_key: jnp.ndarray,  # uint32 [B] (already fold_key'd)
    day: jnp.ndarray,  # int32 [B] — the day of the original transaction
    label: jnp.ndarray,  # int32 [B] 0/1
    valid: jnp.ndarray,  # bool [B]
    cfg: FeatureConfig,
) -> FeatureState:
    """Sharded-exact twin of :func:`apply_feedback`: ownership is
    ``key % n_shards`` (the same modulo the step's owner exchange
    routes by), the slot comes from THAT shard's directory — a LOOKUP,
    never an insert (feedback must not evict live traffic's slots).
    Hits land in the owner's dense window rows (global table row =
    ``owner * cap_local + local_slot``); misses land in the owner's
    sketch replica's fraud column (``cms_add_fraud``'s owner-indexed
    form — ONE bounded-lateness policy with the single-chip path).
    Plain jitted global-array ops — GSPMD inserts the (off-hot-path)
    collectives."""
    from real_time_fraud_detection_system_tpu.ops.keydir import (
        lookup_slots_stacked,
    )

    kd = state.terminal_dir
    n_shards = int(kd.keys.shape[0])
    cap_local = state.terminal.capacity // n_shards
    owner = (terminal_key.astype(jnp.uint32)
             % jnp.uint32(n_shards)).astype(jnp.int32)
    slot, hit = lookup_slots_stacked(kd, owner, terminal_key, valid,
                                     n_probes=cfg.keydir_probes)
    grow = owner * cap_local + slot
    state = apply_feedback_at_slot(state, grow, day, label, valid & hit)
    if state.terminal_cms is None:  # defensive: exact states carry one
        return state
    return state._replace(terminal_cms=cms_add_fraud(
        state.terminal_cms, terminal_key, day, label, valid & ~hit,
        owner=owner))


def apply_feedback_at_slot(
    state: FeatureState,
    term_slot: jnp.ndarray,  # int32 [B] — row into the terminal table
    day: jnp.ndarray,
    label: jnp.ndarray,
    valid: jnp.ndarray,
) -> FeatureState:
    """Slot-addressed core of :func:`apply_feedback`.

    Separated so layouts with a different key→slot mapping (the sharded
    engine's owner-partitioned terminal table, ``parallel/step.py``) can
    land labels without re-deriving the single-chip mapping."""
    nb = state.terminal.n_buckets
    bucket = jnp.remainder(day, nb)
    flat = term_slot * nb + bucket
    # Only land the label if the bucket still holds that day (ring not wrapped).
    live = valid & (state.terminal.bucket_day.reshape(-1)[flat] == day)
    frd = state.terminal.fraud.reshape(-1).at[flat].add(
        label.astype(jnp.float32) * live.astype(jnp.float32)
    )
    terminal = state.terminal._replace(
        fraud=frd.reshape(state.terminal.fraud.shape)
    )
    return state._replace(terminal=terminal)
