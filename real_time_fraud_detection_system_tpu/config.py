"""Single typed configuration for the whole framework.

The reference scatters constants across every job (S3 creds + catalog URIs
duplicated in ``pyspark/scripts/fraud_detection.py:15-23`` and each
``kafka_s3_sink_*.py:7-15``; SparkConf blocks copy-pasted per job). Here one
frozen dataclass tree is the only source of truth, built once and threaded
through every layer.

Canonical feature definitions
-----------------------------
The reference disagrees with itself about two of the 15 model features:

- night: offline training uses ``hour <= 6``
  (``feature_transformation.ipynb · cell 12``) but online serving uses
  ``hour >= 20`` (``fraud_detection.py:104``);
- weekend: offline uses python ``weekday() >= 5`` (Sat/Sun) but online uses
  Spark ``dayofweek() >= 5`` (Thu/Fri/Sat, since Spark's Sunday==1).

Training/serving skew is a bug, not a behavior to reproduce. This framework
uses ONE definition everywhere — the offline one that the model was actually
trained with: ``is_night = hour <= night_end_hour (6)`` and
``is_weekend = weekday >= 5`` with Monday==0. Both are configurable below.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Sequence


@dataclass(frozen=True)
class DataConfig:
    """Synthetic data generator knobs (reference ``data_generator.ipynb · cell 34``)."""

    n_customers: int = 5000
    n_terminals: int = 10000
    n_days: int = 245
    radius: float = 5.0
    start_date: str = "2025-04-01"
    seed: int = 0
    # Fraud scenarios (reference ``data_generator.ipynb · cell 42``).
    scenario1_amount_threshold: float = 220.0
    scenario2_terminals_per_day: int = 2
    scenario2_compromise_days: int = 28
    scenario3_customers_per_day: int = 3
    scenario3_compromise_days: int = 14
    scenario3_amount_multiplier: float = 5.0
    scenario3_fraction: float = 1.0 / 3.0


@dataclass(frozen=True)
class FeatureConfig:
    """Stateful windowed feature computation.

    Windows and delay follow ``feature_transformation.ipynb · cells 17,25``:
    customer {1,7,30}-day count+avg-amount; terminal {1,7,30}-day count+risk
    shifted back by ``delay_days`` (fraud labels arrive late).
    """

    windows: Sequence[int] = (1, 7, 30)
    delay_days: int = 7
    # Day-bucket ring buffers must cover delay + max(window) days of history.
    n_day_buckets: int = 40
    # Dense per-key state capacity (power of 2).
    customer_capacity: int = 8192
    terminal_capacity: int = 16384
    # Slot placement: "direct" (key & (cap-1)) is collision-free for dense
    # serial PKs (the reference's SERIAL ids, postgres/init.sql) as long as
    # capacity >= #keys; "hash" mixes first — use for sparse/adversarial key
    # spaces (collisions then merge keys, CMS bounds the error story);
    # "exact" routes through the on-device key directory (ops/keydir.py):
    # the hot tier is sized to the ACTIVE WORKING SET (capacity = hot-tier
    # slots, decoupled from the key universe), admitted keys are
    # collision-exact, and rows that miss admission are served from the
    # count-min sketch tier (overestimate-only degradation, observable via
    # rtfds_feature_tier_rows_total).
    key_mode: str = "direct"
    # key_mode="exact" knobs: fixed probe depth of the directory's double
    # hashing (the directory is 2x the slot capacity, load factor <= 0.5,
    # so 8 probes make admission misses vanishingly rare until the free
    # list itself runs dry), and the recency-compaction cadence — every
    # N batches a full-table vector pass reclaims slots whose newest
    # bucket_day is older than delay_days + max(windows) (dead history:
    # no query can ever see it). 0 = compaction off.
    keydir_probes: int = 8
    compact_every: int = 0
    # HBM budget for the whole feature state (dense tier + directory +
    # sketches), validated at ENGINE BUILD against the static
    # state_bytes() accounting — a config that cannot fit fails fast
    # instead of OOMing mid-stream. 0 = no budget check.
    state_hbm_budget_mb: float = 0.0
    # Host cold tier for key_mode="exact": compaction DEMOTES pressure-
    # evicted keys' exact window rows to an append+compact keyed store on
    # the host (io/coldstore.py) instead of discarding them; a returning
    # key is detected host-side against the cold index and its rows are
    # PROMOTED back into the hot tier asynchronously between device steps
    # (a ("promote",) dispatch signature — zero mid-stream recompiles).
    # Empty string disables the tier (evictions discard, PR 13 behavior).
    # Accepts a local directory or an s3:// URL (flaky-store retries and
    # CRC verification inherited from the checkpoint backends).
    cold_store: str = ""
    # Bounded promoter request queue (keys awaiting a host cold-store
    # read); a full queue drops the request and the key is re-enqueued
    # on its next touch — backpressure, never unbounded growth.
    cold_promote_queue: int = 64
    # Cold segment flush threshold (MB of buffered demoted rows before a
    # segment blob + manifest is written). Checkpoints always flush.
    cold_segment_mb: float = 4.0
    # Max keys demoted per table per compaction pass (the static top-k
    # width of the eviction scan — one compiled shape).
    cold_demote_slots: int = 1024
    # Hot-tier occupancy target: compaction demotes oldest-first down to
    # ceil(highwater * slot_capacity) occupied slots per table.
    cold_highwater: float = 0.75
    # Count-min sketch for unbounded key cardinality (velocity features).
    cms_depth: int = 4
    cms_width: int = 1 << 15
    # Where customer velocity features come from: "table" = exact dense
    # window state (keys must fit customer_capacity); "cms" = the count-min
    # sketch (BASELINE config 3) — bounded memory for billions of cards,
    # overestimate-only error. Terminal risk always uses the table (the
    # sketch holds no fraud sums).
    customer_source: str = "table"
    # Per-customer event-history ring length for the sequence scorer
    # (features/history.py) — the serving-side max_len of
    # models/sequence.build_sequences.
    history_len: int = 32
    # Attention form for the serving transformer over the history ring:
    # "naive" materializes [B, H, K, K] scores (fastest for short K),
    # "blockwise" runs the flash recurrence ([B, H, K, block] memory —
    # long histories on one chip), "auto" switches to blockwise once
    # history_len exceeds seq_attn_block (naive at K=512/B=64k wants a
    # 137 GB score tensor; blockwise caps it at K/block that).
    seq_attn: str = "auto"
    seq_attn_block: int = 128
    # Canonical flag definitions (see module docstring).
    night_end_hour: int = 6
    weekend_start_weekday: int = 5  # Monday == 0

    def __post_init__(self):
        if self.customer_source not in ("table", "cms"):
            raise ValueError(
                f"customer_source must be 'table' or 'cms', "
                f"got {self.customer_source!r}"
            )
        if self.key_mode not in ("direct", "hash", "exact"):
            raise ValueError(
                f"key_mode must be 'direct', 'hash' or 'exact', "
                f"got {self.key_mode!r}"
            )
        # direct mode masks with (capacity - 1) (features/online._slot) and
        # the hash/exact placements assume pow2 tables — a non-pow2
        # capacity would silently ALIAS keys today, so refuse it loudly.
        for name in ("customer_capacity", "terminal_capacity"):
            cap = getattr(self, name)
            if cap < 1 or cap & (cap - 1):
                raise ValueError(
                    f"{name} must be a power of two (direct mode masks "
                    f"with capacity-1; non-pow2 silently aliases keys), "
                    f"got {cap}")
        if self.keydir_probes < 1:
            raise ValueError(
                f"keydir_probes must be >= 1, got {self.keydir_probes}")
        if self.compact_every < 0:
            raise ValueError(
                f"compact_every must be >= 0 (0 = off), "
                f"got {self.compact_every}")
        if self.state_hbm_budget_mb < 0:
            raise ValueError(
                f"state_hbm_budget_mb must be >= 0 (0 = unchecked), "
                f"got {self.state_hbm_budget_mb}")
        if self.cold_promote_queue < 1:
            raise ValueError(
                f"cold_promote_queue must be >= 1 (the promoter queue is "
                f"bounded), got {self.cold_promote_queue}")
        if self.cold_segment_mb <= 0:
            raise ValueError(
                f"cold_segment_mb must be > 0, got {self.cold_segment_mb}")
        if self.cold_demote_slots < 1:
            raise ValueError(
                f"cold_demote_slots must be >= 1, "
                f"got {self.cold_demote_slots}")
        if not 0 < self.cold_highwater <= 1:
            raise ValueError(
                f"cold_highwater must be in (0, 1], "
                f"got {self.cold_highwater}")
        if self.cold_store:
            if self.key_mode != "exact":
                raise ValueError(
                    "cold_store requires key_mode='exact' (only the "
                    "keyed hot tier has per-key rows to demote), got "
                    f"key_mode={self.key_mode!r}")
            if self.compact_every <= 0:
                raise ValueError(
                    "cold_store requires compact_every > 0 (demotion "
                    "rides the compaction cadence)")
        if self.seq_attn not in ("naive", "blockwise", "auto"):
            raise ValueError(
                f"seq_attn must be 'naive', 'blockwise' or 'auto', "
                f"got {self.seq_attn!r}"
            )


@dataclass(frozen=True)
class ModelConfig:
    """Classifier selection, mirroring the reference's 5-model zoo
    (``model_training.ipynb · cell 50``: LogReg, DT-2, DT, RF, XGBoost)."""

    kind: str = "logreg"  # logreg | mlp | tree | forest | gbt | autoencoder
    n_features: int = 15
    mlp_hidden: Sequence[int] = (64, 32)
    # Unsupervised anomaly scorer (successor to the dormant torch
    # autoencoder, shared_functions.py:1312-1707); encoder widths, the last
    # entry is the bottleneck.
    autoencoder_hidden: Sequence[int] = (32, 8)
    forest_n_trees: int = 100
    forest_max_depth: int = 8
    tree_max_depth: int = 2
    # Sequence (causal transformer) family dims — models/sequence.py.
    seq_d_model: int = 32
    seq_n_heads: int = 2
    seq_n_layers: int = 2
    seq_d_ff: int = 64
    dtype: str = "float32"
    seed: int = 0


@dataclass(frozen=True)
class TrainConfig:
    """Offline training protocol (``model_training.ipynb · cell 8``)."""

    delta_train_days: int = 153
    delta_delay_days: int = 30
    delta_test_days: int = 30
    learning_rate: float = 1e-2
    batch_size: int = 4096
    epochs: int = 5
    weight_decay: float = 0.0
    # Online SGD (BASELINE.json config 4).
    online_learning_rate: float = 1e-3


@dataclass(frozen=True)
class OverloadConfig:
    """Overload-survival ladder (``runtime/overload.py``).

    The reference has no overload story at all: Spark micro-batches just
    fall behind and Kafka lag grows without bound. When enabled, the
    engine runs an explicit hysteresis state machine over the registry
    signals it already emits (windowed batch latency vs
    ``latency_slo_ms``, source lag, prefetch/sink queue fill) and climbs
    a reversible degradation ladder: rung 1 sheds optional work (shadow
    scoring, learner training, flight-recorder sampling), rung 2 forces
    the largest AOT batch bucket + alerts-only emission, rung 3 defers
    whole micro-batches to a durable spill and replays them in order
    once pressure subsides — the stream degrades and recovers, it never
    dies and never silently drops a row (``scored + deferred ==
    polled``)."""

    enabled: bool = False
    # Durable overflow spill for rung-3 deferral (the PR 4 dead-letter
    # machinery, reason=shed, idempotent by tx_id): ``*.jsonl`` = JSONL
    # file, anything else = parquet part directory. "" = memory-only
    # deferral (still ordered and replayed, but a crash loses the
    # spilled copy and relies on checkpoint replay alone).
    spill_path: str = "overload_spill"
    # Hysteresis: climb one rung after ``climb_dwell_batches``
    # consecutive observations at pressure >= ``climb_pressure``;
    # descend one rung after ``descend_dwell_batches`` consecutive
    # observations at pressure <= ``descend_pressure``. The gap between
    # the two thresholds plus the dwell counts is what makes flapping
    # impossible: a single spike can neither climb nor descend.
    climb_pressure: float = 1.0
    descend_pressure: float = 0.6
    climb_dwell_batches: int = 3
    descend_dwell_batches: int = 6
    # Source-lag normalization: lag of this many rows == pressure 1.0
    # (0 disables the lag signal; latency/queue signals still work).
    lag_high_rows: int = 0
    # Windowed p50 batch-latency signal (vs runtime.latency_slo_ms).
    latency_window_batches: int = 8
    # Host-memory bound on rung-3 deferral: at most this many deferred
    # micro-batches are held (spilled + in memory) at once. At the cap
    # the controller replays the queue head through scoring to make
    # room, so the backlog beyond it stays in the source/broker — the
    # one buffer that is allowed to be unbounded, and visibly so via
    # rtfds_source_lag_rows.
    max_deferred_batches: int = 512
    # Flight-recorder sampling while any rung is active (rung 1's
    # "drop the recorder to sampled mode"): record every k-th batch.
    recorder_sample_every: int = 16

    def __post_init__(self):
        if not 0.0 <= self.descend_pressure < self.climb_pressure:
            raise ValueError(
                "overload hysteresis needs 0 <= descend_pressure < "
                f"climb_pressure, got {self.descend_pressure} / "
                f"{self.climb_pressure}")
        if self.climb_dwell_batches < 1 or self.descend_dwell_batches < 1:
            raise ValueError("overload dwell counts must be >= 1")
        if self.max_deferred_batches < 1:
            raise ValueError("overload.max_deferred_batches must be >= 1")
        if self.recorder_sample_every < 1:
            raise ValueError("overload.recorder_sample_every must be >= 1")


@dataclass(frozen=True)
class DistributedConfig:
    """Multi-host process topology (``runtime/distributed.py``).

    The reference scales by adding Spark executors behind one Kafka
    topic; the TPU-native analogue is N OS processes (one per host),
    each owning a contiguous block of the global shard space. Ownership
    is residue-based — process p of P, serving L local devices, owns the
    customer residues ``key % (P·L) ∈ [p·L, (p+1)·L)`` — chosen so the
    sharded step's internal ``key % L`` placement equals the global
    residue minus the block base: the per-process engine runs UNCHANGED
    and the fleet's shard layout matches a single (P·L)-device engine's
    exactly. Ingest is partition-affine (each process polls only its
    owners' traffic), so the host plane never pays a cross-process
    all-to-all; the owner exchange stays on the device fabric."""

    # host:port of process 0's jax.distributed coordination service.
    # "" = uncoordinated fleet: processes still partition the shard
    # space but skip jax.distributed.initialize (no spanning mesh is
    # possible; per-worker restart becomes safe — see the README
    # multi-host playbook's failure-semantics table).
    coordinator: str = ""
    # Total processes in the fleet; 1 = single-process (everything off).
    num_processes: int = 1
    # This process's id in [0, num_processes); -1 = resolve from
    # JAX_PROCESS_ID (the launcher always passes it explicitly).
    process_id: int = -1
    # Refuse polled rows whose customer residue this process does not
    # own (catches mis-wired launchers before state diverges). Applies
    # to residue-sliced sources (replay/synthetic/raw-table); Kafka
    # fleets partition by broker partition, where residue membership is
    # the producer's contract, not checkable per row — the CLI disables
    # the check there.
    strict_affinity: bool = True
    # jax.distributed.initialize barrier timeout.
    init_timeout_s: float = 120.0

    def __post_init__(self):
        if self.num_processes < 1:
            raise ValueError(
                f"distributed.num_processes must be >= 1, "
                f"got {self.num_processes}")
        if self.num_processes > 1 and self.process_id >= self.num_processes:
            raise ValueError(
                f"distributed.process_id {self.process_id} out of range "
                f"for {self.num_processes} process(es)")
        if self.init_timeout_s <= 0:
            raise ValueError(
                f"distributed.init_timeout_s must be > 0, "
                f"got {self.init_timeout_s}")


@dataclass(frozen=True)
class RuntimeConfig:
    """Micro-batch engine (replaces Spark Structured Streaming triggers:
    5 s sinks ``kafka_s3_sink_customers.py:179``, 10 s scorer
    ``fraud_detection.py:208``)."""

    scorer: str = "tpu"  # cpu | tpu
    # Fused Pallas featurize+score kernels (ops/pallas_kernels.py for the
    # linear scorer, ops/pallas_forest.py::fused_forest_leaf_sum for tree
    # ensembles). Interpreted (slow, exact) off-TPU.
    # Stays opt-in by measurement, not neglect: on a real v5e the linear
    # fused kernel and the plain-jnp composition are within ±2% (bench
    # detail `pallas_fused`, 2026-07-30: 2.94M vs 2.91M rows/s,
    # max|Δ| 2.4e-7) — XLA's automatic fusion already captures the win
    # there. The forest fused step attacks the scatter boundary XLA
    # cannot fuse through; its A/B lives in bench detail `device_plane`.
    use_pallas: bool = False
    # MXU arithmetic for the tree-ensemble z contraction
    # (models/forest.py::gemm_leaf_sum — the dominant classify matmul,
    # exact in EVERY mode because its operands are tiny integers):
    # "auto" = int8 on TPU (2× bf16 MXU peak on v5e, measured bit-exact
    # vs f32 — bench detail z_mode/device_plane), f32 elsewhere (the
    # only float mode CPU XLA lowers natively). Forced "int8"/"bf16"/
    # "f32" pin the mode on any backend; decisions are identical by the
    # exactness contract (README § Device plane).
    z_mode: str = "auto"
    trigger_seconds: float = 0.0  # 0 => score as fast as batches arrive
    # Max micro-batches in flight on the device at once (the engine's
    # software pipeline). 2 = classic double-buffering (batch N+1's host
    # prep + H2D overlap batch N's compute); deeper keeps the device fed
    # when per-dispatch overhead (e.g. a remote-tunnel RTT) exceeds the
    # step's compute time. Steps still chain through the feature state,
    # so depth buys dispatch overlap, not device concurrency.
    pipeline_depth: int = 2
    # Coalesce consecutive source polls into one device batch of up to
    # this many rows (0 = off: one poll = one batch). Amortizes per-step
    # dispatch overhead when the source hands out small batches.
    coalesce_rows: int = 0
    # False = alerts-only serving: BatchResult.features is zeros and the
    # [B, 15] feature matrix never leaves the device — the dominant D2H
    # cost per batch when the chip is remote. Only valid with the device
    # scorer and no feature cache (both consume host-side features);
    # sinks that persist feature columns (the analyzed table) should
    # keep the default.
    emit_features: bool = True
    # "bfloat16" halves the feature D2H bytes (the measured full-featured
    # serving bottleneck on constrained links: ~20 MB/s over the dev
    # tunnel; PCIe at very high rates). Lossy (~3 decimal digits on the
    # 15 feature columns; predictions are NOT affected — the classifier
    # consumes the f32 features in-device), so it is opt-in and refused
    # when the host re-consumes features (scorer=cpu, feature cache).
    emit_dtype: str = "float32"  # "float32" | "bfloat16"
    # Selective emission (> 0 enables): probabilities are emitted for
    # EVERY row, but the 15 feature columns are transferred only for rows
    # whose fraud probability clears this threshold — the reference's
    # analyzed_transactions schema lands complete for every flagged row
    # (`fraud_detection.py:136-163`), while clean traffic (~99% at the
    # 0.88% fraud rate) skips the dominant D2H cost. The step compacts
    # flagged rows on-device and packs probs+count+indices+features into
    # ONE flat array, so a batch costs a single transfer (same round-trip
    # count as alerts-only serving). Rows below the threshold carry zero
    # feature columns in BatchResult/sinks. Requires the device scorer
    # and no feature cache (both consume every row's features host-side).
    emit_threshold: float = 0.0
    # On-device compaction capacity as a fraction of the batch rows. If a
    # batch flags more rows than this, the engine falls back to fetching
    # that batch's full feature matrix (kept on device for exactly this) —
    # correctness never depends on the cap, only the D2H savings do.
    emit_cap_fraction: float = 1 / 16
    # Pad/bucket micro-batches to these row counts to keep the jit cache warm.
    batch_buckets: Sequence[int] = (256, 1024, 4096, 16384, 65536)
    max_batch_rows: int = 65536
    # AOT bucket precompilation: at run start, .lower(...).compile() the
    # jitted step for EVERY batch_buckets size (× the engine's donation
    # signature) and serve from the compiled executables — no first-touch
    # bucket size ever pays a mid-stream XLA compile (969 ms measured vs
    # 8 ms steady-state; rtfds_xla_recompiles_total stays 0 by
    # construction). Composes with the persistent compilation cache, so
    # `rtfds warmup` makes later serving restarts warm too.
    precompile: bool = False
    # Adaptive micro-batch controller (runtime/autobatch.py): the
    # coalesce target moves BETWEEN the configured batch_buckets from
    # observed per-batch latency — hold latency_slo_ms when set, else
    # hill-climb for throughput. Overrides coalesce_rows while active.
    autobatch: bool = False
    # p50 micro-batch latency target in ms for the autobatch controller
    # (0 = no SLO: maximize throughput instead).
    latency_slo_ms: float = 0.0
    # Async sink offload (io/sink.py::AsyncSink): sink appends run on a
    # background writer thread behind a bounded FIFO queue; the loop
    # thread's sink_write phase collapses to an enqueue. Checkpoint
    # saves drain the queue first, so offsets keep trailing durable sink
    # output (the exactly-once invariant).
    async_sink: bool = False
    # Ingest-decode worker threads (core/native.py): each polled
    # envelope byte-batch is sharded into contiguous offset slabs decoded
    # concurrently by a thread pool (the ctypes scanner releases the
    # GIL) into disjoint slices of one columnar staging buffer —
    # bit-identical to single-worker decode, scales with cores. 0 = auto
    # (min(8, cores)); 1 = serial.
    decode_workers: int = 0
    # Background source prefetch (runtime/prefetch.py::PrefetchSource):
    # poll + decode run ahead of the serving loop on a producer thread
    # into a bounded queue of this many batches; the loop thread's
    # source_poll phase collapses to a dequeue. Offsets commit only on
    # CONSUMPTION (checkpoint/replay semantics unchanged: a crash
    # replays prefetched-but-unconsumed batches, never skips them), and
    # poison isolation switches the source back to synchronous polling.
    # 0 = off.
    prefetch_batches: int = 0
    # Overlapped result fetch: issue device→host copies asynchronously
    # (copy_to_host_async) the moment a step's handle resolves, so the
    # D2H transfer runs while the loop thread preps/dispatches later
    # batches instead of serializing into result_wait. Free on CPU; the
    # head start is metered as rtfds_fetch_overlap_seconds_total.
    fetch_overlap: bool = True
    # Bounded queue depth (batch results) for the async sink; a full
    # queue backpressures the loop thread
    # (rtfds_sink_backpressure_seconds_total counts the blocked time).
    sink_queue_batches: int = 8
    checkpoint_dir: str = "checkpoints"
    checkpoint_every_batches: int = 50
    # Incremental checkpoints: write a FULL snapshot every K saves and
    # deltas (only the leaves whose bytes changed — feature state churns
    # every batch, params/scaler are static between hot-reloads) in
    # between, chained to their base by checksum. 1 = every save full
    # (the v1 cost model). Restore composes full + verified chain and is
    # bit-identical to a full restore or it falls back.
    checkpoint_full_every: int = 1
    # Flaky-store hardening for object-store checkpointers: per-op
    # timeout in seconds (a hung S3 GET/PUT surfaces as a retryable
    # transient instead of wedging the supervisor; 0 = wait) and retry
    # attempts per op (1 = no retry).
    checkpoint_op_timeout_s: float = 0.0
    checkpoint_op_attempts: int = 3
    n_partitions: int = 8
    # Data-plane non-finite guard (engine host boundary): rows whose
    # score/feature vector crosses the boundary NaN/Inf are quarantined
    # to the dead-letter sink and the batch is re-scored from pre-batch
    # state without them — contamination of the running window
    # aggregates is impossible. Opt-in: it disables step-state donation
    # and serializes the pipeline (depth 1) while on, and it requires a
    # dead_letter sink.
    nan_guard: bool = False
    # Dead-letter queue path for quarantined rows (``*.jsonl`` = JSONL
    # file, anything else = parquet part directory; "" = no DLQ — a
    # crash loop then fails fast instead of quarantining).
    dead_letter: str = ""
    # Crash-loop breaker: this many CONSECUTIVE crash-caused supervisor
    # failures at the same resume point reclassify the failure from
    # transient to poison (bisect + dead-letter instead of replay).
    crash_loop_k: int = 2
    # Backoff between crash-caused supervisor restarts (full jitter,
    # doubling, capped; 0 = the legacy hot restart loop). Stall restarts
    # never back off — they already waited out the stall budget.
    restart_backoff_ms: float = 0.0
    # Overload-survival degradation ladder (see OverloadConfig).
    overload: OverloadConfig = field(default_factory=OverloadConfig)
    # Multi-host process topology (see DistributedConfig): coordinator,
    # process count/id, ingest-affinity strictness.
    distributed: DistributedConfig = field(
        default_factory=DistributedConfig)

    def __post_init__(self):
        if self.z_mode not in ("auto", "f32", "bf16", "int8"):
            raise ValueError(
                f"z_mode must be 'auto', 'f32', 'bf16' or 'int8', "
                f"got {self.z_mode!r}"
            )


@dataclass(frozen=True)
class LearnConfig:
    """Continuous learning: streaming retrain → versioned registry →
    shadow scoring → gated canary promotion (``runtime/learner.py``,
    ``io/registry.py``). The reference's only path to a better model is
    retrain offline, overwrite the pickle, restart the Spark job; here a
    candidate warm-starts from the champion, fits the labeled-feedback
    window off the loop thread, shadow-scores the same live batches, and
    is promoted (and auto-rolled-back) from live precision/recall."""

    # Registry location: a local directory, or ``s3://bucket/prefix``
    # (store-backed; inherits the checkpoint plane's flaky-store
    # hardening). "" = no registry (learning disabled).
    registry_path: str = ""
    # Publish a candidate version after this many NEW labeled rows have
    # been trained since the last publish.
    publish_every_labels: int = 512
    # Bounded replay window of recent labeled rows the learner re-fits
    # per submission (host memory ≈ window_rows × 15 × 4 bytes).
    window_rows: int = 4096
    # Fit passes over the replay window per submitted label chunk.
    epochs: int = 2
    # Bounded learner queue (label chunks); a full queue DROPS (counted
    # in rtfds_learner_dropped_labels_total) — serving never waits.
    queue_chunks: int = 8
    # Learner SGD step size (0 = inherit train.online_learning_rate).
    learning_rate: float = 0.0
    # Shadow score cache rows (tx_id → champion/candidate probs kept
    # until the label arrives; direct-mapped like the FeatureCache).
    shadow_cache_rows: int = 1 << 16
    # Fraud decision threshold used for live precision/recall and for
    # divergence (decision-flip) counting.
    decision_threshold: float = 0.5
    # |p_candidate − p_champion| above this counts as divergence even
    # without a decision flip.
    divergence_threshold: float = 0.25
    # Promotion gate: BOTH models must have this many labeled rows in
    # the current comparison window, AND the candidate's live recall
    # must beat the champion's by promote_margin without giving up more
    # than precision_tolerance of live precision.
    promote_min_labels: int = 256
    promote_margin: float = 0.01
    precision_tolerance: float = 0.02
    # Post-promotion canary watch: after rollback_min_labels labeled
    # rows, the new champion must hold its pre-promotion recall baseline
    # within rollback_margin or the promotion is rolled back.
    rollback_min_labels: int = 256
    rollback_margin: float = 0.05
    # Without an in-stream learner (tree kinds: forest/GBT retrain
    # offline and publish via `rtfds registry`), the loop polls the
    # registry for externally published candidates every this many
    # batches (one backend listing per poll). 0 disables.
    external_poll_batches: int = 64


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh: data axis shards Kafka partitions across chips (ICI)."""

    n_devices: int = 0  # 0 => use all visible devices
    data_axis: str = "data"


@dataclass(frozen=True)
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    features: FeatureConfig = field(default_factory=FeatureConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    learn: LearnConfig = field(default_factory=LearnConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def small_config() -> Config:
    """A tiny config for tests and CPU smoke runs."""
    return Config(
        data=DataConfig(n_customers=50, n_terminals=100, n_days=30, seed=0),
        features=FeatureConfig(customer_capacity=128, terminal_capacity=256,
                               cms_width=1 << 10),
        train=TrainConfig(delta_train_days=15, delta_delay_days=5,
                          delta_test_days=5, epochs=2, batch_size=512),
        runtime=RuntimeConfig(batch_buckets=(64, 256), max_batch_rows=256,
                              n_partitions=4),
    )
