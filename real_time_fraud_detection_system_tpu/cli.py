"""Command-line entry points — the reference Makefile UX, one binary.

Reference targets (``Makefile:2-58``) → subcommands:

- ``make load_initial_data`` / datagen container → ``datagen`` (generate a
  synthetic table to .npz) and ``warmstart`` happens inside ``score``;
- offline notebook chain → ``train`` (features via replay, model fit,
  metrics, artifacts out);
- ``make fraud_detection`` → ``score --scorer {cpu,tpu}`` (the north-star
  switch): stream a table through the engine, Parquet out;
- ``make job3`` (CDC ingestion incl. envelope decode) → ``score
  --mode envelope`` replays through Debezium-format envelopes;
- benchmarking → ``bench`` (delegates to the repo-root harness).

Usage::

    python -m real_time_fraud_detection_system_tpu.cli datagen --out txs.npz
    python -m real_time_fraud_detection_system_tpu.cli train --data txs.npz \
        --model forest --out-model model.npz
    python -m real_time_fraud_detection_system_tpu.cli score --data txs.npz \
        --model-file model.npz --scorer tpu --out analyzed/
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def _backend_probe_ok(timeout_s: float) -> bool:
    """Probe jax backend bring-up in a SUBPROCESS with a hard timeout.

    A remote/tunneled TPU backend (the axon plugin a sitecustomize may
    force) can hang ``jax.devices()`` forever when the tunnel is down —
    observed repeatedly on this hardware. An in-process probe can wedge
    the interpreter, so the probe is its own process."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _probe_cache_path() -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(), "rtfds_backend_probe.json")


def _probe_cache_fresh(ttl_s: float) -> bool:
    """A recent probe success (same JAX_PLATFORMS) skips re-probing.

    The probe is a full backend bring-up in a subprocess; on a healthy
    tunnel that can cost hundreds of seconds, paid on EVERY jax-running
    CLI call without this cache. The sentinel is keyed by the platform
    string so switching JAX_PLATFORMS invalidates it."""
    try:
        with open(_probe_cache_path()) as f:
            c = json.load(f)
        return (
            isinstance(c, dict)
            and c.get("platform") == os.environ.get("JAX_PLATFORMS", "")
            # rtfdslint: disable=wall-clock-duration (TTL vs a stamp persisted by a PREVIOUS process; perf_counter restarts per process, wall clock is the only shared axis)
            and 0 <= time.time() - float(c.get("t", 0)) < ttl_s
        )
    except (OSError, ValueError, TypeError, AttributeError):
        # fixed world-writable path: any unreadable/garbage content just
        # means "no cache" — fall back to probing
        return False


def _probe_cache_store() -> None:
    try:
        tmp = _probe_cache_path() + f".{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"platform": os.environ.get("JAX_PLATFORMS", ""),
                       "t": time.time()}, f)
        os.replace(tmp, _probe_cache_path())
    except OSError:
        pass  # cache is best-effort; next call just probes again


def _platform_setup(platform: str | None, needs_backend: bool = True) -> None:
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    want = os.environ.get("JAX_PLATFORMS")
    # Guard against an unreachable accelerator backend: without an
    # explicit CPU pin, a dead TPU tunnel turns every jax-running command
    # into an indefinite hang inside backend init. Probe first (in a
    # subprocess — costs one extra backend bring-up on the happy path,
    # accepted for never hanging); fail fast with an actionable message.
    # Skipped for commands that run no jax ops (connectors, query,
    # dashboard, datagen) and for bench, whose harness runs its own
    # patient attempt + CPU fallback. RTFDS_BACKEND_PROBE_TIMEOUT=0
    # disables (wait indefinitely); default 600s sits above the longest
    # healthy bring-up observed on this tunnel (~500s, see bench.py).
    probe_needed = needs_backend and (
        (not want) or ("axon" in want) or ("tpu" in want))
    try:
        timeout_s = float(
            os.environ.get("RTFDS_BACKEND_PROBE_TIMEOUT", "600"))
    except ValueError:
        timeout_s = 600.0
    try:
        ttl_s = float(os.environ.get("RTFDS_BACKEND_PROBE_TTL", "600"))
    except ValueError:
        ttl_s = 600.0
    if probe_needed and timeout_s > 0 and ttl_s > 0 \
            and _probe_cache_fresh(ttl_s):
        probe_needed = False
    if probe_needed and timeout_s > 0:
        if not _backend_probe_ok(timeout_s):
            from real_time_fraud_detection_system_tpu.utils import get_logger

            get_logger("cli").error(
                "accelerator backend did not come up within %.0fs (dead "
                "TPU tunnel?) — pass --platform cpu to run on CPU, or set "
                "RTFDS_BACKEND_PROBE_TIMEOUT=0 to wait indefinitely",
                timeout_s,
            )
            raise SystemExit(3)
        _probe_cache_store()
    if want:
        import jax

        jax.config.update("jax_platforms", want)
    from real_time_fraud_detection_system_tpu.utils import (
        enable_compilation_cache,
    )

    # Serving restarts over the TPU tunnel pay ~20-40 s per remote
    # compile; the persistent cache makes them warm starts.
    enable_compilation_cache()


def _json_line(obj) -> str:
    """Strict-JSON dump: NaN/Inf floats become null (json.dumps would emit
    the non-standard literals and break jq/JSON.parse consumers)."""

    def clean(v):
        if isinstance(v, dict):
            return {k: clean(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [clean(x) for x in v]
        if isinstance(v, float) and not np.isfinite(v):
            return None
        return v

    return json.dumps(clean(obj), allow_nan=False)


def _start_epoch_s(start_date: str) -> int:
    from real_time_fraud_detection_system_tpu.utils.timing import (
        date_to_epoch_s,
    )

    return date_to_epoch_s(start_date)


def cmd_datagen(args) -> int:
    from real_time_fraud_detection_system_tpu.config import DataConfig
    from real_time_fraud_detection_system_tpu.data import generate_dataset
    from real_time_fraud_detection_system_tpu.io.artifacts import save_transactions
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("datagen")
    cfg = DataConfig(
        n_customers=args.customers,
        n_terminals=args.terminals,
        n_days=args.days,
        radius=args.radius,
        seed=args.seed,
        start_date=args.start_date,
    )
    customers, terminals, txs = generate_dataset(cfg)
    save_transactions(args.out, txs)
    log.info(
        "generated %d txs (%d customers, %d terminals, %d days) "
        "fraud_rate=%.4f -> %s",
        txs.n, cfg.n_customers, cfg.n_terminals, cfg.n_days,
        txs.tx_fraud.mean(), args.out,
    )
    if args.pg_dsn:
        # Live-OLTP seeding (the reference datagen container's role,
        # datagen/data_gen.py:67-147): rows land in real Postgres for a
        # Debezium connector to CDC out. --pg-rate > 0 drip-feeds.
        from real_time_fraud_detection_system_tpu.io.pg import PgLive
        from real_time_fraud_detection_system_tpu.utils.timing import (
            date_to_epoch_s,
        )

        pg = PgLive(args.pg_dsn)
        pg.ensure_schema()
        pg.upsert_dimension("customers", "customer_id",
                            customers.customer_id, customers.x,
                            customers.y)
        pg.upsert_dimension("terminals", "terminal_id",
                            terminals.terminal_id, terminals.x,
                            terminals.y)
        n = pg.upsert_transactions(
            {
                "tx_id": txs.tx_id,
                "tx_datetime_us": txs.epoch_us(
                    date_to_epoch_s(cfg.start_date)),
                "customer_id": txs.customer_id,
                "terminal_id": txs.terminal_id,
                "tx_amount_cents": txs.amount_cents,
            },
            rate_per_s=args.pg_rate,
        )
        log.info("seeded live postgres with %d transactions", n)
    return 0


def cmd_train(args) -> int:
    from real_time_fraud_detection_system_tpu.config import Config, TrainConfig
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        load_transactions,
        save_model,
    )
    from real_time_fraud_detection_system_tpu.models import train_model
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("train")
    txs = load_transactions(args.data)
    cfg = Config(
        train=TrainConfig(
            delta_train_days=args.delta_train,
            delta_delay_days=args.delta_delay,
            delta_test_days=args.delta_test,
            epochs=args.epochs,
        )
    )
    model, metrics = train_model(txs, cfg, kind=args.model)
    save_model(args.out_model, model)
    log.info("model=%s metrics=%s -> %s", args.model,
             {k: round(v, 4) for k, v in metrics.items()}, args.out_model)
    print(_json_line({"model": args.model, **metrics}))
    return 0


def _make_model_reloader(path: str, kind: str, every_batches: int, log,
                         seed_initial: bool = False, sig_state=None):
    """Hot model reload for serving: every N batches, re-read the model
    artifact and swap weights into the live engine between device steps
    (the reference picks up a retrained pickle only by restarting the
    Spark job, ``fraud_detection.py:59-82``). Local paths gate on mtime,
    ``s3://`` artifacts on HEAD metadata (ETag + size), so an unchanged
    artifact costs one stat/HEAD per interval — the body is downloaded
    only when the metadata changed (stores without ``head()``, or with
    degenerate metadata, fall back to a GET + content digest gate).

    ``seed_initial=False`` (plain serving): the FIRST due interval
    always reloads — a fresh reloader is built per supervisor
    incarnation, and crash recovery restores pre-swap weights from the
    checkpoint, so the new incarnation must re-apply the latest artifact
    rather than trust a stale signature. ``seed_initial=True``
    (``--learn-registry`` active): the file's signature is captured and
    only a CHANGE after startup triggers a reload — the registry's
    champion pointer, not the bootstrap file, is the record of what
    should serve, and the forced first reload would silently clobber an
    adopted promotion with the stale file params. In that mode the
    caller passes ``sig_state`` (one dict shared across supervisor
    incarnations, seeded ONCE): re-baselining per incarnation would
    silently drop a file update that landed between the previous
    incarnation's last poll and its crash.

    The serving kind is pinned — an artifact of a different kind is
    refused (the jitted step's shape family would change under the
    engine)."""
    import hashlib
    import os as _os

    from real_time_fraud_detection_system_tpu.io.artifacts import (
        _split_s3_url,
        load_model,
        load_model_bytes,
    )
    from real_time_fraud_detection_system_tpu.io.store import make_store
    from real_time_fraud_detection_system_tpu.runtime.engine import (
        device_params_for,
    )

    # "n" (poll cadence) is per-incarnation; "sig" lives in sig_state
    # when the caller shares one across incarnations.
    state = sig_state if sig_state is not None else {}
    state.setdefault("sig", None)
    state["n"] = 0
    is_local = not path.startswith("s3://")
    url = key = None
    if not is_local:
        url, key = _split_s3_url(path)

    def _meta_sig(md):
        # the ONE signature format for store artifacts (ETag + size, or
        # None to force the GET+digest fallback) — the seed baseline and
        # poll's change gate must always agree on it
        if md.get("etag") or md.get("size") is not None:
            return f"{md.get('etag')}:{md.get('size')}"
        return None

    if seed_initial and state["sig"] is None:
        try:
            if is_local:
                state["sig"] = _os.stat(path).st_mtime_ns
            else:
                store = make_store(url)
                head = getattr(store, "head", None)
                md = head(key) if head is not None else {}
                state["sig"] = _meta_sig(md) or hashlib.sha256(
                    store.get(key)).hexdigest()
        # rtfdslint: disable=broad-exception-catch (any store/head/hash failure degrades to a forced first-interval reload, warn-logged; reload polling must never kill serving)
        except Exception as e:
            log.warning("could not baseline %s for change-gated reload "
                        "(%s); the first interval will reload it", path, e)
            state["sig"] = None

    def poll():
        state["n"] += 1
        if state["n"] % every_batches:
            return None
        try:
            if is_local:
                sig = _os.stat(path).st_mtime_ns
                if state["sig"] is not None and sig == state["sig"]:
                    return None
                m = load_model(path)
            else:
                store = make_store(url)
                # Change-gate on HEAD metadata (ETag/size) so an
                # unchanged artifact costs one HEAD per interval, not a
                # full GET. When metadata says it changed, the STORED
                # signature comes from the GET response itself
                # (get_with_meta) so it always describes the bytes
                # actually loaded — a pre-GET HEAD sig could belong to an
                # older version overwritten between the two requests
                # (safe direction, but one redundant swap per overwrite).
                # Stores without head() (older fakes) fall back to the
                # GET+digest gate.
                head = getattr(store, "head", None)
                get_with_meta = getattr(store, "get_with_meta", None)
                meta = head(key) if head is not None else {}
                sig = _meta_sig(meta)
                if sig is not None:
                    if state["sig"] is not None and sig == state["sig"]:
                        return None
                    if get_with_meta is not None:
                        data, gmeta = get_with_meta(key)
                        sig = _meta_sig(gmeta) or sig
                    else:
                        data = store.get(key)
                else:
                    # no head() or degenerate metadata: digest-gate (the
                    # digest is computed from the loaded bytes, so it is
                    # always self-consistent)
                    data = store.get(key)
                    sig = hashlib.sha256(data).hexdigest()
                    if state["sig"] is not None and sig == state["sig"]:
                        return None
                m = load_model_bytes(data)
        # rtfdslint: disable=broad-exception-catch (a failed reload poll of ANY kind keeps serving on current weights, warn-logged; next interval retries)
        except Exception as e:
            log.warning("model reload from %s failed (%s); serving "
                        "continues on the current weights", path, e)
            return None
        if m.kind != kind:
            log.warning("model reload skipped: artifact kind %r != "
                        "serving kind %r", m.kind, kind)
            return None
        state["sig"] = sig
        log.info("hot-swapped model weights from %s", path)
        return device_params_for(kind, m.params), m.scaler

    # Shared-baseline mode: expose the dict so the supervisor's zombie
    # fence can roll back a signature a fenced-off incarnation committed
    # for a swap that can never land (faults._run_watched).
    poll.sig_state = state if sig_state is not None else None
    return poll


def _resume_merge_adopt(make_engine, ckpt, cfg, topology, spec,
                        cold_srcs, log):
    """Adopt a drained old-generation fleet's final checkpoints into
    THIS worker's own (empty) checkpoint lineage — the retopologize leg
    of an elastic fleet resize.

    ``spec`` is the parsed ``--resume-merge`` tuple ``(src_root, old_p,
    old_l, reason)``. Every old process's final checkpoint restores
    into a template state, the per-process feature states merge through
    :func:`parallel.mesh.merge_process_states` (checkpointed terminal-
    CMS partials are locals-only, so same-day shard sums stay exact),
    old cold-store generations consolidate into this worker's cold dir,
    and ONE single-chip global checkpoint lands in this worker's
    lineage with the stream cursor rewound to the fleet-wide minimum
    floor. Per-old-owner floors ride in a ``resize_epochs`` record so
    re-polled rows another old process already sank are dropped at
    ingest (:class:`runtime.OwnershipFloorSource`) — no row lost, none
    double-scored. Idempotent: a worker relaunched after its merge
    already landed re-reads the floors from its newest manifest instead
    of re-merging.

    Returns the per-old-owner floor list (possibly empty = no floor
    filtering needed) or ``None`` on failure — the caller exits rc 2,
    because serving without the merged state would break exactly-once.
    """
    import copy as _copy

    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        make_checkpointer,
    )
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        merge_process_states,
    )

    src_root, old_p, old_l, reason = spec
    latest = ckpt.latest()
    if latest is not None:
        # Crash AFTER the merge committed: this worker's lineage already
        # starts from the merged state — re-merging would clobber
        # progress. The floors live in the stamped resize epoch.
        try:
            meta = (ckpt.manifest(latest) or {}).get("meta") or {}
        # rtfdslint: disable=broad-exception-catch (an unreadable tip manifest here only degrades the floor filter; restore itself re-verifies and falls back down the lineage)
        except Exception:
            meta = {}
        epochs = meta.get("resize_epochs") or []
        if epochs:
            rec = epochs[-1]
            log.info("resume-merge: lineage already merged (epoch %s, "
                     "%s->%s); resuming from it",
                     len(epochs), rec.get("from_processes"),
                     rec.get("to_processes"))
            return [int(f) for f in rec.get("floors", [])]
        log.warning("resume-merge: %s already has ordinary checkpoints; "
                    "skipping the merge and resuming from them", latest)
        return []
    tmpl = make_engine()
    eng_l = int(getattr(tmpl.state, "layout_devices", 1) or 1)
    if old_l != eng_l:
        log.error("--resume-merge: old fleet served %d device(s) per "
                  "process but this worker serves %d — resize the "
                  "process count at fixed width, then change width "
                  "separately (the per-process reshard path)",
                  old_l, eng_l)
        return None
    states, floors, rows_done = [], [], 0
    prior_epochs: list = []
    model_version = None
    for pid in range(old_p):
        src_dir = (os.path.join(src_root, f"proc-{pid:02d}")
                   if old_p > 1 else src_root)
        try:
            src = make_checkpointer(
                src_dir,
                op_timeout_s=cfg.runtime.checkpoint_op_timeout_s,
                op_attempts=cfg.runtime.checkpoint_op_attempts)
        # rtfdslint: disable=broad-exception-catch (any backend open failure means the old generation's state is unreachable — report and refuse, whatever the type)
        except Exception as e:
            log.error("resume-merge: cannot open old checkpoints at "
                      "%s: %s", src_dir, e)
            return None
        st = _copy.deepcopy(tmpl.state)
        st.process_count, st.process_id = old_p, pid
        restored = src.restore(st)
        if restored is None:
            log.error("resume-merge: old process %d has no restorable "
                      "checkpoint under %s — a resize must drain to a "
                      "final checkpoint first", pid, src_dir)
            return None
        if len(restored.offsets) > 1:
            log.error("resume-merge: old process %d carries %d stream "
                      "cursors; only single-cursor sources resize "
                      "(broker fleets keep per-partition offsets)",
                      pid, len(restored.offsets))
            return None
        # no cursor at all = the process drained before its first poll
        # (a resize can land during warmup): its floor is stream start
        floors.append(int(restored.offsets[0]) if restored.offsets
                      else 0)
        rows_done += int(restored.rows_done)
        if model_version is None:
            model_version = getattr(restored, "model_version", None)
        if not prior_epochs:
            prior_epochs = list(
                getattr(restored, "resize_epochs", None) or [])
        states.append(restored.feature_state)
    try:
        merged_fs = merge_process_states(states, cfg, [old_l] * old_p)
    except ValueError as e:
        log.error("resume-merge: %s", e)
        return None
    out = _copy.deepcopy(tmpl.state)
    out.feature_state = merged_fs
    out.offsets = [min(floors)]
    out.batches_done = 0  # fresh per-generation sink lineage
    out.rows_done = rows_done
    out.layout_devices = 1
    out.process_count = 1  # global state; restore re-slices per process
    out.process_id = 0
    out.model_version = model_version
    new_p = topology.n_processes if topology is not None else 1
    out.resize_epochs = prior_epochs + [{
        "epoch": len(prior_epochs) + 1,
        "from_processes": old_p,
        "to_processes": new_p,
        "old_local_devices": old_l,
        "reason": reason,
        "floors": floors,
        "min_offset": min(floors),
    }]
    if cold_srcs:
        from real_time_fraud_detection_system_tpu.io.coldstore import (
            ColdStoreCorruptError,
            consolidate_cold_stores,
        )

        try:
            dest = consolidate_cold_stores(
                cold_srcs, cfg.features.cold_store,
                segment_mb=cfg.features.cold_segment_mb)
        except (OSError, ValueError, ColdStoreCorruptError) as e:
            log.error("resume-merge: cold-store consolidation failed: "
                      "%s", e)
            return None
        out.cold_lineage = dest.lineage()
        log.info("resume-merge: consolidated %d cold generation(s) "
                 "into %s (%d keys)", len(cold_srcs),
                 cfg.features.cold_store,
                 int(out.cold_lineage.get("total_keys", 0)))
    saved = ckpt.save(out)
    log.info("resume-merge: adopted %d-process generation at %s -> %s "
             "(floors %s, min offset %d, reason %r)",
             old_p, src_root, saved, floors, min(floors), reason)
    return floors


def cmd_score(args) -> int:
    from real_time_fraud_detection_system_tpu.config import Config
    from real_time_fraud_detection_system_tpu.io import make_parquet_sink
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        load_model,
        load_transactions,
    )
    from real_time_fraud_detection_system_tpu.io.checkpoint import make_checkpointer
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("score")
    if args.source != "kafka" and not args.data:
        log.error("--data is required unless --source kafka")
        return 2
    # Failure-handling flags fail fast BEFORE any artifact loads.
    if args.nan_guard and not args.dead_letter:
        log.error("--nan-guard needs --dead-letter: quarantined rows "
                  "must land somewhere an operator can triage them")
        return 2
    multihost = args.num_processes > 1 or bool(args.coordinator)
    if args.nan_guard and (args.devices > 1 or multihost):
        log.error("--nan-guard is not wired for the sharded engine "
                  "(--devices > 1 / multi-host); rely on the "
                  "supervisor's crash-loop bisection (--dead-letter + "
                  "--max-restarts) there")
        return 2
    if multihost and args.num_processes < 1:
        log.error("--num-processes must be >= 1, got %s",
                  args.num_processes)
        return 2
    if args.max_batch_rows < 0:
        log.error("--max-batch-rows must be >= 0, got %s",
                  args.max_batch_rows)
        return 2
    import dataclasses as _dc

    # Multi-host bootstrap FIRST: jax.distributed.initialize refuses to
    # run after any jax computation, and artifact loading below builds
    # device arrays. The topology is config; everything after threads it.
    topology = None
    dist_cfg = None
    if multihost:
        from real_time_fraud_detection_system_tpu.config import (
            DistributedConfig,
        )
        from real_time_fraud_detection_system_tpu.runtime.distributed \
            import bootstrap_distributed

        try:
            dist_cfg = DistributedConfig(
                coordinator=args.coordinator,
                num_processes=max(args.num_processes, 1),
                process_id=args.process_id,
                # Kafka fleets slice by broker partition; residue
                # membership is the producer's contract, not checkable
                # per polled row
                strict_affinity=args.source != "kafka",
            )
            topology = bootstrap_distributed(
                dist_cfg, local_devices=max(args.devices, 1))
        except (ValueError, RuntimeError) as e:
            log.error("multi-host bootstrap failed: %s", e)
            return 2
        if topology is not None:
            log.info(
                "multi-host: process %d/%d, %d local device(s), global "
                "shards [%d, %d) of %d, coordinator %s",
                topology.process_id, topology.n_processes,
                topology.local_devices, topology.owned_shards.start,
                topology.owned_shards.stop, topology.n_shards_total,
                args.coordinator or "(uncoordinated)")
    if args.crash_loop_k < 1:
        log.error("--crash-loop-k must be >= 1, got %s", args.crash_loop_k)
        return 2
    if args.restart_backoff_ms < 0:
        log.error("--restart-backoff-ms must be >= 0, got %s",
                  args.restart_backoff_ms)
        return 2
    if args.checkpoint_full_every < 1:
        log.error("--checkpoint-full-every must be >= 1, got %s",
                  args.checkpoint_full_every)
        return 2
    if args.checkpoint_op_attempts < 1 or args.checkpoint_op_timeout < 0:
        log.error("--checkpoint-op-attempts must be >= 1 and "
                  "--checkpoint-op-timeout >= 0, got %s / %s",
                  args.checkpoint_op_attempts, args.checkpoint_op_timeout)
        return 2
    # replay reads a generated .npz; raw-table reads a table DIRECTORY
    txs = (load_transactions(args.data)
           if args.data and args.source == "replay" else None)
    model = load_model(args.model_file)
    if args.reload_model_every > 0 and args.scorer == "cpu":
        # the cpu oracle classifies host-side via the startup-captured
        # model object; a swap would re-scale features with the new
        # scaler while the OLD sklearn model predicts — actively wrong
        log.error("--reload-model-every does not compose with "
                  "--scorer cpu (the oracle model is fixed at startup)")
        return 2
    # With --learn-registry the registry's champion pointer, not the
    # bootstrap file, is the record of what should serve: seed the
    # reloader's signature baseline so only a file CHANGE after startup
    # triggers a swap — the forced first reload would silently clobber
    # an adopted promotion with stale file params. The signature dict is
    # shared across supervisor incarnations (seeded once): a fresh
    # baseline per incarnation would silently drop a file update landing
    # in the last-poll→crash window.
    _reload_sig: dict = {}
    make_reloader = (
        (lambda: _make_model_reloader(
            args.model_file, model.kind, args.reload_model_every, log,
            seed_initial=bool(args.learn_registry),
            sig_state=_reload_sig if args.learn_registry else None))
        if args.reload_model_every > 0 else None)
    cfg = Config()
    if args.alerts_only and (args.scorer == "cpu"
                             or args.feedback_bootstrap):
        log.error("--alerts-only keeps features in HBM; it does not "
                  "compose with --scorer cpu or the feedback loop "
                  "(both consume host-side feature rows)")
        return 2
    if args.alerts_only and args.out:
        log.warning("--alerts-only: the analyzed output at %s will carry "
                    "zero feature columns (predictions only)", args.out)
    if args.emit_bf16 and (args.scorer == "cpu" or args.feedback_bootstrap):
        log.error("--emit-bf16 rounds the emitted feature columns; "
                  "--scorer cpu and the feedback loop re-consume them "
                  "and would drift — keep float32 emission")
        return 2
    if not 0.0 <= args.emit_threshold <= 1.0:
        log.error("--emit-threshold must be a probability in [0, 1], "
                  "got %s", args.emit_threshold)
        return 2
    if args.emit_threshold > 0:
        bad = None
        if args.alerts_only:
            bad = ("--emit-threshold emits flagged rows' features; "
                   "--alerts-only emits none — pick one")
        elif args.emit_bf16:
            bad = ("--emit-threshold already cuts feature D2H ~100x at "
                   "alert-rate traffic; it does not compose with "
                   "--emit-bf16 (the packed transfer is f32)")
        elif args.scorer == "cpu" or args.feedback_bootstrap:
            bad = ("--emit-threshold keeps clean rows' features in HBM; "
                   "--scorer cpu and the feedback loop consume every "
                   "row's features host-side")
        if bad:
            log.error(bad)
            return 2
        if args.out:
            log.info("selective emission: feature columns at %s are "
                     "populated only for rows with prob >= %.3g "
                     "(zeros elsewhere)", args.out, args.emit_threshold)
    if args.latency_slo_ms < 0:
        log.error("--latency-slo-ms must be >= 0, got %s",
                  args.latency_slo_ms)
        return 2
    if args.decode_workers < 0 or args.prefetch_batches < 0:
        log.error("--decode-workers and --prefetch-batches must be >= 0, "
                  "got %s / %s", args.decode_workers, args.prefetch_batches)
        return 2
    try:
        overload_cfg = _dc.replace(
            cfg.runtime.overload,
            enabled=args.overload,
            spill_path=args.overload_spill,
            lag_high_rows=args.overload_lag_high,
            climb_pressure=args.overload_climb_pressure,
            descend_pressure=args.overload_descend_pressure,
            climb_dwell_batches=args.overload_climb_dwell,
            descend_dwell_batches=args.overload_descend_dwell,
            max_deferred_batches=args.overload_max_deferred,
        )
    except ValueError as e:
        log.error("--overload thresholds: %s", e)
        return 2
    if args.overload:
        log.info(
            "overload ladder on: climb >= %.2f for %d, descend <= %.2f "
            "for %d, lag high %s rows, spill %r",
            overload_cfg.climb_pressure, overload_cfg.climb_dwell_batches,
            overload_cfg.descend_pressure,
            overload_cfg.descend_dwell_batches,
            overload_cfg.lag_high_rows or "off",
            overload_cfg.spill_path or "(memory only)")
    cfg = cfg.replace(runtime=_dc.replace(
        cfg.runtime,
        max_batch_rows=(args.max_batch_rows
                        or cfg.runtime.max_batch_rows),
        distributed=dist_cfg or cfg.runtime.distributed,
        emit_features=not args.alerts_only,
        emit_dtype="bfloat16" if args.emit_bf16 else "float32",
        emit_threshold=args.emit_threshold,
        pipeline_depth=args.pipeline_depth,
        coalesce_rows=args.coalesce_rows,
        use_pallas=args.use_pallas,
        z_mode=args.z_mode,
        precompile=args.precompile,
        # an SLO implies the controller: the knob is the intent
        autobatch=args.autobatch or args.latency_slo_ms > 0,
        latency_slo_ms=args.latency_slo_ms,
        async_sink=args.async_sink,
        sink_queue_batches=args.sink_queue_batches,
        decode_workers=args.decode_workers,
        prefetch_batches=args.prefetch_batches,
        fetch_overlap=not args.no_fetch_overlap,
        nan_guard=args.nan_guard,
        dead_letter=args.dead_letter,
        crash_loop_k=args.crash_loop_k,
        restart_backoff_ms=args.restart_backoff_ms,
        checkpoint_full_every=args.checkpoint_full_every,
        checkpoint_op_timeout_s=args.checkpoint_op_timeout,
        checkpoint_op_attempts=args.checkpoint_op_attempts,
        overload=overload_cfg,
    ))
    # Feature-plane knobs (the tiered device-resident feature store).
    if args.state_compact_every > 0 and args.key_mode != "exact":
        log.error("--state-compact-every only applies to --key-mode "
                  "exact (direct/hash tables have no slot allocator to "
                  "reclaim into)")
        return 2
    try:
        cfg = cfg.replace(features=_dc.replace(
            cfg.features,
            key_mode=args.key_mode,
            compact_every=args.state_compact_every,
            state_hbm_budget_mb=args.state_hbm_budget_mb,
            cold_store=args.cold_store,
            cold_promote_queue=args.cold_promote_queue,
            cold_segment_mb=args.cold_segment_mb,
        ))
    except ValueError as e:
        log.error("feature-plane config: %s", e)
        return 2
    if args.state_hbm_budget_mb > 0:
        # pre-validate with the CLI convention (rc 2 + a log line, not a
        # constructor traceback); the engines enforce the same check at
        # build for non-CLI callers
        from real_time_fraud_detection_system_tpu.features.online import (
            state_bytes as _state_bytes,
        )

        need = _state_bytes(cfg.features,
                            n_shards=max(args.devices, 1))["total"]
        if need > args.state_hbm_budget_mb * 2 ** 20:
            log.error(
                "--state-hbm-budget-mb %g cannot hold the configured "
                "feature state (%.1f MB: run with a larger budget, or "
                "shrink customer/terminal capacity or cms_width)",
                args.state_hbm_budget_mb, need / 2 ** 20)
            return 2
    if args.key_mode == "exact":
        from real_time_fraud_detection_system_tpu.features.online import (
            state_bytes,
        )

        sb = state_bytes(cfg.features, n_shards=max(args.devices, 1))
        log.info(
            "tiered feature store: hot tier %d+%d slots, compaction "
            "every %s batches, state %.1f MB (dense %.1f, directory "
            "%.1f, cms %.1f)%s",
            cfg.features.customer_capacity, cfg.features.terminal_capacity,
            args.state_compact_every or "off",
            sb["total"] / 2 ** 20, sb["dense"] / 2 ** 20,
            sb["directory"] / 2 ** 20, sb["cms"] / 2 ** 20,
            f" of {args.state_hbm_budget_mb:g} MB budget"
            if args.state_hbm_budget_mb > 0 else "")
        if cfg.features.cold_store:
            log.info(
                "host cold tier: %s (segment %.1f MB, promote queue %d) "
                "— evicted keys demote with exact rows and promote back "
                "asynchronously on return",
                cfg.features.cold_store, cfg.features.cold_segment_mb,
                cfg.features.cold_promote_queue)
    cfg = cfg.replace(learn=_dc.replace(
        cfg.learn,
        registry_path=args.learn_registry,
        publish_every_labels=args.publish_every_labels,
        promote_min_labels=args.promote_min_labels,
        promote_margin=args.promote_margin,
        rollback_min_labels=args.rollback_min_labels,
        rollback_margin=args.rollback_margin,
    ))
    if args.learn_registry:
        bad = None
        if args.devices > 1 or multihost:
            bad = ("--learn-registry is not wired for the sharded "
                   "engine (--devices > 1 / multi-host)")
        elif args.scorer == "cpu":
            bad = ("--learn-registry promotes by swapping on-device "
                   "params; --scorer cpu classifies host-side with a "
                   "model fixed at startup")
        elif model.kind == "sequence":
            bad = ("shadow scoring is not wired for kind='sequence' "
                   "(no host-side feature matrix to dual-score)")
        elif args.alerts_only or args.emit_threshold > 0 or args.emit_bf16:
            bad = ("shadow scoring re-consumes every row's features "
                   "host-side; it does not compose with --alerts-only, "
                   "--emit-threshold or --emit-bf16")
        if bad:
            log.error(bad)
            return 2
        if not args.feedback_bootstrap:
            log.warning(
                "continuous learning without --feedback-bootstrap: no "
                "live labels arrive, so the shadow's live precision/"
                "recall windows stay empty and promotion never fires "
                "(the registry lineage still records reloads)")
    # Unconditional (0 resolves to auto): publishes the
    # rtfds_decode_workers gauge the README's host-plane reading uses,
    # in auto mode too.
    from real_time_fraud_detection_system_tpu.core import native

    log.info("ingest decode workers: %d",
             native.set_decode_workers(args.decode_workers))
    if model.kind in ("tree", "forest", "gbt"):
        from real_time_fraud_detection_system_tpu.models.forest import (
            resolve_z_mode,
        )

        log.info("device plane: z_mode=%s (requested %r), use_pallas=%s",
                 resolve_z_mode(args.z_mode), args.z_mode, args.use_pallas)
    cpu_model = None
    if args.scorer == "cpu":
        cpu_model = model  # TrainedModel.predict_proba runs host-side numpy

    if (args.devices > 1 or multihost) and args.scorer == "cpu":
        log.error("--scorer cpu is the single-host sklearn oracle; it does "
                  "not compose with --devices > 1 or multi-host (the "
                  "sharded engine always scores on-device)")
        return 2
    if multihost and model.kind == "sequence":
        log.error("multi-host serving is not wired for kind='sequence' "
                  "(no history-state process adoption); serve it "
                  "single-process")
        return 2

    if model.kind == "sequence":
        # fail fast with the CLI convention instead of constructor
        # tracebacks (the engines raise the same constraints)
        bad = None
        if args.scorer == "cpu":
            bad = ("--scorer cpu does not apply to kind='sequence' "
                   "(no sklearn oracle for the transformer)")
        elif args.online_lr > 0:
            bad = "online SGD is not wired for kind='sequence'"
        elif args.feedback_bootstrap:
            bad = ("the labeled-feedback loop is not wired for "
                   "kind='sequence'")
        elif args.emit_threshold > 0:
            bad = ("--emit-threshold has no effect for kind='sequence' "
                   "(no feature matrix leaves the device)")
        if bad:
            log.error(bad)
            return 2

    feature_cache = None
    make_feedback = None
    if args.feedback_bootstrap:
        from real_time_fraud_detection_system_tpu.runtime import (
            FeatureCache,
            FeedbackLoop,
            KafkaFeedbackSource,
        )

        feature_cache = FeatureCache()

        def make_feedback(engine):
            # Fresh consumer session per incarnation (group fencing).
            # Non-blocking polls: the loop runs in the scoring hot path
            # between batches, and the feedback topic is usually quiet
            # (labels arrive days late) — a blocking poll would cap
            # serving throughput.
            return FeedbackLoop(
                engine,
                KafkaFeedbackSource(args.feedback_bootstrap,
                                    topic=args.feedback_topic,
                                    poll_timeout_s=0.0),
            )

    dead_letter = None
    if args.dead_letter:
        from real_time_fraud_detection_system_tpu.io.sink import (
            make_dead_letter_sink,
        )

        dead_letter = make_dead_letter_sink(args.dead_letter)
        log.info("dead-letter queue: %s (%d row(s) already quarantined)",
                 args.dead_letter, len(dead_letter))

    learning = None
    if args.learn_registry:
        from real_time_fraud_detection_system_tpu.io.registry import (
            make_model_registry,
        )
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            loss_fn_for,
        )
        from real_time_fraud_detection_system_tpu.runtime.learner import (
            LearningLoop,
            StreamingLearner,
        )

        model_registry = make_model_registry(
            args.learn_registry,
            op_timeout_s=cfg.runtime.checkpoint_op_timeout_s,
            op_attempts=cfg.runtime.checkpoint_op_attempts)
        # Restart continuity: a registry with a champion pointer is the
        # record of what should be serving — a promotion must survive a
        # process restart, so the champion artifact supersedes the
        # (bootstrap-era) --model-file params. Without this the lineage,
        # metrics and rollback baselines would all describe a model that
        # is not actually serving.
        champ_v = model_registry.champion_version()
        # False when a champion exists but could not be adopted: the
        # engines then serve --model-file params, and the learning
        # loop's version stamp must not claim they are the champion's.
        model_is_champion = True
        if champ_v is not None:
            model_is_champion = False
            try:
                champ = model_registry.champion()
            # rtfdslint: disable=broad-exception-catch (corrupt/missing champion falls back to the --model-file params; the registry names the repair path)
            except Exception as e:
                log.warning(
                    "registry champion v%s failed verification (%s: %s); "
                    "serving the --model-file params instead — repair "
                    "with `rtfds registry --verify` / --rollback",
                    champ_v, type(e).__name__, e)
            else:
                if champ.kind != model.kind:
                    log.error(
                        "registry champion v%s is kind=%r but "
                        "--model-file is kind=%r; point --learn-registry "
                        "at this model's registry or retrain",
                        champ_v, champ.kind, model.kind)
                    return 2
                log.info("serving registry champion v%s (supersedes "
                         "--model-file)", champ_v)
                model = champ
                model_is_champion = True
        learner = None
        if loss_fn_for(model.kind) is not None:
            learner = StreamingLearner(
                model.kind, model.params, model.scaler, cfg,
                model_registry,
                publish_every_labels=cfg.learn.publish_every_labels,
                window_rows=cfg.learn.window_rows,
                epochs=cfg.learn.epochs,
                max_queue=cfg.learn.queue_chunks,
                learning_rate=cfg.learn.learning_rate or None)
        else:
            log.info("model kind %r has no gradient path: the registry "
                     "records lineage and shadow-scores externally "
                     "published candidates, but no streaming learner "
                     "runs (tree ensembles retrain offline and publish "
                     "via `rtfds registry`)", model.kind)
        learning = LearningLoop(model_registry, cfg, model.kind,
                                model=model, learner=learner,
                                model_is_champion=model_is_champion)
        log.info("continuous learning on: registry %s (champion v%s)",
                 args.learn_registry, learning.champion_version)

    def make_engine():
        if args.devices > 1 or topology is not None:
            from real_time_fraud_detection_system_tpu.runtime import (
                ShardedScoringEngine,
            )

            return ShardedScoringEngine(
                cfg,
                kind=model.kind,
                params=model.params,
                scaler=model.scaler,
                n_devices=args.devices,
                online_lr=args.online_lr,
                feature_cache=feature_cache,
                dead_letter=dead_letter,
                topology=topology,
            )
        return ScoringEngine(
            cfg,
            kind=model.kind,
            params=model.params,
            scaler=model.scaler,
            scorer=args.scorer,
            cpu_model=cpu_model,
            online_lr=args.online_lr,
            feature_cache=feature_cache,
            dead_letter=dead_letter,
        )

    ckpt_dir, out_path, raw_path = (args.checkpoint_dir, args.out,
                                    args.raw_table)
    if topology is not None:
        # Shard-aware durable state: each process owns its residue
        # block's lineage under proc-NN/ of the shared roots (same
        # paths across restarts, so --resume finds the right block; a
        # topology change is refused at restore with the merge path
        # named). Sink parts split the same way — per-process
        # batch_index lineages stay individually gap/dup-free — and so
        # does the cold tier (two processes appending segments into one
        # directory would collide on segment seq numbers).
        sub = f"proc-{topology.process_id:02d}"
        ckpt_dir = os.path.join(ckpt_dir, sub) if ckpt_dir else ckpt_dir
        out_path = os.path.join(out_path, sub) if out_path else out_path
        raw_path = os.path.join(raw_path, sub) if raw_path else raw_path
        if cfg.features.cold_store:
            cfg = cfg.replace(features=_dc.replace(
                cfg.features,
                cold_store=os.path.join(cfg.features.cold_store, sub)))
    ckpt = make_checkpointer(
        ckpt_dir,
        full_every=cfg.runtime.checkpoint_full_every,
        op_timeout_s=cfg.runtime.checkpoint_op_timeout_s,
        op_attempts=cfg.runtime.checkpoint_op_attempts,
    ) if ckpt_dir else None

    # --- elastic-fleet seams (tools/multihost_launcher.py --autoscale) --
    drain_ev = None
    if args.drain_on_sigterm:
        import signal as _signal
        import threading as _threading

        drain_ev = _threading.Event()
        # idempotent: repeated SIGTERMs keep the same drain in flight;
        # the engine breaks at the NEXT batch boundary (no batch is
        # abandoned mid-flight, offsets stay behind durable output)
        _signal.signal(_signal.SIGTERM,
                       lambda _sig, _frm: drain_ev.set())
        log.info("drain-on-sigterm armed: SIGTERM = coordinated drain "
                 "to a final checkpoint, not a kill")
    cms_exchange = None
    if args.cms_exchange and topology is None:
        # Not an error: an elastic fleet passes uniform worker args and
        # legitimately shrinks to one process, where local terminal
        # aggregates are already global.
        log.info("--cms-exchange idle: single-process terminal "
                 "aggregates are already global")
    elif args.cms_exchange:
        from real_time_fraud_detection_system_tpu.runtime import (
            SketchExchange,
        )

        cms_exchange = SketchExchange(
            args.cms_exchange, topology.process_id,
            topology.n_processes)
        log.info("terminal-sketch exchange: %s (fleet-wide merge at "
                 "checkpoint boundaries, locals-only partials in "
                 "checkpoints)", args.cms_exchange)
    if drain_ev is not None or cms_exchange is not None:
        _make_engine_plain = make_engine

        def make_engine():
            eng = _make_engine_plain()
            eng.stop_event = drain_ev
            eng.cms_exchange = cms_exchange
            return eng

    resume_floors = None
    merge_old_p = merge_old_l = 0
    if args.resume_merge:
        try:
            src_root, p_s, l_s, merge_reason = \
                args.resume_merge.rsplit(":", 3)
            merge_old_p, merge_old_l = int(p_s), int(l_s)
            if not src_root or merge_old_p < 1 or merge_old_l < 1:
                raise ValueError(args.resume_merge)
        except ValueError:
            log.error("--resume-merge wants OLD_CKPT_ROOT:P:L:REASON, "
                      "got %r", args.resume_merge)
            return 2
        bad = None
        if ckpt is None:
            bad = "--resume-merge requires --checkpoint-dir"
        elif not args.resume:
            bad = ("--resume-merge requires --resume (the merged "
                   "checkpoint is what this worker resumes from)")
        elif args.source == "kafka":
            bad = ("--resume-merge does not apply to --source kafka "
                   "(broker fleets carry per-partition offsets through "
                   "a resize; no single-cursor merge is needed)")
        elif args.resume_merge_cold and not cfg.features.cold_store:
            bad = "--resume-merge-cold requires --cold-store"
        if bad:
            log.error(bad)
            return 2
        resume_floors = _resume_merge_adopt(
            make_engine, ckpt, cfg, topology,
            (src_root, merge_old_p, merge_old_l, merge_reason),
            [d for d in args.resume_merge_cold.split(",") if d],
            log)
        if resume_floors is None:
            return 2

    source_factory = None
    if args.source == "kafka":
        from real_time_fraud_detection_system_tpu.runtime.sources import (
            make_kafka_source,
        )

        kafka_kw = {}
        if topology is not None:
            # Partition-affine ingest: this process consumes ONLY its
            # block of broker partitions (manual assign — the framework
            # owns placement, not the consumer group), so no row ever
            # crosses a process boundary on the host plane.
            kafka_kw = dict(
                partitions=topology.kafka_partitions(
                    cfg.runtime.n_partitions),
                n_partitions=cfg.runtime.n_partitions,
                group_id=f"rtfds-scorer-p{topology.process_id}",
            )
            log.info("kafka partition affinity: consuming partitions %s "
                     "of %d", kafka_kw["partitions"],
                     cfg.runtime.n_partitions)

        def source_factory():
            # Fresh consumer per incarnation: a zombie session's partitions
            # are fenced off by the broker's group generation.
            return make_kafka_source(
                args.bootstrap, topic=args.topic,
                batch_rows=args.batch_rows,
                idle_timeout_s=args.idle_timeout or None,
                **kafka_kw,
            )

        source = source_factory()
    elif args.source == "raw-table":
        from real_time_fraud_detection_system_tpu.runtime.sources import (
            RawTableSource,
        )

        try:
            source = RawTableSource(
                args.data,
                batch_rows=args.batch_rows,
                from_day=args.from_date or None,
                to_day=args.to_date or None,
            )
        except (FileNotFoundError, ValueError) as e:
            log.error("%s", e)
            return 2
        log.info("raw-table backfill: %d rows", source.n)
    else:
        source = ReplaySource(
            txs,
            _start_epoch_s(args.start_date),
            batch_rows=args.batch_rows,
            mode=args.mode,
            with_labels=args.online_lr > 0,
        )
    if resume_floors and len(set(resume_floors)) > 1:
        # Post-merge resume with DIVERGED old-process cursors: drop
        # re-polled rows the further-ahead old owners already sank.
        # Inside the affine wrap below — floors index the shared
        # stream's positions, pre-slicing.
        from real_time_fraud_detection_system_tpu.runtime import (
            OwnershipFloorSource,
        )

        source = OwnershipFloorSource(source, resume_floors,
                                      merge_old_p, merge_old_l)
        log.info("per-owner resume floors active: %s (pure passthrough "
                 "past position %d)", resume_floors, max(resume_floors))
    if topology is not None and args.source != "kafka":
        # Residue-sliced ingest for partition-less sources: this process
        # serves only its owned customer residues of the shared stream
        # (Kafka fleets got true partition assignment above instead).
        # Wrapped INSIDE any prefetch below, so the producer thread
        # prefetches already-sliced batches.
        from real_time_fraud_detection_system_tpu.runtime import (
            PartitionAffineSource,
        )

        source = PartitionAffineSource(source, topology)
        log.info("partition-affine ingest: serving residues [%d, %d) "
                 "of %d", topology.owned_shards.start,
                 topology.owned_shards.stop, topology.n_shards_total)
    if cfg.runtime.prefetch_batches > 0:
        # Background source prefetch: poll + decode run ahead of the
        # loop on a producer thread. Wrapped OUTSIDE any fault injectors
        # the source may carry, and re-wrapped per incarnation via the
        # factory (supervised mode) so each restart owns a fresh
        # producer generation. Offsets commit on consumption; poison
        # isolation flips the wrapper to synchronous serving.
        from real_time_fraud_detection_system_tpu.runtime import (
            PrefetchSource,
        )

        depth = cfg.runtime.prefetch_batches
        if source_factory is not None:
            inner_factory = source_factory

            def source_factory():
                return PrefetchSource(inner_factory(), max_batches=depth)

        source = PrefetchSource(source, max_batches=depth)
        log.info("source prefetch on (queue depth %d)", depth)
    sink = make_parquet_sink(out_path) if out_path else None
    raw_table = None
    if args.raw_table:
        from real_time_fraud_detection_system_tpu.io import (
            RawTransactionsTable,
        )
        from real_time_fraud_detection_system_tpu.io.sink import FanoutSink

        raw_table = RawTransactionsTable(raw_path,
                                         flush_every_batches=64)
        sink = FanoutSink(sink, raw_table)
    if cfg.runtime.async_sink and sink is not None:
        # Wrap OUTSIDE the fanout so one writer thread serves every
        # destination in order; the engine drains it before checkpoint
        # saves (offsets keep trailing durable output) and at run end.
        from real_time_fraud_detection_system_tpu.io.sink import AsyncSink

        sink = AsyncSink(sink, max_queue=cfg.runtime.sink_queue_batches)
        log.info("async sink offload on (queue depth %d)",
                 cfg.runtime.sink_queue_batches)
    if args.max_restarts > 0 and ckpt is None:
        log.error("--max-restarts requires --checkpoint-dir "
                  "(there is nothing to recover from without checkpoints)")
        return 2
    if args.stall_timeout > 0 and not (args.max_restarts > 0 and ckpt):
        log.error("--stall-timeout requires supervised mode "
                  "(--max-restarts with --checkpoint-dir); without it the "
                  "watchdog has no restart path to escalate into")
        return 2
    from real_time_fraud_detection_system_tpu.utils import profile_to

    if args.trace_dir and args.source == "kafka" and not args.max_batches:
        # jax.profiler buffers the whole trace in host memory until
        # stop_trace; an unbounded live stream would grow it without limit.
        log.warning(
            "--trace-dir on an unbounded Kafka stream traces the ENTIRE "
            "run and buffers it in host memory; bound the run with "
            "--max-batches for a usable trace"
        )

    server = None
    recorder = None
    tracer = None
    if args.trace_out or args.metrics_port:
        from real_time_fraud_detection_system_tpu.utils.trace import (
            get_tracer,
        )

        # Span tracing for the serving run: per-batch waterfalls as
        # Chrome-trace JSON (Perfetto / chrome://tracing / `rtfds
        # trace`). The ring buffer keeps the most recent spans, so an
        # unbounded stream stays memory-bounded — unlike --trace-dir's
        # full jax.profiler capture. A --metrics-port run enables it
        # too (µs/batch): GET /trace must serve a live timeline, not a
        # silently empty one.
        tracer = get_tracer().configure(enabled=True)
        if args.trace_out:
            log.info("span tracing on: will export %s", args.trace_out)
        else:
            log.info("span tracing on: GET /trace serves the live "
                     "span ring buffer")
    if args.metrics_port or args.flight_record:
        from real_time_fraud_detection_system_tpu.utils.metrics import (
            FlightRecorder,
            MetricsServer,
            run_manifest,
            set_active_recorder,
        )
    if args.metrics_port:
        # Opt-in ops endpoints for the serve loop: /metrics (Prometheus
        # text), /metrics.json, /healthz (source lag + last-batch-age).
        # 0.0.0.0 so a scrape sidecar / probe can reach it in-container.
        server = MetricsServer(
            port=args.metrics_port, host="0.0.0.0",
            max_batch_age_s=args.healthz_max_batch_age,
            max_source_lag_rows=args.healthz_max_lag_rows or None)
        server.start()
        log.info("telemetry: /metrics /metrics.json /healthz on port %d",
                 server.port)
    if args.flight_record:
        recorder = FlightRecorder(
            args.flight_record,
            manifest=run_manifest(
                cfg=cfg, model_kind=model.kind, scorer=args.scorer,
                source=args.source, devices=args.devices),
            max_bytes=int(args.flight_record_max_mb * 2 ** 20)
            if args.flight_record_max_mb > 0 else None)
        # process-wide: the engine loop, checkpointer, supervisor, and
        # fault injectors all append to this run's record
        set_active_recorder(recorder)
        log.info("flight record: %s", args.flight_record)

    fb = None
    try:
        with profile_to(args.trace_dir or None):
            if ckpt is not None and args.max_restarts > 0:
                # Supervised mode: restart-on-failure with checkpoint replay
                # (the compose `restart: on-failure` + Spark checkpoint
                # contract).
                from real_time_fraud_detection_system_tpu.runtime.faults import (
                    RetryPolicy,
                    run_with_recovery,
                )

                backoff = None
                if args.restart_backoff_ms > 0:
                    # doubling, full jitter, capped at 30 s — the
                    # fleet-safe default curve; the knob sets the base
                    backoff = RetryPolicy(
                        base_delay_s=args.restart_backoff_ms / 1000.0,
                        multiplier=2.0, max_delay_s=30.0, jitter=1.0)
                stats = run_with_recovery(
                    make_engine, source, ckpt, sink=sink,
                    max_restarts=args.max_restarts, max_batches=args.max_batches,
                    resume=args.resume, stall_timeout_s=args.stall_timeout,
                    make_source=source_factory, make_feedback=make_feedback,
                    make_model_reload=make_reloader,
                    learning=learning,
                    crash_loop_k=args.crash_loop_k,
                    dead_letter=dead_letter,
                    restart_backoff=backoff,
                )
            else:
                engine = make_engine()
                if ckpt is not None and args.resume:
                    restored = ckpt.restore(engine.state)
                    if restored is not None:
                        source.seek(engine.state.offsets)
                        log.info("resumed from batch %d",
                                 engine.state.batches_done)
                    truncate = getattr(sink, "truncate_after", None)
                    if truncate is not None:
                        truncate(engine.state.batches_done)
                fb = make_feedback(engine) if make_feedback else None
                stats = engine.run(
                    source, sink=sink, checkpointer=ckpt,
                    max_batches=args.max_batches, feedback=fb,
                    model_reload=make_reloader() if make_reloader else None,
                    learning=learning,
                )
                if drain_ev is not None and ckpt is not None:
                    # Drain-armed worker: run() ended (SIGTERM break OR
                    # natural stream end) at a batch boundary with the
                    # sink drained and cold lineage refreshed — pin the
                    # FINAL checkpoint to that exact frontier so a
                    # resize merge resumes gap/dup-free (deferred/shed
                    # rows sit behind these offsets by the overload
                    # defer contract and re-poll under the new fleet; a
                    # stale cadence checkpoint would replay rows the
                    # sink already holds).
                    ckpt.save(engine.checkpoint_state())
                    if drain_ev.is_set():
                        stats["drained_at_batch"] = \
                            engine.state.batches_done
                        log.info("coordinated drain complete: final "
                                 "checkpoint at batch %d",
                                 engine.state.batches_done)
    finally:
        close = getattr(source, "close", None)
        if close is not None:
            close()
        if cfg.runtime.async_sink and sink is not None:
            # stop the writer thread; never mask the run's own error
            # with a drain-time one (it was already warn-logged)
            try:
                sink.close()
            # rtfdslint: disable=broad-exception-catch (drain-time close error was already warn-logged by the writer; re-raising here would mask the run's own error)
            except Exception as e:
                log.warning("async sink close: %s: %s",
                            type(e).__name__, e)
        if fb is not None:
            fb.close()
        if learning is not None:
            learning.close()
        if recorder is not None:
            set_active_recorder(None)
            recorder.close()
        if server is not None:
            server.stop()
        if args.metrics_dump:
            # success or failure: the registry snapshot is how the
            # multihost bench/smoke assert recompile counts per worker
            # without scraping a live port
            from real_time_fraud_detection_system_tpu.utils.metrics \
                import get_registry

            try:
                with open(args.metrics_dump, "w", encoding="utf-8") as f:
                    json.dump(get_registry().snapshot(), f)
            except OSError as e:
                log.warning("metrics dump to %s failed: %s",
                            args.metrics_dump, e)
        if tracer is not None and args.trace_out:
            # export even on a failed run — a crash mid-stream is
            # exactly when the last batches' waterfalls matter
            try:
                man = tracer.export(args.trace_out)
                log.info("span trace: %s (%d events) — summarize with "
                         "`rtfds trace --trace %s`, or load in "
                         "ui.perfetto.dev", man["trace"], man["events"],
                         args.trace_out)
            except OSError as e:
                log.warning("span trace export to %s failed: %s",
                            args.trace_out, e)
    if raw_table is not None:
        raw_table.flush()
        stats["raw_tx_rows"] = len(raw_table)
    if dead_letter is not None:
        stats["dead_letter_rows"] = len(dead_letter)
        close_dlq = getattr(dead_letter, "close", None)
        if close_dlq is not None:
            close_dlq()
    if topology is not None:
        stats.update(
            num_processes=topology.n_processes,
            process_id=topology.process_id,
            owned_shards=[topology.owned_shards.start,
                          topology.owned_shards.stop],
        )
    log.info("done: %s", stats)
    print(_json_line({"scorer": args.scorer, **stats}))
    return 0


def cmd_warmup(args) -> int:
    """AOT-compile the serving step for every batch bucket, then exit.

    Run once per deploy (or in an init container): every bucket size ×
    step variant is ``.lower(...).compile()``d through the persistent
    compilation cache (``utils.enable_compilation_cache``), so the
    serving process that follows — with or without ``--precompile`` —
    starts warm instead of paying per-bucket XLA compiles inside the
    stream (969 ms measured vs 8 ms steady-state per first-touch
    bucket). Pass the same serving-shape flags you will serve with
    (``--devices``, ``--online-lr``, emission mode): they change the
    step's compiled program."""
    import dataclasses as _dc
    import time as _time

    from real_time_fraud_detection_system_tpu.config import Config
    from real_time_fraud_detection_system_tpu.io.artifacts import load_model
    from real_time_fraud_detection_system_tpu.runtime import ScoringEngine
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("warmup")
    model = load_model(args.model_file)
    cfg = Config()
    cfg = cfg.replace(runtime=_dc.replace(
        cfg.runtime,
        emit_features=not args.alerts_only,
        emit_threshold=args.emit_threshold,
        emit_dtype="bfloat16" if args.emit_bf16 else "float32",
        use_pallas=args.use_pallas,
        z_mode=args.z_mode,
        precompile=True,
    ))
    t0 = _time.perf_counter()
    if args.devices > 1:
        from real_time_fraud_detection_system_tpu.runtime import (
            ShardedScoringEngine,
        )

        engine = ShardedScoringEngine(
            cfg, kind=model.kind, params=model.params, scaler=model.scaler,
            n_devices=args.devices, online_lr=args.online_lr)
    else:
        engine = ScoringEngine(
            cfg, kind=model.kind, params=model.params, scaler=model.scaler,
            online_lr=args.online_lr)
    man = engine.precompile()
    out = {
        "kind": model.kind,
        "devices": args.devices,
        "buckets": man["buckets"],
        "variants": man["variants"],
        "compile_seconds": man["seconds"],
        "total_seconds": round(_time.perf_counter() - t0, 3),
    }
    log.info("warmup done: %s", out)
    print(_json_line(out))
    return 0


def cmd_dlq(args) -> int:
    """Inspect / replay dead-letter-queue rows (the poison quarantine).

    Inspection prints a one-line summary (rows by reason/error) plus up
    to ``--limit`` row records as JSON lines. ``--replay`` re-scores the
    quarantined rows through a fresh engine built from ``--model-file``
    — the post-fix triage tool: rows that now score cleanly print a
    prediction, rows that still crash print their error and stay
    quarantined. Replay runs against FRESH feature state (window
    aggregates start empty), so it answers "does this row still crash?",
    not "what would its production score have been" — re-run the stream
    for that."""
    from real_time_fraud_detection_system_tpu.io.sink import (
        read_dead_letter,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("dlq")
    try:
        rows = read_dead_letter(args.path)
    except FileNotFoundError as e:
        print(_json_line({"error": str(e)}))
        return 2
    by_reason: dict = {}
    by_error: dict = {}
    for r in rows:
        by_reason[r.get("reason", "?")] = \
            by_reason.get(r.get("reason", "?"), 0) + 1
        etype = str(r.get("error", ""))[:60].split(":")[0] or "?"
        by_error[etype] = by_error.get(etype, 0) + 1
    summary = {
        "path": args.path,
        "rows": len(rows),
        "by_reason": by_reason,
        "by_error_type": by_error,
        "batches": sorted({int(r.get("batch_index", -1)) for r in rows}),
    }
    if not args.replay:
        print(_json_line(summary))
        for r in rows[: max(args.limit, 0)]:
            print(_json_line(r))
        if args.limit and len(rows) > args.limit:
            print(_json_line({"truncated": True, "limit": args.limit}))
        return 0
    if not args.model_file:
        log.error("--replay needs --model-file")
        return 2
    if not rows:
        print(_json_line({**summary, "replayed": 0}))
        return 0
    # Replay runs real jax ops: apply the dead-tunnel probe the plain
    # inspection path deliberately skips (needs_backend=False).
    _platform_setup(getattr(args, "platform", None), needs_backend=True)
    from real_time_fraud_detection_system_tpu.config import Config
    from real_time_fraud_detection_system_tpu.io.artifacts import load_model
    from real_time_fraud_detection_system_tpu.runtime import ScoringEngine

    model = load_model(args.model_file)
    need = ("tx_id", "tx_datetime_us", "customer_id", "terminal_id",
            "tx_amount_cents", "kafka_ts_ms")

    def row_cols(recs):
        return {k: np.asarray([int(r["columns"].get(k, 0)) for r in recs],
                              dtype=np.int64) for k in need}

    def fresh_engine():
        return ScoringEngine(Config(), kind=model.kind,
                             params=model.params, scaler=model.scaler)

    out = []
    try:
        res = fresh_engine().process_batch(row_cols(rows))
        probs = {int(t): float(p) for t, p in zip(res.tx_id, res.probs)}
        for r in rows:
            out.append({"tx_id": r["tx_id"], "reason": r.get("reason"),
                        "prediction": probs.get(int(r["tx_id"]))})
    # rtfdslint: disable=broad-exception-catch (DLQ replay triage: the batch probe exists to catch WHATEVER the poison rows throw, then re-probe row-by-row)
    except Exception:
        # at least one row still crashes: probe row-by-row so the clean
        # ones still get a score and the poison names itself
        for r in rows:
            try:
                res = fresh_engine().process_batch(row_cols([r]))
                out.append({
                    "tx_id": r["tx_id"], "reason": r.get("reason"),
                    "prediction": float(res.probs[0]) if len(res.probs)
                    else None})
            # rtfdslint: disable=broad-exception-catch (per-row triage: a still-poison row reports its error type in the JSON verdict instead of aborting the replay)
            except Exception as e:
                out.append({"tx_id": r["tx_id"], "reason": r.get("reason"),
                            "error": f"{type(e).__name__}: {e}"[:200],
                            "still_poison": True})
    print(_json_line({**summary, "replayed": len(out)}))
    for o in out:
        print(_json_line(o))
    return 0


def cmd_ckpt(args) -> int:
    """Inspect / verify the checkpoint lineage (the durable-state plane).

    Default: list every live checkpoint with kind (full/delta/v1), size,
    age, batch counter, and a cheap validity verdict. ``--verify``
    re-checksums every live checkpoint AND its delta chain (the deploy
    preflight: exit 1 on any corruption, so a rollout gates on a
    restorable lineage). ``--inspect NAME`` dumps one checkpoint's
    manifest (per-leaf CRCs, fingerprint, incarnation, chain link).
    """
    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        make_checkpointer,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("ckpt")
    try:
        ck = make_checkpointer(args.path)
    # rtfdslint: disable=broad-exception-catch (bad URL/creds/store backend → rc 2 usage error with the cause printed; a triage CLI must report, not traceback)
    except Exception as e:
        log.error("cannot open checkpoint lineage at %s: %s", args.path, e)
        return 2
    if args.inspect:
        try:
            man = ck.manifest(args.inspect)
        except KeyError:
            log.error("no checkpoint named %s under %s", args.inspect,
                      args.path)
            return 2
        # rtfdslint: disable=broad-exception-catch (corrupt manifest is the FINDING this preflight exists to report — rc 1 with the error, whatever its type)
        except Exception as e:
            print(_json_line({"path": args.inspect, "valid": False,
                              "error": f"{type(e).__name__}: {e}"[:300]}))
            return 1
        from real_time_fraud_detection_system_tpu.io.checkpoint import (
            feature_state_report,
        )

        fs = feature_state_report(man)
        if fs is not None:
            # named feature-state leaves with per-shard byte attribution
            # + writer-recorded directory occupancy: state skew visible
            # from the manifest, no restore needed
            man = {**man, "feature_state": fs}
        meta = man.get("meta") or {}
        pc = int(meta.get("process_count", 1) or 1)
        ld = int(meta.get("layout_devices", 1) or 1)
        # writer topology from the manifest alone: which residue block
        # this entry holds, and how wide the fleet's shard space was —
        # the preflight that catches a topology-mismatched relaunch
        # before restore refuses it
        man = {**man, "topology": {
            "process_count": pc,
            "process_id": int(meta.get("process_id", 0) or 0),
            "layout_devices": ld,
            "fleet_shards_total": pc * ld,
        }}
        if meta.get("resize_epochs"):
            # Elastic-resize lineage from the manifest alone: every
            # fleet P→P′ this state lived through, with the per-old-
            # owner resume floors that made the transition exact.
            man = {**man, "resize_epochs": meta["resize_epochs"]}
        print(_json_line({"path": args.inspect, **man}))
        return 0
    # listing stays cheap (one read per entry); only --verify pays for
    # the full chain re-checksum
    report = ck.verify_all(deep=bool(args.verify))
    n_bad = sum(1 for e in report if not e.get("valid"))
    summary = {
        "path": args.path,
        "checkpoints": len(report),
        "corrupt": n_bad,
        "latest": ck.latest(),
    }
    print(_json_line(summary))
    for e in report:
        if not args.verify:
            # listing mode: drop the verbose corruption detail
            e = {k: v for k, v in e.items() if k != "detail"}
        print(_json_line(e))
    if args.verify and n_bad:
        log.error("%d corrupt checkpoint(s) in the lineage — restore "
                  "would fall back past them; quarantine or rebuild "
                  "before deploying", n_bad)
        return 1
    return 0


def cmd_registry(args) -> int:
    """Inspect / verify / roll back the versioned model registry (the
    continuous-learning artifact plane — `rtfds ckpt`'s model twin).

    Default: one row per live version (kind, size, parent lineage,
    source, labels trained, champion flag). ``--verify`` re-hashes every
    artifact against its manifest AND its internal content hash (deploy
    preflight: exit 1 on any corruption — a corrupt candidate must never
    reach a promotion gate). ``--inspect N`` dumps one version's
    manifest. ``--promote N`` verifies THEN moves the champion pointer;
    ``--rollback`` pops it back to the previous champion.
    """
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        CorruptModelError,
    )
    from real_time_fraud_detection_system_tpu.io.registry import (
        make_model_registry,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("registry")
    try:
        reg = make_model_registry(args.path)
    # rtfdslint: disable=broad-exception-catch (bad URL/creds/store backend → rc 2 usage error with the cause printed; a triage CLI must report, not traceback)
    except Exception as e:
        log.error("cannot open model registry at %s: %s", args.path, e)
        return 2
    if args.publish:
        from real_time_fraud_detection_system_tpu.io.artifacts import (
            load_model,
        )

        try:
            m = load_model(args.publish)  # content-hash verified
        except CorruptModelError as e:
            log.error("refusing to publish %s: artifact failed "
                      "verification (%s)", args.publish, e.reason)
            return 1
        # rtfdslint: disable=broad-exception-catch (missing file / bad npz / OS error all mean "cannot publish this artifact" → rc 2 with the cause)
        except Exception as e:
            log.error("cannot load model artifact %s: %s",
                      args.publish, e)
            return 2
        v = reg.publish(m, parent=reg.champion_version(), source="cli",
                        note=args.publish)
        print(_json_line({"published": v, "kind": m.kind,
                          "parent": reg.champion_version()}))
        return 0
    if args.rollback:
        prev = reg.rollback()
        if prev is None:
            log.error("no promotion history to roll back to")
            return 1
        print(_json_line({"champion": prev, "by": "rollback"}))
        return 0
    if args.promote:
        try:
            reg.get(args.promote)  # verify AT the gate, like the loop
        except KeyError:
            log.error("no version %d in the registry", args.promote)
            return 2
        except CorruptModelError as e:
            log.error("version %d failed verification (%s) and was "
                      "quarantined — it can never be promoted",
                      args.promote, e.reason)
            return 1
        ptr = reg.promote(args.promote, by="cli")
        print(_json_line(ptr))
        return 0
    if args.inspect:
        try:
            man = reg.meta(args.inspect)
        except KeyError:
            log.error("no version %d in the registry", args.inspect)
            return 2
        except CorruptModelError as e:
            log.error("manifest for version %d is corrupt (%s)",
                      args.inspect, e.reason)
            return 1
        print(_json_line(man))
        return 0
    if args.verify:
        report = reg.verify_all()
        n_bad = sum(1 for e in report if not e.get("valid"))
        print(_json_line({"path": args.path, "versions": len(report),
                          "corrupt": n_bad,
                          "champion": reg.champion_version()}))
        for e in report:
            print(_json_line(e))
        if n_bad:
            log.error("%d corrupt artifact(s) still listed in the "
                      "registry (the preflight never quarantines; each "
                      "will be quarantined on its first read and can "
                      "never be promoted) — republish or roll back "
                      "before deploying", n_bad)
            return 1
        return 0
    print(_json_line({"path": args.path,
                      "champion": reg.champion_version()}))
    for row in reg.list_versions():
        print(_json_line(row))
    return 0


def cmd_demo(args) -> int:
    """Full E2E demo: generate → CDC envelopes → sink jobs → score.

    The in-process equivalent of the reference's `make up && make
    load_initial_data && make connectors && make run-all` flow (README.md:
    31-43) with the datagen container driving it.
    """
    from real_time_fraud_detection_system_tpu.config import (
        Config,
        DataConfig,
        FeatureConfig,
        TrainConfig,
    )
    from real_time_fraud_detection_system_tpu.runtime.pipeline import run_demo
    from real_time_fraud_detection_system_tpu.utils.logging import get_logger

    log = get_logger("demo")
    if args.out.startswith("s3://"):
        # run_demo also lands a local raw table + dashboard beside the
        # analyzed parts; object-store output is the serving path's job.
        log.error("rtfds demo writes a local output directory (analyzed "
                  "parts + raw table + dashboard); for s3:// output use "
                  "rtfds score --out s3://...")
        return 2
    cfg = Config(
        data=DataConfig(
            n_customers=args.customers,
            n_terminals=args.terminals,
            n_days=args.days,
            seed=args.seed,
        ),
        features=FeatureConfig(
            customer_capacity=_pow2_capacity_for(args.customers),
            terminal_capacity=_pow2_capacity_for(args.terminals),
        ),
        train=TrainConfig(
            delta_train_days=args.delta_train,
            delta_delay_days=args.delta_delay,
            delta_test_days=args.delta_test,
        ),
    )
    model = None
    if args.model_file:
        from real_time_fraud_detection_system_tpu.io.artifacts import (
            load_model,
        )

        model = load_model(args.model_file)
        log.info("loaded model %s from %s", model.kind, args.model_file)
    summary = run_demo(
        cfg,
        model=model,
        model_kind=args.model,
        out_dir=args.out or None,
        batch_rows=args.batch_rows,
        n_devices=args.devices,
    )
    if args.out:
        # Close the loop the way the reference demo does — README.md:31-43
        # ends at the Superset dashboard; here it ends at the static one.
        # A dashboard failure must not discard the already-computed summary.
        from real_time_fraud_detection_system_tpu.io.dashboard import (
            write_dashboard,
        )

        try:
            dash = write_dashboard(
                args.out, os.path.join(args.out, "dashboard.html"))
            summary["dashboard"] = dash["dashboard"]
        except OSError as e:
            log.warning("dashboard render failed: %s", e)
            summary["dashboard_error"] = str(e)
    print(_json_line(summary))
    return 0


def _pow2_capacity_for(n: int) -> int:
    """Smallest power of two >= 2n — direct-mode slot capacity with 2x
    headroom over the live key count."""
    p = 1
    while p < 2 * n:
        p *= 2
    return p


def cmd_query(args) -> int:
    """Dashboard reports over analyzed output (the Trino/Superset role)."""
    from real_time_fraud_detection_system_tpu.io.query import (
        load_analyzed,
        raw_transactions_report,
        report,
    )

    if args.report == "transactions":
        # Raw-table report: --data is the day-partitioned table directory
        # (e.g. <demo-out>/transactions).
        try:
            print(_json_line(raw_transactions_report(args.data)))
        except FileNotFoundError as e:
            print(_json_line({"error": str(e)}))
            return 2
        return 0
    cols = load_analyzed(args.data)
    out = report(cols, kind=args.report, threshold=args.threshold,
                 k=args.top_k, bucket=args.bucket)
    print(_json_line(out))
    return 0


def cmd_sql(args) -> int:
    """Ad-hoc SQL over the analyzed output — the Trino role, in-process.

    Mounts the ParquetSink directory as an ``analyzed`` table (DuckDB
    when installed, else pyarrow+sqlite; latest-wins dedup view either
    way) and prints the result as JSON lines, one object per row.
    """
    from real_time_fraud_detection_system_tpu.io.sqlquery import (
        AnalyzedSql,
    )

    limit = max(0, args.limit)  # <= 0 means unlimited
    try:
        db = AnalyzedSql(args.data)
    # rtfdslint: disable=broad-exception-catch (the JSON error contract holds for EVERY open failure — corrupt part file, permissions, missing dir — not just FileNotFoundError)
    except Exception as e:
        # corrupt part file / permissions / missing dir: the JSON error
        # contract holds for every failure, not just FileNotFoundError
        print(_json_line({"error": f"{type(e).__name__}: {e}"}))
        return 2
    try:
        # fetch one row past the limit: bounds memory on huge results
        # while still detecting truncation
        names, rows = db.query(args.query,
                               max_rows=limit + 1 if limit else 0)
    # rtfdslint: disable=broad-exception-catch (same JSON error contract for query execution: sqlite/duckdb/pyarrow each raise their own types)
    except Exception as e:
        print(_json_line({"error": f"{type(e).__name__}: {e}"}))
        return 2
    finally:
        db.close()
    shown = rows[:limit] if limit else rows
    for r in shown:
        print(_json_line(dict(zip(names, r))))
    if limit and len(rows) > limit:
        print(_json_line({"truncated": True, "limit": limit}))
    return 0


def cmd_import_model(args) -> int:
    """Convert the reference's pickled artifacts into the npz model.

    The reference ships ``trained_model.pkl`` (a fitted sklearn
    classifier, uploaded to S3 by ``load_initial_data.py:269-287``) and
    ``scaler.pkl`` (joblib StandardScaler, ``model_training.ipynb ·
    cell 31``). This imports both into the framework's pickle-free npz
    (``io/artifacts.py``) so existing reference artifacts serve on TPU
    unchanged: RandomForest/DecisionTree → flat node tables, XGBClassifier
    → GBT leaf-sum form (xgboost import-gated), LogisticRegression →
    logreg weights. Unpickling EXECUTES code — import only artifacts you
    trust (your own training output)."""
    import pickle

    import jax.numpy as jnp

    from real_time_fraud_detection_system_tpu.features.spec import (
        FEATURE_NAMES,
    )
    from real_time_fraud_detection_system_tpu.io.artifacts import save_model
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.models.train import TrainedModel
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("import-model")
    n_features = len(FEATURE_NAMES)
    if args.model_pkl.startswith("s3://"):
        # the reference keeps trained_model.pkl in the object store
        # (s3://commerce/trained_model.pkl, load_initial_data.py:269-287)
        import io as _io

        from real_time_fraud_detection_system_tpu.io.artifacts import (
            _split_s3_url,
        )
        from real_time_fraud_detection_system_tpu.io.store import make_store

        try:
            url, key = _split_s3_url(args.model_pkl)
        except ValueError as e:
            log.error("%s", e)
            return 2
        clf = pickle.load(_io.BytesIO(make_store(url).get(key)))
    else:
        with open(args.model_pkl, "rb") as f:
            clf = pickle.load(f)

    # Fail loudly on shape/class mismatches: a 20-feature or multiclass
    # model would otherwise import cleanly and serve silently-wrong
    # probabilities (tree feature gathers clamp out-of-range indices).
    n_in = getattr(clf, "n_features_in_", None)
    if n_in is not None and int(n_in) != n_features:
        log.error("model was fitted on %d features; the serving feature "
                  "vector has %d (features/spec.py)", int(n_in), n_features)
        return 2
    classes = getattr(clf, "classes_", None)
    if classes is not None and len(classes) != 2:
        log.error("binary classifiers only: model has %d classes",
                  len(classes))
        return 2
    # Same count in a different COLUMN ORDER would also serve
    # silently-wrong probabilities; when the pickle recorded its fitted
    # feature names (sklearn ≥1.0 with a DataFrame fit), require them to
    # match the serving order exactly.
    names = getattr(clf, "feature_names_in_", None)
    if names is not None:
        from real_time_fraud_detection_system_tpu.features.spec import (
            FEATURE_NAMES,
        )

        got = [str(x) for x in names]
        if got != list(FEATURE_NAMES):
            log.error(
                "model was fitted on feature names/order %s; the serving "
                "vector is %s (features/spec.py) — re-export the model "
                "with the serving column order", got, list(FEATURE_NAMES))
            return 2

    if args.scaler_pkl:
        import joblib  # ships with sklearn

        sk_scaler = joblib.load(args.scaler_pkl)
        if len(np.asarray(sk_scaler.mean_)) != n_features:
            log.error("scaler was fitted on %d features; expected %d",
                      len(np.asarray(sk_scaler.mean_)), n_features)
            return 2
        scaler = Scaler(
            mean=jnp.asarray(sk_scaler.mean_, jnp.float32),
            scale=jnp.asarray(sk_scaler.scale_, jnp.float32),
        )
    else:
        # identity scaling (model trained on raw features)
        scaler = Scaler(mean=jnp.zeros(n_features, jnp.float32),
                        scale=jnp.ones(n_features, jnp.float32))

    name = type(clf).__name__
    if name in ("RandomForestClassifier", "ExtraTreesClassifier",
                "DecisionTreeClassifier"):
        from real_time_fraud_detection_system_tpu.models.forest import (
            ensemble_from_sklearn,
        )

        kind = "tree" if name == "DecisionTreeClassifier" else "forest"
        params = ensemble_from_sklearn(clf, n_features)
    elif name == "XGBClassifier":
        from real_time_fraud_detection_system_tpu.models.gbt import (
            gbt_from_xgboost,
        )

        kind = "gbt"
        params = gbt_from_xgboost(clf, n_features)
    elif name == "LogisticRegression":
        from real_time_fraud_detection_system_tpu.models.logreg import (
            LogRegParams,
        )

        kind = "logreg"
        params = LogRegParams(
            w=jnp.asarray(clf.coef_[0], jnp.float32),
            b=jnp.asarray(clf.intercept_[0], jnp.float32),
        )
    else:
        log.error("unsupported classifier type %s (supported: "
                  "RandomForest/ExtraTrees/DecisionTree/XGB/"
                  "LogisticRegression)", name)
        return 2

    model = TrainedModel(kind=kind, scaler=scaler, params=params)
    save_model(args.out_model, model)
    log.info("imported %s (%s) -> %s", args.model_pkl, kind, args.out_model)
    print(_json_line({"kind": kind, "out_model": args.out_model,
                      "n_features": n_features}))
    return 0


def cmd_connectors(args) -> int:
    """Register the Debezium Postgres source connector with Kafka Connect.

    The reference's ``make connectors`` POSTs its connector JSON to the
    Connect REST API (``Makefile:21-22`` → ``:8083/connectors/``, config
    at ``connect/pg-src-connector.json``: PostgresConnector, tasks.max 1,
    schema include ``payment``, topic prefix ``debezium``). Same here,
    stdlib-only; 409 Conflict (already registered) is success."""
    import urllib.error
    import urllib.request

    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("connectors")
    body = {
        "name": args.name,
        "config": {
            "connector.class":
                "io.debezium.connector.postgresql.PostgresConnector",
            "tasks.max": "1",
            "database.hostname": args.db_host,
            "database.port": str(args.db_port),
            "database.user": args.db_user,
            "database.password": args.db_password,
            "database.dbname": args.db_name,
            "database.include.list": args.db_name,
            "schema.include.list": args.schema,
            "topic.prefix": args.topic_prefix,
        },
    }
    url = args.connect_url.rstrip("/") + "/connectors/"
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Accept": "application/json",
                 "Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=args.timeout) as resp:
            raw = resp.read() or b"{}"
            try:
                payload = json.loads(raw)
            except json.JSONDecodeError:
                # a 2xx from something that is NOT Kafka Connect
                log.error("non-JSON response from %s (is this really the "
                          "Connect REST API?): %r", url, raw[:120])
                return 1
            # Connect echoes the full config back — redact the secret
            # before it can reach stdout/CI logs
            if isinstance(payload, dict):
                cfg_echo = payload.get("config")
                if isinstance(cfg_echo, dict) and "database.password" in cfg_echo:
                    cfg_echo["database.password"] = "***"
            out = {"status": resp.status,
                   "connector": args.name,
                   "response": payload}
    except urllib.error.HTTPError as e:
        if e.code == 409:
            out = {"status": 409, "connector": args.name,
                   "already_registered": True}
        else:
            log.error("connect REST error %s: %s", e.code,
                      e.read()[:200].decode(errors="replace"))
            return 1
    except (urllib.error.URLError, OSError) as e:
        log.error("cannot reach Kafka Connect at %s: %s", url, e)
        return 1
    print(_json_line(out))
    return 0


def cmd_dashboard(args) -> int:
    """Render the static-HTML ops dashboard (the Superset role)."""
    from real_time_fraud_detection_system_tpu.io.dashboard import (
        write_dashboard,
        write_ops_dashboard,
    )

    if bool(args.data) == bool(args.flight_record):
        # exactly one input: each view is a full page written to --out,
        # so taking both would silently drop one of them
        print(_json_line(
            {"error": "pass exactly one of --data (analyzed view) or "
                      "--flight-record (ops-health view); render them "
                      "to separate --out files"}))
        return 2
    try:
        if args.flight_record:
            # Ops-health view over the serving run's flight record.
            manifest = write_ops_dashboard(
                args.flight_record, args.out, title=args.title)
        else:
            manifest = write_dashboard(
                args.data,
                args.out,
                threshold=args.threshold,
                top_k=args.top_k,
                bucket=args.bucket,
                title=args.title,
            )
    except FileNotFoundError as e:
        print(_json_line({"error": str(e)}))
        return 2
    print(_json_line(manifest))
    return 0


def cmd_trace(args) -> int:
    """Summarize an exported span trace: per-batch critical path, top-K
    slowest spans, XLA compile/recompile events, and an ASCII waterfall
    of the slowest (or a chosen) batch.

    Input is the Chrome-trace JSON written by ``rtfds score
    --trace-out``, fetched from the serving loop's ``GET /trace``, or
    produced by ``make trace-demo`` — the same file loads graphically
    in ui.perfetto.dev / chrome://tracing."""
    from real_time_fraud_detection_system_tpu.io.dashboard import (
        render_trace_waterfall,
    )
    from real_time_fraud_detection_system_tpu.utils.trace import (
        summarize_chrome,
    )

    try:
        with open(args.trace, encoding="utf-8") as f:
            trace = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(_json_line({"error": f"{type(e).__name__}: {e}"}))
        return 2
    summary = summarize_chrome(trace, top_k=args.top_k)
    if args.json:
        print(_json_line(summary))
        return 0
    batches = summary["batches"]
    print(f"{summary['n_events']} span events, {len(batches)} batches, "
          f"{len(summary['compile_events'])} XLA compile events")
    if batches:
        worst = sorted(batches, key=lambda b: -b["total_ms"])[:args.top_k]
        print(f"\nslowest batches (top {len(worst)}), critical phase "
              "per batch:")
        for b in worst:
            phases = " ".join(f"{k}={v:.2f}" for k, v in
                              b["phases_ms"].items())
            print(f"  {b['trace_id']}  total {b['total_ms']:9.3f} ms  "
                  f"critical {b['critical_phase']} "
                  f"({b['critical_ms']:.3f} ms)  [{phases}]")
    if summary["slowest_spans"]:
        print(f"\nslowest spans (top {len(summary['slowest_spans'])}):")
        for s in summary["slowest_spans"]:
            print(f"  {s['dur_ms']:9.3f} ms  {s['name']:<16} "
                  f"{s['trace_id'] or '-'}")
    if summary["compile_events"]:
        print("\nXLA compile/recompile events:")
        for c in summary["compile_events"]:
            extra = (" " + ", ".join(f"{k}={v}" for k, v in
                                     c["args"].items())
                     if c.get("args") else "")
            print(f"  {c['name']:<14} {c['dur_ms']:9.3f} ms  "
                  f"{c['trace_id'] or '-'}{extra}")
    print()
    print(render_trace_waterfall(trace, trace_id=args.batch or None))
    return 0


def cmd_compare(args) -> int:
    """Fit every requested model kind on one shared split and report
    metrics + fit/predict wall-clock per kind — the reference's
    5-classifier comparison (``model_training.ipynb · cells 50-56``,
    timing hooks ``shared_functions.py:312-320``) as one command.
    Optionally saves the ROC/PR/threshold PNG report per kind."""
    from real_time_fraud_detection_system_tpu.config import Config, TrainConfig
    from real_time_fraud_detection_system_tpu.features.offline import (
        compute_features_replay,
    )
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        load_transactions,
    )
    from real_time_fraud_detection_system_tpu.models.train import (
        fit_and_assess,
        fit_and_assess_sequence,
        scale_split_to_txs,
        train_delay_test_split,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    log = get_logger("compare")
    txs = load_transactions(args.data)
    cfg = Config(
        train=TrainConfig(
            delta_train_days=args.delta_train,
            delta_delay_days=args.delta_delay,
            delta_test_days=args.delta_test,
            epochs=args.epochs,
        )
    )
    # the sequence family scores from event histories, not the replayed
    # aggregate features — skip the (minutes-at-scale) replay if no
    # feature-matrix kind was requested
    features = (
        compute_features_replay(
            txs, cfg.features, start_date=cfg.data.start_date)
        if any(k != "sequence" for k in args.models) else None
    )
    dtr, dde, dte = scale_split_to_txs(
        txs, cfg.train.delta_train_days, cfg.train.delta_delay_days,
        cfg.train.delta_test_days,
    )
    train_mask, test_mask = train_delay_test_split(
        txs, delta_train=dtr, delta_delay=dde, delta_test=dte
    )
    if args.plots_dir:
        from real_time_fraud_detection_system_tpu.models.plots import (
            save_plots,
        )

        os.makedirs(args.plots_dir, exist_ok=True)
    rows = []
    for kind in args.models:
        if kind == "sequence":
            _, metrics, fit_s, pred_s, probs = fit_and_assess_sequence(
                txs, cfg, train_mask, test_mask
            )
        else:
            _, metrics, fit_s, pred_s, probs = fit_and_assess(
                txs, features, cfg, kind, train_mask, test_mask
            )
        row = {
            "model": kind,
            **{k: round(float(v), 4) for k, v in metrics.items()},
            "fit_seconds": round(fit_s, 3),
            "predict_seconds": round(pred_s, 3),
        }
        rows.append(row)
        log.info("%s", row)
        if args.plots_dir:
            save_plots(
                os.path.join(args.plots_dir, f"{kind}.png"),
                txs.tx_fraud[test_mask], probs, label=kind,
            )
    print(_json_line({"split_days": [dtr, dde, dte], "models": rows}))
    return 0


def cmd_select(args) -> int:
    """Prequential hyper-parameter selection — the reference's
    ``prequential_grid_search`` / ``model_selection_wrapper`` notebooks
    (``shared_functions.py:774-872``) as one command. ``--grid`` takes
    ``field=v1,v2,...`` pairs over ModelConfig/TrainConfig fields."""
    from real_time_fraud_detection_system_tpu.config import Config, TrainConfig
    from real_time_fraud_detection_system_tpu.features.offline import (
        compute_features_replay,
    )
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        load_transactions,
    )
    from real_time_fraud_detection_system_tpu.models.selection import (
        execution_times,
        model_selection_wrapper,
        summarize_performances,
    )
    from real_time_fraud_detection_system_tpu.utils import get_logger

    import dataclasses

    from real_time_fraud_detection_system_tpu.config import ModelConfig

    log = get_logger("select")
    # Validate the grid BEFORE the (minutes-long at scale) data load and
    # feature replay: spec syntax and field names both.
    known = {f.name for f in dataclasses.fields(ModelConfig)} | {
        f.name for f in dataclasses.fields(TrainConfig)
    }
    grid = {}
    for spec in args.grid:
        field, _, vals = spec.partition("=")
        if not vals:
            log.error("--grid expects field=v1,v2,... (got %r)", spec)
            return 2
        if field not in known:
            log.error("--grid field %r is not a ModelConfig/TrainConfig "
                      "field (known: %s)", field, ", ".join(sorted(known)))
            return 2
        parsed = []
        for v in vals.split(","):
            try:
                parsed.append(int(v))
            except ValueError:
                try:
                    parsed.append(float(v))
                except ValueError:
                    parsed.append(v)
        grid[field] = parsed
    txs = load_transactions(args.data)
    cfg = Config(train=TrainConfig(epochs=args.epochs))
    features = compute_features_replay(
        txs, cfg.features, start_date=cfg.data.start_date
    )
    rows = model_selection_wrapper(
        txs, features, cfg, args.model, grid,
        start_day_training_for_valid=args.start_valid,
        start_day_training_for_test=args.start_test,
        n_folds=args.folds,
    )
    summaries = summarize_performances(rows)
    out = {
        "model": args.model,
        "grid": grid,
        "metrics": {
            m: {
                "best_params": s.best_params,
                "validation": [round(s.validation_mean, 4),
                               round(s.validation_std, 4)],
                "test": [round(s.test_mean, 4), round(s.test_std, 4)],
            }
            for m, s in summaries.items()
        },
        "execution_times": execution_times(rows),
    }
    log.info("best by auc_roc: %s", summaries["auc_roc"].best_params)
    print(_json_line(out))
    return 0


def cmd_bench(args) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    import bench

    sys.argv = ["bench.py"] + (["--quick"] if args.quick else [])
    bench.main()
    return 0


def cmd_lint(args) -> int:
    """Project-native static analysis (tools/rtfdslint).

    The analyzer lives beside the repo, not inside the installed
    package — it lints SOURCE (including README and tests), so it only
    makes sense in a checkout. ``make lint-static`` and the tier-1 gate
    (tests/test_lint_static.py) are the two canonical callers; this
    subcommand is the operator spelling with the same exit contract
    (1 = unbaselined P0/P1 findings, 2 = usage/config error)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tools_dir = os.path.join(repo_root, "tools")
    if not os.path.isdir(os.path.join(tools_dir, "rtfdslint")):
        print("rtfds lint: tools/rtfdslint not found beside the package "
              "(installed without the repo checkout?) — run from a "
              "source tree", file=sys.stderr)
        return 2
    sys.path.insert(0, tools_dir)
    from rtfdslint.cli import main as lint_main

    # rtfdslint.cli is the AUTHORITATIVE flag surface (python -m
    # rtfdslint); this subcommand mirrors the stable subset below —
    # a new analyzer flag must be added to the lint subparser AND this
    # forwarding block to be reachable via `rtfds lint`.
    fwd = ["--root", repo_root]
    for flag in ("json", "strict", "verbose", "no_baseline",
                 "update_baseline", "list_rules", "verify_device"):
        if getattr(args, flag):
            fwd.append("--" + flag.replace("_", "-"))
    if args.reason:
        fwd += ["--reason", args.reason]
    if args.baseline:
        fwd += ["--baseline", args.baseline]
    for r in args.rule or ():
        fwd += ["--rule", r]
    return lint_main(fwd + list(args.paths))


def cmd_verify_device(args) -> int:
    """Jaxpr-level device-contract verifier (tools/rtfdsverify).

    The semantic sibling of ``rtfds lint``: instead of parsing source,
    it builds weightless template engines, loads their dispatch
    signature inventories, and proves the device-plane contracts (AOT
    coverage, z-mode exactness, donation safety, Pallas VMEM
    admission) on the traced programs — CPU-only, before any stream
    starts. Same exit contract as lint (1 = unbaselined P0/P1,
    2 = usage/config error)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tools_dir = os.path.join(repo_root, "tools")
    if not os.path.isdir(os.path.join(tools_dir, "rtfdsverify")):
        print("rtfds verify-device: tools/rtfdsverify not found beside "
              "the package (installed without the repo checkout?) — "
              "run from a source tree", file=sys.stderr)
        return 2
    sys.path.insert(0, tools_dir)
    from rtfdsverify.cli import main as verify_main

    fwd = ["--root", repo_root]
    for flag in ("json", "strict", "verbose", "no_baseline",
                 "update_baseline", "list_checks"):
        if getattr(args, flag):
            fwd.append("--" + flag.replace("_", "-"))
    if args.reason:
        fwd += ["--reason", args.reason]
    if args.baseline:
        fwd += ["--baseline", args.baseline]
    for c in args.check or ():
        fwd += ["--check", c]
    return verify_main(fwd)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtfds", description="TPU-native real-time fraud detection"
    )
    ap.add_argument("--platform", choices=["cpu", "tpu", "axon"], default=None,
                    help="force a JAX platform (default: environment)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("datagen", help="generate a synthetic transaction table")
    p.add_argument("--out", required=True)
    p.add_argument("--customers", type=int, default=5000)
    p.add_argument("--terminals", type=int, default=10000)
    p.add_argument("--days", type=int, default=245)
    p.add_argument("--radius", type=float, default=5.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--start-date", default="2025-04-01")
    p.add_argument("--pg-dsn", default=None,
                   help="also seed a live Postgres (psycopg2 DSN) — the "
                        "reference datagen container's role")
    p.add_argument("--pg-rate", type=float, default=0.0,
                   help="paced rows/s for --pg-dsn (0 = bulk)")
    p.set_defaults(fn=cmd_datagen, needs_backend=False)

    p = sub.add_parser("train", help="offline training on a generated table")
    p.add_argument("--data", required=True)
    p.add_argument("--model", default="forest",
                   choices=["logreg", "mlp", "tree", "forest", "gbt",
                            "autoencoder", "sequence"])
    p.add_argument("--out-model", required=True)
    p.add_argument("--delta-train", type=int, default=153)
    p.add_argument("--delta-delay", type=int, default=30)
    p.add_argument("--delta-test", type=int, default=30)
    p.add_argument("--epochs", type=int, default=5)
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("score", help="stream-score a table through the engine")
    p.add_argument("--data", default="",
                   help="transactions .npz (required unless --source kafka)")
    p.add_argument("--model-file", required=True)
    p.add_argument("--scorer", default="tpu", choices=["cpu", "tpu"])
    p.add_argument("--mode", default="columnar", choices=["columnar", "envelope"])
    p.add_argument("--source", default="replay",
                   choices=["replay", "kafka", "raw-table"],
                   help="replay a generated table (.npz), consume the "
                        "Debezium transaction topic from a real Kafka "
                        "cluster, or backfill from a persistent raw-"
                        "transactions table directory (--data <dir>, the "
                        "reference's stream-read of nessie.payment."
                        "transactions history)")
    p.add_argument("--from-date", default="",
                   help="raw-table backfill start day (YYYY-MM-DD, incl.)")
    p.add_argument("--to-date", default="",
                   help="raw-table backfill end day (YYYY-MM-DD, incl.)")
    p.add_argument("--bootstrap", default="localhost:9092",
                   help="Kafka bootstrap servers (--source kafka)")
    p.add_argument("--topic", default="debezium.payment.transactions")
    p.add_argument("--idle-timeout", type=float, default=0.0,
                   help="stop when the Kafka topic is idle this long "
                        "(0 = serve forever)")
    p.add_argument("--feedback-bootstrap", default="",
                   help="consume delayed fraud labels from this Kafka "
                        "cluster's feedback topic between micro-batches "
                        "(online learning, BASELINE config 4)")
    p.add_argument("--feedback-topic", default="payment.feedback")
    p.add_argument("--out", default="",
                   help="analyzed output: local directory (ParquetSink) "
                        "or s3://bucket/prefix (StoreParquetSink; "
                        "RTFDS_S3_ENDPOINT targets MinIO)")
    p.add_argument("--raw-table", default="",
                   help="also land raw transactions in a day-partitioned "
                        "parquet table at this directory (the reference's "
                        "nessie.payment.transactions)")
    p.add_argument("--batch-rows", type=int, default=4096)
    p.add_argument("--key-mode", default="direct",
                   choices=["direct", "hash", "exact"],
                   help="feature-state key→slot placement: direct "
                        "(dense serial ids, capacity >= key universe), "
                        "hash (bounded memory, colliding keys MERGE "
                        "windows), exact (tiered store: on-device key "
                        "directory, hot tier sized to the working set, "
                        "admission misses served from the count-min "
                        "sketch — README 'Feature-state playbook')")
    p.add_argument("--state-compact-every", type=int, default=0,
                   help="recency compaction cadence for --key-mode "
                        "exact: every N batches a full-table vector "
                        "pass reclaims hot-tier slots whose newest day "
                        "is older than delay + max(window) (dead "
                        "history; counted in "
                        "rtfds_feature_slots_reclaimed_total). 0 = off")
    p.add_argument("--state-hbm-budget-mb", type=float, default=0.0,
                   help="HBM budget for the whole feature state (dense "
                        "tier + directories + sketches), validated at "
                        "engine build from the static state_bytes() "
                        "accounting — fail fast instead of OOMing "
                        "mid-stream. 0 = unchecked")
    p.add_argument("--cold-store", default="",
                   help="host cold tier for --key-mode exact: directory "
                        "or s3:// url where compaction demotes evicted "
                        "keys' exact window rows instead of discarding "
                        "them; returning keys promote back "
                        "asynchronously (README 'Feature-state playbook' "
                        "§ Cold tier). Requires --state-compact-every. "
                        "Empty = off (evictions degrade to the sketch)")
    p.add_argument("--cold-promote-queue", type=int, default=64,
                   help="bounded depth of the async promoter's request "
                        "queue; a full queue drops the request and the "
                        "key re-enqueues on its next touch "
                        "(rtfds_feature_cold_promote_backlog vs the "
                        "_queue_limit gauge is the overload ladder's "
                        "cold_promote pressure input)")
    p.add_argument("--cold-segment-mb", type=float, default=4.0,
                   help="cold-store flush threshold: buffered demotions "
                        "become one durable segment (blob + CRC'd "
                        "manifest) once they exceed this many MB")
    p.add_argument("--alerts-only", action="store_true",
                   help="serve predictions only: the feature matrix "
                        "never leaves the device (the highest-throughput "
                        "mode; incompatible with --scorer cpu/feedback)")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="micro-batches in flight (2 = double-buffering; "
                        "deeper hides per-dispatch overhead)")
    p.add_argument("--coalesce-rows", type=int, default=0,
                   help="merge consecutive source polls into one device "
                        "batch up to this many rows (0 = off)")
    p.add_argument("--precompile", action="store_true",
                   help="AOT-compile the jitted step for every batch "
                        "bucket before the first poll, so no bucket's "
                        "first touch pays a mid-stream XLA compile "
                        "(rtfds_xla_recompiles_total stays 0); see also "
                        "`rtfds warmup`")
    p.add_argument("--autobatch", action="store_true",
                   help="adaptive micro-batching: move the coalesce "
                        "target between the batch buckets from observed "
                        "latency (maximize throughput, or hold "
                        "--latency-slo-ms when set)")
    p.add_argument("--latency-slo-ms", type=float, default=0.0,
                   help="p50 micro-batch latency target for the "
                        "adaptive batch controller (implies --autobatch;"
                        " 0 = no SLO, maximize throughput)")
    p.add_argument("--async-sink", action="store_true",
                   help="offload sink appends to a background writer "
                        "thread behind a bounded queue; the loop's "
                        "sink_write phase becomes an enqueue, and "
                        "checkpoints drain the queue first (exactly-"
                        "once output is preserved)")
    p.add_argument("--decode-workers", type=int, default=0,
                   help="ingest-decode worker threads: each envelope "
                        "byte-batch is sharded into contiguous slabs "
                        "decoded concurrently (bit-identical to serial "
                        "decode). 0 = auto (min(8, cores)); 1 = serial")
    p.add_argument("--prefetch-batches", type=int, default=0,
                   help="background source prefetch: poll + decode run "
                        "ahead of the loop into a bounded queue of this "
                        "many batches (offsets commit on consumption, so "
                        "checkpoint replay semantics are unchanged; "
                        "poison isolation runs unprefetched). 0 = off")
    p.add_argument("--no-fetch-overlap", action="store_true",
                   help="disable overlapped result fetch (async D2H "
                        "copies issued at dispatch time); on by default")
    p.add_argument("--sink-queue-batches", type=int, default=8,
                   help="bounded queue depth (batch results) for "
                        "--async-sink; a full queue backpressures the "
                        "loop thread")
    p.add_argument("--use-pallas", action="store_true",
                   help="serve with the fused Pallas kernels where "
                        "available (tree/forest fused featurize+score, "
                        "gbt leaf-sum, logreg featurize+score) instead "
                        "of the XLA composition")
    p.add_argument("--z-mode", default="auto",
                   choices=["auto", "f32", "bf16", "int8"],
                   help="tree-ensemble z-contraction arithmetic on the "
                        "MXU (auto = int8 on TPU, f32 elsewhere); every "
                        "mode is decision-identical by the exactness "
                        "contract — int8 is additionally bit-identical "
                        "to f32 (README § Device plane)")
    p.add_argument("--emit-threshold", type=float, default=0.0,
                   help="selective emission: transfer + persist the 15 "
                        "feature columns only for rows whose fraud "
                        "probability clears this threshold (probs land "
                        "for every row; flagged rows' features are "
                        "bit-identical to full emission, clean rows "
                        "carry zeros) — near-alerts-only throughput with "
                        "the full analyzed schema for flagged traffic "
                        "(0 = emit features for every row)")
    p.add_argument("--emit-bf16", action="store_true",
                   help="emit the analyzed feature columns in bfloat16 "
                        "(half the device->host bytes; predictions stay "
                        "f32-exact, features lose ~3 decimal digits; "
                        "incompatible with --scorer cpu / feedback)")
    p.add_argument("--reload-model-every", type=int, default=0,
                   help="hot model reload: every N batches re-read "
                        "--model-file (mtime-gated for local paths) and "
                        "swap weights into the live loop — retrain + "
                        "overwrite the artifact, no serving restart "
                        "(0 = off)")
    p.add_argument("--start-date", default="2025-04-01")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--checkpoint-full-every", type=int, default=1,
                   help="write a FULL checkpoint every K saves and "
                        "cheap deltas (changed leaves only, checksum-"
                        "chained to their base) in between; restore "
                        "composes and verifies the chain, falling back "
                        "to the last valid full on any broken link "
                        "(1 = every save full)")
    p.add_argument("--checkpoint-op-timeout", type=float, default=0.0,
                   help="per-op timeout in seconds for object-store "
                        "checkpoint PUT/GET/LIST (a hung call surfaces "
                        "as a retryable transient instead of wedging "
                        "the supervisor; 0 = wait indefinitely)")
    p.add_argument("--checkpoint-op-attempts", type=int, default=3,
                   help="retry attempts per object-store checkpoint op "
                        "(original-typed error propagation after "
                        "exhaustion; 1 = no retry)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--drain-on-sigterm", action="store_true",
                   help="SIGTERM stops the stream at the next batch "
                        "boundary instead of killing the process: "
                        "in-flight batches finish, the sink drains, "
                        "and a final checkpoint lands at that exact "
                        "frontier — the coordinated-drain leg of an "
                        "elastic fleet resize (deferred/shed rows stay "
                        "behind the committed offsets for the next "
                        "topology to re-poll)")
    p.add_argument("--resume-merge", default="",
                   help="OLD_CKPT_ROOT:P:L:REASON — adopt a drained "
                        "P-process fleet's final checkpoints (under "
                        "proc-NN/ of the root, or the root itself when "
                        "P=1, each written at L devices/process) into "
                        "this worker's --checkpoint-dir before "
                        "serving: states merge to one global "
                        "checkpoint, the stream cursor rewinds to the "
                        "fleet-wide minimum with per-old-owner resume "
                        "floors (no row lost, none double-scored), and "
                        "a resize epoch is stamped into the lineage "
                        "(`rtfds ckpt --inspect` surfaces it). "
                        "Idempotent: skipped when this worker's "
                        "lineage already has a checkpoint. Requires "
                        "--resume; not for --source kafka")
    p.add_argument("--resume-merge-cold", default="",
                   help="comma-separated old-generation cold-store "
                        "directories to consolidate into --cold-store "
                        "during --resume-merge (restore then re-homes "
                        "ownership to the new topology)")
    p.add_argument("--cms-exchange", default="",
                   help="shared directory for cross-process terminal-"
                        "sketch exchange at checkpoint boundaries: "
                        "terminal risk aggregates (NOT co-partitioned "
                        "by the customer-residue ingest split) merge "
                        "fleet-wide under the newest-day rule, while "
                        "checkpoints keep locals-only partials so "
                        "resize merges stay exact (multi-host only)")
    p.add_argument("--max-batches", type=int, default=0)
    p.add_argument("--online-lr", type=float, default=0.0)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="supervised mode: restart-on-failure with "
                        "checkpoint replay (requires --checkpoint-dir)")
    p.add_argument("--stall-timeout", type=float, default=0.0,
                   help="watchdog: restart the engine if it makes no "
                        "progress for this many seconds (supervised mode "
                        "only; 0 = off)")
    p.add_argument("--dead-letter", default="",
                   help="dead-letter queue for poison rows (*.jsonl = "
                        "JSONL file, else a parquet directory): the "
                        "supervisor bisects a crash-looping micro-batch "
                        "down to the failing rows, quarantines them here "
                        "with envelope + error metadata, and the stream "
                        "continues; inspect/replay with `rtfds dlq`")
    p.add_argument("--crash-loop-k", type=int, default=2,
                   help="consecutive supervised crashes at the SAME "
                        "resume point before the failure is reclassified "
                        "from transient to poison (bisect + dead-letter "
                        "instead of burning the restart budget)")
    p.add_argument("--restart-backoff-ms", type=float, default=0.0,
                   help="base backoff between crash-caused restarts "
                        "(doubles per restart, full jitter, 30 s cap; "
                        "0 = restart hot); stall restarts never back "
                        "off — they already waited the stall budget")
    p.add_argument("--nan-guard", action="store_true",
                   help="data-plane guard: rows producing NaN/Inf "
                        "features or scores are quarantined to "
                        "--dead-letter (reason=nonfinite) and the batch "
                        "is re-scored without them BEFORE the running "
                        "feature state is contaminated (serializes the "
                        "pipeline to depth 1 while on)")
    p.add_argument("--overload", action="store_true",
                   help="overload-survival ladder: under sustained "
                        "pressure (batch p50 vs --latency-slo-ms, "
                        "source lag, queue fill) shed optional work, "
                        "then force the largest AOT bucket + alerts-"
                        "only emission, then defer whole micro-batches "
                        "to a durable spill and replay them in order "
                        "on recovery — degrade, never die (README "
                        "section 'Overload survival playbook')")
    p.add_argument("--overload-spill", default="overload_spill",
                   help="durable spill for rung-3 deferred batches "
                        "(*.jsonl = JSONL, else a parquet directory; "
                        "idempotent by tx_id, reason=shed)")
    p.add_argument("--overload-lag-high", type=int, default=0,
                   help="source-lag normalization: this many backlogged "
                        "rows == pressure 1.0 (0 = lag signal off)")
    p.add_argument("--overload-climb-pressure", type=float, default=1.0,
                   help="climb one rung after --overload-climb-dwell "
                        "consecutive observations at or above this "
                        "normalized pressure")
    p.add_argument("--overload-descend-pressure", type=float,
                   default=0.6,
                   help="descend one rung after --overload-descend-"
                        "dwell consecutive observations at or below "
                        "this pressure (must be < climb: the gap is "
                        "the anti-flap hysteresis band)")
    p.add_argument("--overload-climb-dwell", type=int, default=3,
                   help="consecutive high-pressure observations before "
                        "each climb")
    p.add_argument("--overload-descend-dwell", type=int, default=6,
                   help="consecutive low-pressure observations before "
                        "each descent")
    p.add_argument("--overload-max-deferred", type=int, default=512,
                   help="memory bound on deferred micro-batches; at the "
                        "cap the queue head replays through scoring to "
                        "make room and the rest of the backlog stays "
                        "in the source/broker")
    p.add_argument("--devices", type=int, default=1,
                   help="serve on an N-device mesh (sharded engine: "
                        "customer-partitioned rows, all_to_all terminal "
                        "exchange); 1 = single-chip engine. In a "
                        "multi-host fleet this is the PER-PROCESS width")
    p.add_argument("--max-batch-rows", type=int, default=0,
                   help="cap assembled micro-batches at this many rows "
                        "(0 = config default 65536). The sharded "
                        "engine's per-chunk step width derives from it "
                        "(2x the balanced per-device load), so smoke/"
                        "bench fleets size their compiled step with "
                        "this knob")
    p.add_argument("--coordinator", default="",
                   help="host:port of process 0's jax.distributed "
                        "coordination service — multi-host fleets "
                        "(tools/multihost_launcher.py passes it); \"\" "
                        "with --num-processes > 1 = uncoordinated "
                        "fleet (no cross-process jax state; see the "
                        "README multi-host playbook)")
    p.add_argument("--num-processes", type=int, default=1,
                   help="total processes in the multi-host fleet; this "
                        "process serves the customer residue block "
                        "[pid*devices, (pid+1)*devices) of the "
                        "num-processes*devices global shard space")
    p.add_argument("--process-id", type=int, default=-1,
                   help="this process's id in [0, num-processes); -1 = "
                        "resolve from JAX_PROCESS_ID")
    p.add_argument("--metrics-dump", default="",
                   help="write the final registry snapshot "
                        "(/metrics.json content) to this path at run "
                        "end, success or failure — the artifact the "
                        "multihost bench/smoke assert zero recompiles "
                        "from without scraping a live port")
    p.add_argument("--trace-dir", default="",
                   help="capture a jax.profiler/TensorBoard trace of the "
                        "serving run into this directory")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve /metrics (Prometheus text), /metrics.json "
                        "and /healthz on this port while scoring "
                        "(0 = off)")
    p.add_argument("--healthz-max-batch-age", type=float, default=300.0,
                   help="/healthz goes 503 when the last finished batch "
                        "is older than this many seconds")
    p.add_argument("--healthz-max-lag-rows", type=float, default=0.0,
                   help="/healthz goes 503 when the source backlog "
                        "(rtfds_source_lag_rows) exceeds this many rows "
                        "(0 = lag check off)")
    p.add_argument("--flight-record", default="",
                   help="append one JSONL record per micro-batch (per-"
                        "phase timings, queue depth) plus checkpoint/"
                        "feedback/fault events to this file; render it "
                        "with `rtfds dashboard --flight-record`")
    p.add_argument("--flight-record-max-mb", type=float, default=256.0,
                   help="rotate the flight record when it exceeds this "
                        "many MB (previous generation kept at <path>.1; "
                        "a `rotated` event marks the trip; 0 = "
                        "unbounded)")
    p.add_argument("--trace-out", default="",
                   help="export per-batch span waterfalls as Chrome-"
                        "trace JSON to this file at run end (load in "
                        "ui.perfetto.dev or summarize with `rtfds "
                        "trace`); bounded ring buffer — safe on "
                        "unbounded streams, unlike --trace-dir")
    p.add_argument("--learn-registry", default="",
                   help="continuous learning: versioned model registry "
                        "at this path (directory or s3:// prefix). The "
                        "serving model bootstraps as v1; a streaming "
                        "learner trains a candidate on labeled feedback "
                        "(needs --feedback-bootstrap for live labels), "
                        "shadow-scores it beside the champion, and "
                        "promotes/rolls back on live precision-recall. "
                        "Inspect with `rtfds registry`")
    p.add_argument("--publish-every-labels", type=int, default=512,
                   help="publish a candidate version after this many new "
                        "labeled rows trained since the last publish")
    p.add_argument("--promote-min-labels", type=int, default=256,
                   help="labeled rows BOTH models need in the live "
                        "comparison window before promotion can fire")
    p.add_argument("--promote-margin", type=float, default=0.01,
                   help="live recall improvement the candidate must show "
                        "over the champion to be promoted")
    p.add_argument("--rollback-min-labels", type=int, default=256,
                   help="labeled rows after a promotion before the "
                        "canary verdict (hold baseline or roll back)")
    p.add_argument("--rollback-margin", type=float, default=0.05,
                   help="live recall drop below the promotion baseline "
                        "that triggers automatic rollback")
    p.set_defaults(fn=cmd_score)

    p = sub.add_parser(
        "warmup",
        help="AOT-compile the serving step for every batch bucket "
             "(fills the persistent compilation cache, then exits)")
    p.add_argument("--model-file", required=True)
    p.add_argument("--devices", type=int, default=1,
                   help="warm the N-device sharded step instead of the "
                        "single-chip one")
    p.add_argument("--online-lr", type=float, default=0.0,
                   help="match the serving flag: online SGD changes the "
                        "compiled step")
    p.add_argument("--alerts-only", action="store_true",
                   help="match the serving flag (emit_features=False "
                        "compiles a different step tail)")
    p.add_argument("--emit-threshold", type=float, default=0.0,
                   help="match the serving flag (selective emission "
                        "compiles a different step tail)")
    p.add_argument("--emit-bf16", action="store_true",
                   help="match the serving flag")
    p.add_argument("--use-pallas", action="store_true",
                   help="match the serving flag")
    p.add_argument("--z-mode", default="auto",
                   choices=["auto", "f32", "bf16", "int8"],
                   help="match the serving flag (the z-contraction mode "
                        "is part of the compiled step)")
    p.set_defaults(fn=cmd_warmup)

    p = sub.add_parser(
        "dlq",
        help="inspect / replay dead-letter-queue rows (poison quarantine)")
    p.add_argument("--path", required=True,
                   help="DLQ written by --dead-letter (JSONL file or "
                        "parquet directory)")
    p.add_argument("--limit", type=int, default=20,
                   help="max row records printed when inspecting "
                        "(0 = summary only)")
    p.add_argument("--replay", action="store_true",
                   help="re-score the quarantined rows through a fresh "
                        "engine (post-fix triage; rows that still crash "
                        "report their error and stay quarantined)")
    p.add_argument("--model-file", default="",
                   help="model artifact for --replay")
    p.set_defaults(fn=cmd_dlq, needs_backend=False)

    p = sub.add_parser(
        "ckpt",
        help="inspect / verify the checkpoint lineage (durable state)")
    p.add_argument("--path", required=True,
                   help="checkpoint directory or s3:// prefix "
                        "(the --checkpoint-dir of the serving run)")
    p.add_argument("--verify", action="store_true",
                   help="re-checksum every live checkpoint + delta "
                        "chain; exit 1 on any corruption (deploy "
                        "preflight)")
    p.add_argument("--inspect", default="",
                   help="dump one checkpoint's manifest (name or full "
                        "path, e.g. ckpt-0000000004.npz)")
    p.set_defaults(fn=cmd_ckpt, needs_backend=False)

    p = sub.add_parser(
        "registry",
        help="inspect / verify / promote / roll back the versioned "
             "model registry (continuous learning)")
    p.add_argument("--path", required=True,
                   help="registry directory or s3:// prefix (the "
                        "--learn-registry of the serving run)")
    p.add_argument("--verify", action="store_true",
                   help="re-hash every artifact against its manifest + "
                        "internal content hash; exit 1 on any corruption "
                        "(deploy preflight)")
    p.add_argument("--inspect", type=int, default=0,
                   help="dump one version's manifest (versions start "
                        "at 1)")
    p.add_argument("--promote", type=int, default=0,
                   help="verify, then move the champion pointer to this "
                        "version (manual canary override)")
    p.add_argument("--rollback", action="store_true",
                   help="pop the champion pointer back to the previous "
                        "champion (one pointer move; no artifact bytes "
                        "change)")
    p.add_argument("--publish", default="",
                   help="register a model artifact (.npz, e.g. an "
                        "offline-retrained forest/GBT) as a new "
                        "candidate version; a serving run with "
                        "--learn-registry picks it up for shadow "
                        "scoring on its next registry poll")
    p.set_defaults(fn=cmd_registry, needs_backend=False)

    p = sub.add_parser("demo",
                       help="full E2E demo: datagen → CDC → sinks → scorer")
    p.add_argument("--customers", type=int, default=500)
    p.add_argument("--terminals", type=int, default=1000)
    p.add_argument("--days", type=int, default=90)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model", default="forest",
                   choices=["logreg", "mlp", "tree", "forest", "gbt",
                            "autoencoder", "sequence"])
    p.add_argument("--model-file", default="")
    p.add_argument("--delta-train", type=int, default=45)
    p.add_argument("--delta-delay", type=int, default=10)
    p.add_argument("--delta-test", type=int, default=20)
    p.add_argument("--batch-rows", type=int, default=4096)
    p.add_argument("--out", default="")
    p.add_argument("--devices", type=int, default=1,
                   help="serve the scoring leg on an N-device mesh")
    p.set_defaults(fn=cmd_demo)

    p = sub.add_parser("query",
                       help="dashboard reports over analyzed parquet output")
    p.add_argument("--data", required=True,
                   help="analyzed output directory (ParquetSink); for "
                        "--report transactions, the raw day-partitioned "
                        "table directory (tx_date=*/ layout)")
    p.add_argument("--report", default="summary",
                   choices=["summary", "timeseries", "terminals",
                            "customers", "alerts", "drift",
                            "transactions"])
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--bucket", default="day", choices=["hour", "day"])
    p.set_defaults(fn=cmd_query, needs_backend=False)

    p = sub.add_parser(
        "sql",
        help="ad-hoc SQL over analyzed parquet output (Trino's role, "
             "in-process; table name: analyzed)",
    )
    p.add_argument("--data", required=True,
                   help="analyzed output directory (ParquetSink)")
    p.add_argument("query", help="SQL, e.g. \"SELECT COUNT(*) FROM "
                                 "analyzed WHERE prediction >= 0.5\"")
    p.add_argument("--limit", type=int, default=1000,
                   help="max rows printed (default 1000; 0 = unlimited)")
    p.set_defaults(fn=cmd_sql, needs_backend=False)

    p = sub.add_parser(
        "import-model",
        help="convert the reference's pickled artifacts "
             "(trained_model.pkl [+ scaler.pkl]) into the npz model "
             "format — existing reference models serve on TPU unchanged",
    )
    p.add_argument("--model-pkl", required=True,
                   help="pickled sklearn/xgboost classifier "
                        "(the reference's trained_model.pkl; unpickling "
                        "executes code — trusted artifacts only)")
    p.add_argument("--scaler-pkl", default="",
                   help="joblib StandardScaler (the reference's "
                        "scaler.pkl); omit for identity scaling")
    p.add_argument("--out-model", required=True)
    p.set_defaults(fn=cmd_import_model, needs_backend=False)

    p = sub.add_parser(
        "connectors",
        help="register the Debezium Postgres source connector "
             "(the reference's make connectors)",
    )
    p.add_argument("--connect-url", default="http://localhost:8083")
    p.add_argument("--name", default="pg-src-connector")
    p.add_argument("--db-host", default="postgres")
    p.add_argument("--db-port", type=int, default=5432)
    p.add_argument("--db-user", default="postgres")
    p.add_argument("--db-password", default="postgres")
    p.add_argument("--db-name", default="postgres")
    p.add_argument("--schema", default="payment")
    p.add_argument("--topic-prefix", default="debezium")
    p.add_argument("--timeout", type=float, default=10.0)
    p.set_defaults(fn=cmd_connectors, needs_backend=False)

    p = sub.add_parser(
        "dashboard",
        help="render the static-HTML ops dashboard (the Superset role)",
    )
    p.add_argument("--data", default="",
                   help="analyzed output directory (ParquetSink)")
    p.add_argument("--flight-record", default="",
                   help="render the ops-health view from a flight-record "
                        "JSONL (per-phase latency series + event strip) "
                        "instead of the analyzed-output view")
    p.add_argument("--out", default="dashboard.html")
    p.add_argument("--threshold", type=float, default=0.5)
    p.add_argument("--top-k", type=int, default=10)
    p.add_argument("--bucket", default="day", choices=["hour", "day"])
    p.add_argument("--title", default=None,
                   help="page title (default set in io.dashboard)")
    p.set_defaults(fn=cmd_dashboard, needs_backend=False)

    p = sub.add_parser(
        "trace",
        help="summarize an exported span trace (critical path, top-K "
             "slowest spans, recompiles, ASCII waterfall)",
    )
    p.add_argument("--trace", required=True,
                   help="Chrome-trace JSON from `rtfds score "
                        "--trace-out`, GET /trace, or make trace-demo")
    p.add_argument("--top-k", type=int, default=10,
                   help="slowest batches/spans to list")
    p.add_argument("--batch", default="",
                   help="trace id (e.g. b00000042) to render the "
                        "waterfall for (default: the slowest batch)")
    p.add_argument("--json", action="store_true",
                   help="print the machine-readable summary as one "
                        "JSON line instead of the text report")
    p.set_defaults(fn=cmd_trace, needs_backend=False)

    p = sub.add_parser(
        "compare",
        help="fit several model kinds on one split; metrics + timings",
    )
    p.add_argument("--data", required=True)
    p.add_argument("--models", nargs="+",
                   default=["logreg", "tree", "forest", "gbt", "mlp"],
                   choices=["logreg", "mlp", "tree", "forest", "gbt",
                            "autoencoder", "sequence"])
    p.add_argument("--delta-train", type=int, default=153)
    p.add_argument("--delta-delay", type=int, default=30)
    p.add_argument("--delta-test", type=int, default=30)
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--plots-dir", default="",
                   help="write <kind>.png ROC/PR/threshold reports here")
    p.set_defaults(fn=cmd_compare)

    p = sub.add_parser(
        "select",
        help="prequential hyper-parameter selection (validation+test sweeps)",
    )
    p.add_argument("--data", required=True)
    p.add_argument("--model", default="tree",
                   choices=["logreg", "mlp", "tree", "forest", "gbt"])
    p.add_argument("--grid", nargs="+", required=True,
                   metavar="FIELD=V1,V2",
                   help="e.g. tree_max_depth=2,4,8 epochs=3,5")
    p.add_argument("--start-valid", type=int, required=True,
                   help="training-start day for the validation sweep")
    p.add_argument("--start-test", type=int, required=True,
                   help="training-start day for the test sweep (later; "
                        "windows stay disjoint per the wrapper contract)")
    p.add_argument("--folds", type=int, default=4)
    p.add_argument("--epochs", type=int, default=3)
    p.set_defaults(fn=cmd_select)

    p = sub.add_parser("bench", help="run the benchmark harness")
    p.add_argument("--quick", action="store_true")
    p.set_defaults(fn=cmd_bench, needs_backend=False)

    p = sub.add_parser(
        "lint",
        help="static analysis: recompile hazards, thread races, "
             "exception taxonomy, metric drift (tools/rtfdslint)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--strict", action="store_true",
                   help="P2 findings also fail the gate")
    p.add_argument("--verbose", action="store_true",
                   help="also list suppressed/baselined findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--update-baseline", action="store_true",
                   help="absorb current P0/P1 findings (needs --reason)")
    p.add_argument("--reason", default="",
                   help="reason recorded on new baseline entries")
    p.add_argument("--baseline", default="",
                   help="override the baseline file path")
    p.add_argument("--rule", action="append",
                   help="run only this rule (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--verify-device", action="store_true",
                   help="also run the jaxpr-level device-contract "
                        "verifier (tools/rtfdsverify) and fold its "
                        "findings into the report/gate (--json carries "
                        "them under \"verifier\")")
    p.set_defaults(fn=cmd_lint, needs_backend=False)

    p = sub.add_parser(
        "verify-device",
        help="device-contract verifier: prove AOT coverage, z-mode "
             "exactness, donation safety and Pallas VMEM admission on "
             "the traced step programs (tools/rtfdsverify; CPU-only, "
             "no weights)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--strict", action="store_true",
                   help="P2 findings also fail the gate")
    p.add_argument("--verbose", action="store_true",
                   help="also list baselined findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file")
    p.add_argument("--update-baseline", action="store_true",
                   help="absorb current P0/P1 findings (needs --reason)")
    p.add_argument("--reason", default="",
                   help="reason recorded on new baseline entries")
    p.add_argument("--baseline", default="",
                   help="override the baseline file path")
    p.add_argument("--check", action="append",
                   help="run only this check (repeatable)")
    p.add_argument("--list-checks", action="store_true",
                   help="print the check catalog and exit")
    p.set_defaults(fn=cmd_verify_device, needs_backend=False)

    args = ap.parse_args(argv)
    _platform_setup(args.platform,
                    getattr(args, "needs_backend", True))
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
