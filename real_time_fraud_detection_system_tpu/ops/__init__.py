from real_time_fraud_detection_system_tpu.ops.hashing import (  # noqa: F401
    hash_u32,
    multi_hash,
    slot_of,
)
from real_time_fraud_detection_system_tpu.ops.windows import (  # noqa: F401
    WindowState,
    init_window_state,
    query_windows,
    update_windows,
)
from real_time_fraud_detection_system_tpu.ops.cms import (  # noqa: F401
    CountMinSketch,
    cms_init,
    cms_query,
    cms_update,
)
from real_time_fraud_detection_system_tpu.ops.dedup import (  # noqa: F401
    latest_wins_mask,
    latest_wins_mask_np,
)
# ops.pallas_forest is deliberately NOT re-exported: like ops.pallas_kernels
# it pulls in jax.experimental.pallas(+tpu), which stays a lazy, opt-in
# import behind RuntimeConfig.use_pallas (see runtime/engine.py).
