"""Exact on-device key directory: open-addressing uint32 key→slot table.

The dense ``WindowState`` tier historically coupled its capacity to the
key universe: ``key_mode="direct"`` needs capacity ≥ max(key) + 1 (a
10M-customer corpus is ~5 GB of HBM window state before donation
double-buffering), while ``key_mode="hash"`` silently MERGES colliding
keys' windows. This module decouples the two: the hot tier is sized to
the *active working set* (``slot_capacity`` rows) and an open-addressing
hash directory (``dir_capacity`` = 2× slots → load factor ≤ 0.5) maps
keys to slots *exactly* — a key either owns a private slot or it misses
admission and is served from the count-min sketch tier, but two keys
never share window state.

Everything is vectorized, fixed-shape and jit/shard_map-friendly:

- **probing** is double hashing over a power-of-two table
  (``h1 + j·(h2|1)``, an odd stride walks the whole table) with a FIXED
  probe depth — lookups scan all P candidate positions and pick the
  match, so there is no early-exit data dependence and deleted entries
  need no tombstones;
- **batched insert** resolves scatter races with claim rounds: round j's
  writers scatter-min their key into still-empty positions, re-read, and
  the losers continue to probe j+1. Batch duplicates of one new key all
  win the same entry; a scatter-min of the row index picks ONE owner to
  pop the free-slot stack, so one key costs one slot;
- **the free-slot stack** (``free``/``free_top``) is the admission
  bound: when it runs dry the claimed entry is rolled back and the row
  reports ``admitted=False`` — a full hot tier degrades to the sketch
  tier instead of clobbering a live slot;
- **reclaim** pushes dead slots back on the stack and vacates their
  directory entries (no tombstones needed — see probing above), which is
  what the engine's recency compaction pass calls.

Sentinel note: ``EMPTY_KEY`` (0xFFFFFFFF) is reserved; a real key equal
to it is remapped to 0xFFFFFFFE (``fold_key`` output collides with that
one value in 2^32 — the same order of aliasing the 32-bit fold already
accepts).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.ops.hashing import hash_u32

# np scalar, NOT jnp: a module-level jnp constant would run a JAX
# computation at import time, which breaks jax.distributed.initialize
# in multiprocess workers (same idiom as ops/hashing._M1/_M2)
EMPTY_KEY = np.uint32(0xFFFFFFFF)


class KeyDirectory(NamedTuple):
    """Pytree: the directory + the free-slot stack (all HBM-resident).

    Invariant: an entry is either vacant (``keys[e] == EMPTY_KEY`` and
    ``slots[e] == -1``) or owns exactly one live slot; every slot id is
    either owned by exactly one entry or sits on the free stack
    (``free[:free_top]``)."""

    keys: jnp.ndarray  # uint32 [dir_cap]; EMPTY_KEY = vacant
    slots: jnp.ndarray  # int32 [dir_cap]; slot owned by the entry, -1 vacant
    free: jnp.ndarray  # int32 [slot_cap]; free[:free_top] = free slot ids
    free_top: jnp.ndarray  # int32 [] — live height of the free stack

    @property
    def dir_capacity(self) -> int:
        return int(self.keys.shape[0])

    @property
    def slot_capacity(self) -> int:
        return int(self.free.shape[0])


def init_keydir(dir_capacity: int, slot_capacity: int) -> KeyDirectory:
    assert dir_capacity & (dir_capacity - 1) == 0, \
        "dir_capacity must be a power of 2"
    assert slot_capacity <= dir_capacity, \
        "more slots than directory entries can never all be reachable"
    return KeyDirectory(
        keys=jnp.full((dir_capacity,), EMPTY_KEY, dtype=jnp.uint32),
        slots=jnp.full((dir_capacity,), -1, dtype=jnp.int32),
        # low slot ids pop first (free[top-1] is the next grant)
        free=jnp.arange(slot_capacity - 1, -1, -1, dtype=jnp.int32),
        free_top=jnp.int32(slot_capacity),
    )


def _canon(key: jnp.ndarray) -> jnp.ndarray:
    key = key.astype(jnp.uint32)
    return jnp.where(key == EMPTY_KEY, jnp.uint32(0xFFFFFFFE), key)


def _probe_positions(key: jnp.ndarray, dir_cap: int,
                     n_probes: int) -> jnp.ndarray:
    """[B] keys → [B, P] probe positions (double hashing, odd stride)."""
    h1 = hash_u32(key, seed=0)
    h2 = hash_u32(key, seed=1) | jnp.uint32(1)
    j = jnp.arange(n_probes, dtype=jnp.uint32)
    pos = (h1[:, None] + j[None, :] * h2[:, None]) \
        & jnp.uint32(dir_cap - 1)
    return pos.astype(jnp.int32)


def lookup_slots(
    kd: KeyDirectory,
    key: jnp.ndarray,  # uint32 [B]
    valid: jnp.ndarray,  # bool [B]
    n_probes: int = 8,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Read-only probe: (slot [B] int32, hit [B] bool). Missing/invalid
    rows return slot 0 with ``hit=False`` — mask before scattering."""
    key = _canon(key)
    pos = _probe_positions(key, kd.dir_capacity, n_probes)  # [B, P]
    found = kd.keys[pos] == key[:, None]  # [B, P]
    pidx = jnp.argmax(found, axis=1)
    entry = jnp.take_along_axis(pos, pidx[:, None], axis=1)[:, 0]
    slot = kd.slots[entry]
    hit = valid & found.any(axis=1) & (slot >= 0)
    return jnp.where(hit, slot, 0), hit


def init_stacked_keydir(dir_capacity: int, slot_capacity: int,
                        n_shards: int) -> KeyDirectory:
    """``n_shards`` independent per-shard directories as ONE pytree with
    a leading shard axis on every leaf (``keys``/``slots``
    [n, dir_cap], ``free`` [n, slot_cap], ``free_top`` [n]) — the
    layout the sharded engine places over the mesh (one directory per
    device, sharded on axis 0). Inside ``shard_map`` each device
    squeezes the axis off and runs the plain single-shard ops."""
    kd = init_keydir(dir_capacity, slot_capacity)
    return KeyDirectory(
        keys=jnp.broadcast_to(kd.keys[None], (n_shards,) + kd.keys.shape),
        slots=jnp.broadcast_to(kd.slots[None],
                               (n_shards,) + kd.slots.shape),
        free=jnp.broadcast_to(kd.free[None], (n_shards,) + kd.free.shape),
        free_top=jnp.full((n_shards,), slot_capacity, dtype=jnp.int32),
    )


def lookup_slots_stacked(
    kd: KeyDirectory,  # STACKED layout: [n_shards, ...] leaves
    owner: jnp.ndarray,  # int32 [B] — shard that owns each row's key
    key: jnp.ndarray,  # uint32 [B]
    valid: jnp.ndarray,  # bool [B]
    n_probes: int = 8,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Read-only probe into a stacked directory: row i probes shard
    ``owner[i]``'s table. Returns (local_slot [B] int32, hit [B] bool) —
    the slot is LOCAL to the owner shard (callers compose the global
    table row as ``owner * slot_capacity + slot``). Off the hot path
    (feedback); GSPMD inserts the cross-shard gathers."""
    key = _canon(key)
    dir_cap = int(kd.keys.shape[1])
    pos = _probe_positions(key, dir_cap, n_probes)  # [B, P]
    found = kd.keys[owner[:, None], pos] == key[:, None]  # [B, P]
    pidx = jnp.argmax(found, axis=1)
    entry = jnp.take_along_axis(pos, pidx[:, None], axis=1)[:, 0]
    slot = kd.slots[owner, entry]
    hit = valid & found.any(axis=1) & (slot >= 0)
    return jnp.where(hit, slot, 0), hit


def admit_slots(
    kd: KeyDirectory,
    key: jnp.ndarray,  # uint32 [B]
    valid: jnp.ndarray,  # bool [B]
    n_probes: int = 8,
) -> Tuple[KeyDirectory, jnp.ndarray, jnp.ndarray]:
    """Lookup-or-insert a batch of keys; the hot path's admission op.

    Returns ``(kd', slot [B] int32, admitted [B] bool)``. A row is
    admitted iff its key already owned a slot or could claim a directory
    entry within ``n_probes`` probes AND a free slot remained; batch
    duplicates of one key share a single slot. Non-admitted rows return
    slot 0 and MUST be masked out of dense-tier scatters (the caller
    serves them from the sketch tier).
    """
    dir_cap = kd.dir_capacity
    slot_cap = kd.slot_capacity
    key = _canon(key)
    B = int(key.shape[0])
    pos = _probe_positions(key, dir_cap, n_probes)  # [B, P]
    keys = kd.keys
    # FULL-depth lookup FIRST, claims only for keys with no existing
    # entry: reclaim_entries can vacate a position on a live key's
    # probe-path PREFIX, and a claim-as-you-probe insert would grab that
    # vacancy before ever reaching the key's real entry — duplicating
    # the key, resetting its window history, and leaking its old slot.
    # (lookup_slots scans all P positions for the same reason; this is
    # the insert-side half of the no-tombstones argument.)
    found = keys[pos] == key[:, None]  # [B, P]
    pidx = jnp.argmax(found, axis=1)
    hit0 = found.any(axis=1) & valid
    entry = jnp.where(
        hit0, jnp.take_along_axis(pos, pidx[:, None], axis=1)[:, 0], 0)
    placed = ~valid | hit0
    claimed = jnp.zeros(B, dtype=bool)  # matched via a claim made NOW
    for j in range(n_probes):
        p = pos[:, j]
        cur = keys[p]
        # batch duplicates of a key claimed in an EARLIER round match
        # here (pre-call lookup could not see that claim)
        hit = (~placed) & (cur == key)
        entry = jnp.where(hit, p, entry)
        placed = placed | hit
        # Claim attempt: scatter-min our key into still-empty positions;
        # among racing writers the smallest key wins, losers re-probe.
        want = (~placed) & (cur == EMPTY_KEY)
        cand = jnp.where(want, key, EMPTY_KEY)
        keys = keys.at[p].min(cand)
        won = want & (keys[p] == key)
        entry = jnp.where(won, p, entry)
        claimed = claimed | won
        placed = placed | won
    # One owner per newly claimed entry (batch duplicates of one new key
    # all carry claimed=True on the same entry; exactly one pops a slot).
    rows = jnp.arange(B, dtype=jnp.int32)
    owner = jnp.full((dir_cap,), B, jnp.int32).at[
        jnp.where(claimed, entry, dir_cap)].min(rows, mode="drop")
    new = claimed & (owner[entry] == rows)
    # Grant free slots to owners in row order; owners past the stack
    # height roll their claim back (their duplicates then miss too).
    rank = jnp.cumsum(new.astype(jnp.int32)) - 1  # [B]
    avail = kd.free_top
    has = new & (rank < avail)
    slot_new = kd.free[jnp.clip(avail - 1 - rank, 0, slot_cap - 1)]
    slots = kd.slots.at[jnp.where(has, entry, dir_cap)].set(
        slot_new, mode="drop")
    revert = new & ~(rank < avail)
    keys = keys.at[jnp.where(revert, entry, dir_cap)].set(
        EMPTY_KEY, mode="drop")
    free_top = avail - jnp.sum(has.astype(jnp.int32))
    # Final resolution covers every case at once: hits, fresh grants,
    # batch duplicates of grants, rolled-back claims (keys[entry] no
    # longer matches), and rows that never placed (probe overflow).
    slot = slots[entry]
    admitted = placed & valid & (keys[entry] == key) & (slot >= 0)
    return (
        KeyDirectory(keys=keys, slots=slots, free=kd.free,
                     free_top=free_top),
        jnp.where(admitted, slot, 0),
        admitted,
    )


def reclaim_entries(
    kd: KeyDirectory,
    dead_entry: jnp.ndarray,  # bool [dir_cap] — entries to vacate
) -> Tuple[KeyDirectory, jnp.ndarray, jnp.ndarray]:
    """Vacate ``dead_entry`` positions and push their slots back on the
    free stack. Returns ``(kd', dead [dir_cap] bool, n_reclaimed [])``
    — ``dead`` is the mask restricted to live entries, which the caller
    uses to reset the reclaimed slots' window rows."""
    slot_cap = kd.slot_capacity
    dead = dead_entry & (kd.slots >= 0)
    rank = jnp.cumsum(dead.astype(jnp.int32)) - 1
    push = jnp.where(dead, kd.free_top + rank, slot_cap)
    free = kd.free.at[push].set(kd.slots, mode="drop")
    n = jnp.sum(dead.astype(jnp.int32))
    return (
        KeyDirectory(
            keys=jnp.where(dead, EMPTY_KEY, kd.keys),
            slots=jnp.where(dead, -1, kd.slots),
            free=free,
            free_top=kd.free_top + n,
        ),
        dead,
        n,
    )


def occupied_slots(kd: KeyDirectory) -> jnp.ndarray:
    """Live slot count (int32 scalar): slots granted and not reclaimed."""
    return jnp.int32(kd.slot_capacity) - kd.free_top
