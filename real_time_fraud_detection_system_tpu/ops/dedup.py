"""Latest-wins dedup — the MERGE/ROW_NUMBER upsert semantics on device.

The reference dedups every micro-batch with
``ROW_NUMBER() OVER (PARTITION BY tx_id ORDER BY timestamp DESC)`` and keeps
rank 1 before a MERGE (``kafka_s3_sink_transactions.py:173-190``). Here the
same semantics are a mask op: keep, for each key, the row with the greatest
timestamp — ties broken by latest batch position (Kafka log order), exactly
like a descending sort on (timestamp, offset).

Two implementations:
- ``latest_wins_mask``: jnp, static-shape, jit/shard_map-safe (sort-based,
  O(B log B)) — for fully on-device pipelines;
- ``latest_wins_mask_np``: NumPy int64 host-side — used by the ingest path
  before device_put (tx_ids are 64-bit there).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def latest_wins_mask(
    key: jnp.ndarray,  # int32/uint32 [B]
    ts: jnp.ndarray,  # int32 [B] — ordering timestamp
    valid: jnp.ndarray,  # bool [B]
) -> jnp.ndarray:
    """bool [B]: True where the row is the latest version of its key.

    Invalid rows are never selected. Static shapes only.
    """
    b = key.shape[0]
    pos = jnp.arange(b, dtype=jnp.int32)
    k = key.astype(jnp.uint32)
    # Invalid rows sort to the front of their key group (minimal ts) so a
    # valid row, if any, is always the group's last element.
    ts_eff = jnp.where(valid, ts, jnp.iinfo(jnp.int32).min)
    order = jnp.lexsort((pos, ts_eff, k))  # ascending; last of key group wins
    k_sorted = k[order]
    is_last = jnp.concatenate([k_sorted[1:] != k_sorted[:-1], jnp.ones(1, bool)])
    win_sorted = is_last & valid[order]
    mask = jnp.zeros(b, dtype=bool).at[order].set(win_sorted)
    return mask


def latest_wins_mask_np(
    key: np.ndarray, ts: np.ndarray, valid: np.ndarray | None = None
) -> np.ndarray:
    """NumPy version for host-side ingest (int64 keys)."""
    b = len(key)
    pos = np.arange(b)
    if valid is None:
        valid = np.ones(b, dtype=bool)
    k = np.where(valid, key, np.int64(np.iinfo(np.int64).min))
    order = np.lexsort((pos, ts, k))
    k_sorted = k[order]
    is_last = np.concatenate([k_sorted[1:] != k_sorted[:-1], [True]])
    win_sorted = is_last & (k_sorted != np.iinfo(np.int64).min)
    mask = np.zeros(b, dtype=bool)
    mask[order] = win_sorted
    return mask


def latest_wins_mask_host(key: np.ndarray, ts: np.ndarray) -> np.ndarray:
    """Host-side dispatcher: the C++ O(n) hash pass when the native unit
    is available (``native/hostprep.cc``), else :func:`latest_wins_mask_np`
    — bit-identical either way (differential-pinned,
    ``tests/test_native.py``). The single entry point both serving
    engines use, so dedup semantics cannot diverge between them."""
    from real_time_fraud_detection_system_tpu.core import native

    if native.hostprep_available():
        return native.latest_wins_keep(key, ts)
    return latest_wins_mask_np(key, ts)
