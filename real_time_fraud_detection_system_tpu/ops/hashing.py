"""Integer hashing ops in pure jnp uint32 arithmetic.

Used for key→slot placement in the HBM-resident feature tables and for the
count-min sketch's row hashes. TPU has no native 64-bit int path worth using
here; a finalizer-style 32-bit mixer (splitmix/murmur-finale family) gives
good avalanche with 6 VPU ops per key.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x7FEB352D)
_M2 = np.uint32(0x846CA68B)


def hash_u32(x: jnp.ndarray, seed: int = 0) -> jnp.ndarray:
    """Mix uint32 keys (vectorized). Distinct seeds give independent hashes."""
    h = x.astype(jnp.uint32) ^ jnp.uint32(0x9E3779B9 * (seed + 1) & 0xFFFFFFFF)
    h = h ^ (h >> 16)
    h = h * _M1
    h = h ^ (h >> 15)
    h = h * _M2
    h = h ^ (h >> 16)
    return h


def slot_of(key: jnp.ndarray, capacity: int, seed: int = 0) -> jnp.ndarray:
    """Key → table slot in [0, capacity). capacity must be a power of two."""
    assert capacity & (capacity - 1) == 0, "capacity must be a power of 2"
    return (hash_u32(key, seed) & jnp.uint32(capacity - 1)).astype(jnp.int32)


def multi_hash(key: jnp.ndarray, depth: int, width: int) -> jnp.ndarray:
    """[B] keys → [depth, B] independent column indices in [0, width)."""
    assert width & (width - 1) == 0, "width must be a power of 2"
    cols = [
        (hash_u32(key, seed=d) & jnp.uint32(width - 1)).astype(jnp.int32)
        for d in range(depth)
    ]
    return jnp.stack(cols, axis=0)
