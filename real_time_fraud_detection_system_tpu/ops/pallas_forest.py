"""Fused Pallas kernel for tree-ensemble (GEMM-form) inference.

Runs the whole per-tree chain of ``models/forest.py::gemm_leaf_sum``

    proj = x @ sel[t]   (f32, HIGHEST — decision-exact, see forest.py)
    d    = proj <= thresh[t]          (bf16: 0/1, exact)
    z    = d @ path[t]                (bf16×bf16→f32 MXU, exact: |z| ≤ depth)
    acc += Σ_l leaf_val[t] where |z − target[t]| < 0.5

inside VMEM, tiling rows on the grid's first axis and streaming tree blocks
on the second; only ``x`` (60 B/row) is read from and the leaf-sum (4 B/row)
written to HBM.  Covers the role of the reference's sklearn
``model.predict_proba`` inside ``scale_and_predict_udf``
(``pyspark/scripts/fraud_detection.py:183-195``).

**Measured verdict (v5e, round 4): XLA wins.** At the flagship point
(T=100, depth 8) the plain XLA composition runs 10.7M rows/s classify-only
at 1M-row batches vs 6.6M for this kernel (8.0M vs 5.7M at 262k) — XLA's
automatic fusion of the three contractions is already intermediate-free and
schedules the VPU-bound compare/select chain better than the hand-rolled
tree loop.  The kernel therefore stays an **opt-in**
(``RuntimeConfig.use_pallas``) proof of hand-fusibility and a template for
deeper fusions — the same conclusion as the logreg featurize+score kernel
(``ops/pallas_kernels.py``), now established for the flagship model, with
the measurement recorded in ``bench.py`` (``detail.pallas_forest``).

Numerics match ``gemm_leaf_sum``'s documented mixed-precision contract: every
branch decision is bit-identical to sklearn on f32 inputs (proj in f32
HIGHEST against f32-rounded-down thresholds), the z counts are small exact
integers in bf16, and only the final f32 accumulation order differs (per-tree
sequential here) — a ≤1-ulp-scale difference on the bagged mean.

On non-TPU backends the kernel runs in interpreter mode (slow, exact) so CPU
tests validate the identical code path the TPU compiles.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if TYPE_CHECKING:  # type-only: models.forest imports would cycle through
    from real_time_fraud_detection_system_tpu.models.forest import (
        GemmEnsemble,
    )


from real_time_fraud_detection_system_tpu.ops.pallas_kernels import _on_tpu


def _ceil_to(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


# Trees per grid step: amortizes per-step grid/DMA overhead while keeping the
# double-buffered table blocks (2 × TT·Ip·Lp bf16) small next to ~16MB VMEM.
TREE_BLOCK = 10


class PallasForest(NamedTuple):
    """``GemmEnsemble`` re-padded to MXU tiles (I, L → ×128; F → ×8;
    T → ×TREE_BLOCK).

    Padding is inert by construction: fake internal nodes carry ``thresh=+inf``
    (decision always 1) and all-zero ``path`` rows; fake leaves carry
    ``target=1e9`` (never matched) and ``leaf_val=0``; fake trees are all of
    the above, so they contribute exactly 0 to the leaf sum.
    """

    sel: jnp.ndarray  # f32 [Tp, Fp, Ip] one-hot feature selector
    thresh: jnp.ndarray  # f32 [Tp, 1, Ip] (+inf padding)
    path: jnp.ndarray  # bf16 [Tp, Ip, Lp] ±1/0 requirement matrix
    target: jnp.ndarray  # f32 [Tp, 1, Lp] (#left-required; 1e9 padding)
    leaf_val: jnp.ndarray  # f32 [Tp, 1, Lp]
    n_trees: int  # REAL tree count (bagging divisor); static


def to_pallas(g: GemmEnsemble) -> PallasForest:
    """Pad a compiled ``GemmEnsemble`` into the kernel's tile layout.

    Pure jnp pads, so it runs eagerly (one-time conversion) AND inside a
    jitted step — the engine derives the tables from its LIVE params every
    step (a few µs of pad writes next to ms of batch work), which keeps a
    checkpoint restore that overwrites ``state.params`` in-place serving
    the restored trees, never stale build-time copies.
    """
    t, f, i = g.sel.shape
    l = g.path.shape[2]
    tp = _ceil_to(int(t), TREE_BLOCK)
    fp = _ceil_to(int(f), 8)
    ip = _ceil_to(int(i), 128)
    lp = _ceil_to(int(l), 128)
    return PallasForest(
        sel=jnp.pad(g.sel, ((0, tp - t), (0, fp - f), (0, ip - i))),
        thresh=jnp.pad(g.thresh, ((0, tp - t), (0, ip - i)),
                       constant_values=jnp.inf)[:, None, :],
        path=jnp.pad(g.path, ((0, tp - t), (0, ip - i), (0, lp - l))
                     ).astype(jnp.bfloat16),
        target=jnp.pad(g.target, ((0, tp - t), (0, lp - l)),
                       constant_values=1e9)[:, None, :],
        leaf_val=jnp.pad(g.leaf_val, ((0, tp - t), (0, lp - l)))[:, None, :],
        n_trees=int(t),
    )


def pallas_table_bytes(g: GemmEnsemble) -> int:
    """TOTAL padded table footprint (HBM-resident; diagnostics)."""
    t = g.sel.shape[0]
    return (_ceil_to(int(t), TREE_BLOCK) // TREE_BLOCK) * pallas_block_bytes(g)


def pallas_block_bytes(g: GemmEnsemble) -> int:
    """Padded table bytes of ONE tree block — the VMEM-residency gate.

    The kernel streams (TREE_BLOCK, …) table blocks through VMEM (double-
    buffered), so per-step residency scales with the BLOCK, not the whole
    ensemble: T=100 depth-8 totals ~14 MB of tables in HBM but only
    ~1.5 MB/block in flight.
    """
    f, i = g.sel.shape[1:]
    l = g.path.shape[2]
    fp, ip, lp = _ceil_to(int(f), 8), _ceil_to(int(i), 128), _ceil_to(int(l), 128)
    return TREE_BLOCK * (fp * ip * 4 + ip * lp * 2 + lp * 8 + ip * 4)


def _leaf_sum_kernel(
    x_ref,  # f32 [Bt, Fp]
    sel_ref,  # f32 [TT, Fp, Ip]
    thresh_ref,  # f32 [TT, 1, Ip]
    path_ref,  # bf16 [TT, Ip, Lp]
    target_ref,  # f32 [TT, 1, Lp]
    leaf_ref,  # f32 [TT, 1, Lp]
    out_ref,  # f32 [Bt, 1]
    *,
    tree_block: int,
):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]
    hi = jax.lax.Precision.HIGHEST

    # Rolled loop, not a static unroll: one set of [Bt, Ip/Lp] intermediate
    # buffers is reused across the block's trees (an unroll keeps all
    # tree_block sets live at once — measured 17MB of scoped VMEM at
    # Bt=2048·TT=10, over the 16MB limit).
    def body(k, acc):
        proj = jnp.dot(x, sel_ref[k], precision=hi)  # [Bt, Ip] f32
        d = (proj <= thresh_ref[k]).astype(jnp.bfloat16)
        z = jnp.dot(d, path_ref[k], preferred_element_type=jnp.float32)
        # single fused select→reduce pass (VPU-bound chain: one traversal
        # of [Bt, Lp] instead of onehot-cast + mul + reduce)
        contrib = jnp.sum(
            jnp.where(jnp.abs(z - target_ref[k]) < 0.5, leaf_ref[k], 0.0),
            axis=1, keepdims=True)
        return acc + contrib

    acc0 = jnp.zeros((x.shape[0], 1), jnp.float32)
    out_ref[:] += jax.lax.fori_loop(0, tree_block, body, acc0)


def pallas_leaf_sum(
    pf: PallasForest,
    x: jnp.ndarray,
    block_rows: int = 2048,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """[B, F] → Σ_t leaf value [B] — the fused-kernel ``gemm_leaf_sum``."""
    if interpret is None:
        interpret = not _on_tpu()
    b, f = x.shape
    tp, fp, ip = pf.sel.shape
    lp = pf.path.shape[2]
    tt = TREE_BLOCK
    if f < fp:
        x = jnp.pad(x, ((0, 0), (0, fp - f)))
    # Split b over the fewest blocks of ≤ block_rows, each the smallest ×8
    # size that covers its share — padding stays < 8·n_blocks rows instead
    # of rounding b up to a full block_rows multiple.
    nb = max(1, -(-b // block_rows))
    bt = _ceil_to(-(-b // nb), 8)
    bp = nb * bt
    if bp != b:  # pad rows; padded rows score garbage and are sliced off
        x = jnp.pad(x, ((0, bp - b), (0, 0)))
    grid = (nb, tp // tt)

    table = lambda *dims: pl.BlockSpec(  # noqa: E731
        (tt, *dims), lambda i, t: (t, 0, 0), memory_space=pltpu.VMEM,
    )
    out = pl.pallas_call(
        lambda *refs: _leaf_sum_kernel(*refs, tree_block=tt),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, fp), lambda i, t: (i, 0),
                         memory_space=pltpu.VMEM),
            table(fp, ip), table(1, ip), table(ip, lp),
            table(1, lp), table(1, lp),
        ],
        out_specs=pl.BlockSpec((bt, 1), lambda i, t: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        interpret=interpret,
    )(x, pf.sel, pf.thresh, pf.path, pf.target, pf.leaf_val)
    return out[:b, 0]


def pallas_predict_proba(
    pf: PallasForest, x: jnp.ndarray, **kw
) -> jnp.ndarray:
    """[B, F] → fraud probability [B] (bagging mean over real trees)."""
    return pallas_leaf_sum(pf, x, **kw) / pf.n_trees
