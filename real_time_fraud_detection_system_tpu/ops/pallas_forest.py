"""Fused Pallas kernels for tree-ensemble (GEMM-form) inference.

Two kernels share one tree-block traversal core:

**1. Classify-only** (:func:`pallas_leaf_sum`) — the per-tree chain of
``models/forest.py::gemm_leaf_sum``

    proj = x @ sel[t]   (f32, HIGHEST — decision-exact, see forest.py)
    d    = proj <= thresh[t]          (0/1, exact in every z dtype)
    z    = d @ path[t]                (MXU; exact: |z| ≤ depth)
    acc += Σ_l leaf_val[t] where z matches target[t]

inside VMEM, tiling rows on the grid's first axis and streaming tree blocks
on the second; only ``x`` (60 B/row) is read from and the leaf-sum (4 B/row)
written to HBM.

**Measured verdict (v5e, round 4): XLA wins classify-only.** At the
flagship point (T=100, depth 8) the plain XLA composition runs 10.7M
rows/s at 1M-row batches vs 6.6M for this kernel (8.0M vs 5.7M at 262k) —
XLA's automatic fusion of the three contractions is already
intermediate-free and schedules the VPU-bound compare/select chain better
than the hand-rolled tree loop.

**2. Fused featurize→score** (:func:`fused_forest_leaf_sum`, round 9) —
the round-4 loss localized the remaining fusion win PAST the classify
chain: XLA cannot fuse through the window-update scatter/gather boundary
(``ops/windows.py``), so the feature block round-trips HBM between
featurization and the classifier. This kernel starts from the GATHERED
state rows (the gather stays in XLA, whose TPU gather emitter wins — same
split as ``ops/pallas_kernels.py``) and keeps the feature block
VMEM-resident end-to-end: window aggregates → 15-feature assembly
(``pallas_kernels.assemble_features``) → standardize → tree traversal, one
pass per row tile, the scaled feature block living in a VMEM scratch
across the streamed tree blocks. Covers the reference's enrichment SQL +
feature join + ``scale_and_predict_udf``
(``pyspark/scripts/fraud_detection.py:100-132,183-195``) for the flagship
RandomForest.

**Measured verdict (round 9): no TPU attached this round** — the sandbox
served CPU only, so the honest A/B (engine-level ``detail.device_plane``
in bench.py: z_mode off/on × fused off/on with ``mfu_of_ceiling``
before/after) is wired and runs automatically on the next TPU session;
interpret-mode parity vs the unfused jit composition (same rows, all
buckets) is pinned in ``tests/test_pallas_forest.py``. The kernel stays
**opt-in** (``RuntimeConfig.use_pallas``) until a TPU measurement says
otherwise — the same honest-A/B culture as the round-4 classify verdict
above.

Both kernels honor the serving ``z_mode`` (``RuntimeConfig.z_mode``): the
table layout (:func:`to_pallas`) carries ``path`` in the z dtype — int8
(int8×int8→int32 MXU, 2× bf16 peak on v5e, bit-exact: operands are tiny
integers), bf16 (exact: integers ≪ 2^8), or f32 — and the traversal core
picks the matching arithmetic. Numerics match ``gemm_leaf_sum``'s
documented mixed-precision contract: every branch decision is
bit-identical to sklearn on f32 inputs (proj in f32 HIGHEST against
f32-rounded-down thresholds), and only the final f32 accumulation order
differs (per-tree sequential here) — a ≤1-ulp-scale difference on the
bagged mean.

On non-TPU backends the kernels run in interpreter mode (slow, exact) so
CPU tests validate the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools
from typing import TYPE_CHECKING, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if TYPE_CHECKING:  # type-only: models.forest imports would cycle through
    from real_time_fraud_detection_system_tpu.models.forest import (
        GemmEnsemble,
    )


from real_time_fraud_detection_system_tpu.ops.pallas_kernels import (
    _on_tpu,
    assemble_features,
)


def _ceil_to(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


# Trees per grid step: amortizes per-step grid/DMA overhead while keeping the
# double-buffered table blocks (2 × TT·Ip·Lp bf16) small next to ~16MB VMEM.
TREE_BLOCK = 10


# Bytes per path-matrix element, by z_mode (see to_pallas).
_Z_DTYPES = {"bf16": jnp.bfloat16, "int8": jnp.int8, "f32": jnp.float32}
_Z_BYTES = {"bf16": 2, "int8": 1, "f32": 4}


class PallasForest(NamedTuple):
    """``GemmEnsemble`` re-padded to MXU tiles (I, L → ×128; F → ×8;
    T → ×TREE_BLOCK).

    Padding is inert by construction: fake internal nodes carry ``thresh=+inf``
    (decision always 1) and all-zero ``path`` rows; fake leaves carry
    ``target=1e9`` (never matched) and ``leaf_val=0``; fake trees are all of
    the above, so they contribute exactly 0 to the leaf sum.
    """

    sel: jnp.ndarray  # f32 [Tp, Fp, Ip] one-hot feature selector
    thresh: jnp.ndarray  # f32 [Tp, 1, Ip] (+inf padding)
    path: jnp.ndarray  # z-dtype [Tp, Ip, Lp] ±1/0 requirement matrix
    target: jnp.ndarray  # f32 [Tp, 1, Lp] (#left-required; 1e9 padding)
    leaf_val: jnp.ndarray  # f32 [Tp, 1, Lp]
    n_trees: int  # REAL tree count (bagging divisor); static


def to_pallas(g: GemmEnsemble, z_mode: str = "bf16") -> PallasForest:
    """Pad a compiled ``GemmEnsemble`` into the kernel's tile layout.

    Pure jnp pads, so it runs eagerly (one-time conversion) AND inside a
    jitted step — the engine derives the tables from its LIVE params every
    step (a few µs of pad writes next to ms of batch work), which keeps a
    checkpoint restore that overwrites ``state.params`` in-place serving
    the restored trees, never stale build-time copies.

    ``z_mode`` picks the ``path`` dtype — and with it the traversal
    core's z arithmetic (exact in every mode: path is ±1/0, d is 0/1,
    z counts ≤ depth; see ``models/forest.py::gemm_leaf_sum``).
    """
    t, f, i = g.sel.shape
    l = g.path.shape[2]
    tp = _ceil_to(int(t), TREE_BLOCK)
    fp = _ceil_to(int(f), 8)
    ip = _ceil_to(int(i), 128)
    lp = _ceil_to(int(l), 128)
    return PallasForest(
        sel=jnp.pad(g.sel, ((0, tp - t), (0, fp - f), (0, ip - i))),
        thresh=jnp.pad(g.thresh, ((0, tp - t), (0, ip - i)),
                       constant_values=jnp.inf)[:, None, :],
        path=jnp.pad(g.path, ((0, tp - t), (0, ip - i), (0, lp - l))
                     ).astype(_Z_DTYPES[z_mode]),
        target=jnp.pad(g.target, ((0, tp - t), (0, lp - l)),
                       constant_values=1e9)[:, None, :],
        leaf_val=jnp.pad(g.leaf_val, ((0, tp - t), (0, lp - l)))[:, None, :],
        n_trees=int(t),
    )


def pallas_table_bytes(g: GemmEnsemble, z_mode: str = "bf16") -> int:
    """TOTAL padded table footprint (HBM-resident; diagnostics)."""
    t = g.sel.shape[0]
    blocks = _ceil_to(int(t), TREE_BLOCK) // TREE_BLOCK
    return blocks * pallas_block_bytes(g, z_mode)


def pallas_block_bytes(g: GemmEnsemble, z_mode: str = "bf16") -> int:
    """Padded table bytes of ONE tree block — the VMEM-residency gate.

    The kernels stream (TREE_BLOCK, …) table blocks through VMEM (double-
    buffered), so per-step residency scales with the BLOCK, not the whole
    ensemble: T=100 depth-8 totals ~14 MB of tables in HBM but only
    ~1.5 MB/block in flight.
    """
    f, i = g.sel.shape[1:]
    l = g.path.shape[2]
    fp, ip, lp = _ceil_to(int(f), 8), _ceil_to(int(i), 128), _ceil_to(int(l), 128)
    return TREE_BLOCK * (
        fp * ip * 4 + ip * lp * _Z_BYTES[z_mode] + lp * 8 + ip * 4)


class PallasAdmission(NamedTuple):
    """The admission verdict for serving a ``GemmEnsemble`` through the
    fused kernels — every STATIC fact the gate decides on, in one
    record, so the engine's trace-time gate and the device-contract
    verifier (``tools/rtfdsverify``) consume the same predicate and can
    never drift. Shape math only: safe to call at trace time and on a
    weightless CPU-only verifier process."""

    fits: bool           # the whole verdict: bytes within budget AND tiled
    block_bytes: int     # one double-buffered tree block's VMEM bytes
    budget: int          # the byte budget the verdict was taken against
    tiles_aligned: bool  # padded dims divide the MXU/grid tile sizes
    padded: Tuple[int, int, int, int]  # (Tp, Fp, Ip, Lp) kernel layout


def admit_block(g: "GemmEnsemble", z_mode: str,
                budget: int) -> PallasAdmission:
    """Decide (statically) whether the fused kernels may serve ``g``.

    Two conditions, both provable from the params' shape tuple alone:
    the double-buffered tree-block tables must fit ``budget`` bytes of
    VMEM next to the row tile (see :func:`pallas_block_bytes`), and the
    padded table layout must tile exactly — ``Tp`` by ``TREE_BLOCK``
    (the grid's second axis), ``Fp`` by 8 and ``Ip``/``Lp`` by 128 (the
    MXU tile). The padded dims here re-derive :func:`to_pallas`'s math,
    so ``tiles_aligned`` alone cannot catch a drifted padding
    discipline — ``tools/rtfdsverify``'s pallas-admission check
    cross-checks ``padded`` against the layout ``to_pallas`` actually
    builds, which is what makes the alignment claim non-vacuous.
    """
    # shape tuples are static python ints even on traced values, so all
    # of the math below is host arithmetic — safe inside a traced step
    t, f, i = g.sel.shape
    l = g.path.shape[2]
    tp, fp = _ceil_to(t, TREE_BLOCK), _ceil_to(f, 8)
    ip, lp = _ceil_to(i, 128), _ceil_to(l, 128)
    aligned = (tp % TREE_BLOCK == 0 and fp % 8 == 0
               and ip % 128 == 0 and lp % 128 == 0)
    bb = pallas_block_bytes(g, z_mode)
    return PallasAdmission(
        fits=aligned and bb <= budget,
        block_bytes=bb,
        budget=budget,
        tiles_aligned=aligned,
        padded=(tp, fp, ip, lp),
    )


def _tree_block_leaf_sum(
    x,  # f32 [Bt, Fp] scaled feature tile (VMEM-resident)
    sel_ref,  # f32 [TT, Fp, Ip]
    thresh_ref,  # f32 [TT, 1, Ip]
    path_ref,  # z-dtype [TT, Ip, Lp]
    target_ref,  # f32 [TT, 1, Lp]
    leaf_ref,  # f32 [TT, 1, Lp]
    tree_block: int,
):
    """One tree block's leaf-sum contribution [Bt, 1] — the traversal
    core shared by the classify-only and fused featurize→score kernels.
    The z arithmetic follows ``path_ref``'s dtype (see ``to_pallas``):
    int8×int8→int32 on the MXU's int8 path, or bf16/f32×→f32."""
    hi = jax.lax.Precision.HIGHEST
    int8_z = path_ref.dtype == jnp.int8

    # Rolled loop, not a static unroll: one set of [Bt, Ip/Lp] intermediate
    # buffers is reused across the block's trees (an unroll keeps all
    # tree_block sets live at once — measured 17MB of scoped VMEM at
    # Bt=2048·TT=10, over the 16MB limit).
    def body(k, acc):
        proj = jnp.dot(x, sel_ref[k], precision=hi)  # [Bt, Ip] f32
        d = (proj <= thresh_ref[k]).astype(path_ref.dtype)
        if int8_z:
            # exact integer counts; target compares exactly in int32
            # (the 1e9 leaf padding is representable and never matched)
            z = jnp.dot(d, path_ref[k],
                        preferred_element_type=jnp.int32)
            matched = z == target_ref[k].astype(jnp.int32)
        else:
            z = jnp.dot(d, path_ref[k],
                        preferred_element_type=jnp.float32)
            matched = jnp.abs(z - target_ref[k]) < 0.5
        # single fused select→reduce pass (VPU-bound chain: one traversal
        # of [Bt, Lp] instead of onehot-cast + mul + reduce)
        contrib = jnp.sum(
            jnp.where(matched, leaf_ref[k], 0.0), axis=1, keepdims=True)
        return acc + contrib

    acc0 = jnp.zeros((x.shape[0], 1), jnp.float32)
    return jax.lax.fori_loop(0, tree_block, body, acc0)


def _leaf_sum_kernel(
    x_ref,  # f32 [Bt, Fp]
    sel_ref,  # f32 [TT, Fp, Ip]
    thresh_ref,  # f32 [TT, 1, Ip]
    path_ref,  # z-dtype [TT, Ip, Lp]
    target_ref,  # f32 [TT, 1, Lp]
    leaf_ref,  # f32 [TT, 1, Lp]
    out_ref,  # f32 [Bt, 1]
    *,
    tree_block: int,
):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += _tree_block_leaf_sum(
        x_ref[:], sel_ref, thresh_ref, path_ref, target_ref, leaf_ref,
        tree_block)


def pallas_leaf_sum(
    pf: PallasForest,
    x: jnp.ndarray,
    block_rows: int = 2048,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """[B, F] → Σ_t leaf value [B] — the fused-kernel ``gemm_leaf_sum``."""
    if interpret is None:
        interpret = not _on_tpu()
    b, f = x.shape
    tp, fp, ip = pf.sel.shape
    lp = pf.path.shape[2]
    tt = TREE_BLOCK
    if f < fp:
        x = jnp.pad(x, ((0, 0), (0, fp - f)))
    # Split b over the fewest blocks of ≤ block_rows, each the smallest ×8
    # size that covers its share — padding stays < 8·n_blocks rows instead
    # of rounding b up to a full block_rows multiple.
    nb = max(1, -(-b // block_rows))
    bt = _ceil_to(-(-b // nb), 8)
    bp = nb * bt
    if bp != b:  # pad rows; padded rows score garbage and are sliced off
        x = jnp.pad(x, ((0, bp - b), (0, 0)))
    grid = (nb, tp // tt)

    table = lambda *dims: pl.BlockSpec(  # noqa: E731
        (tt, *dims), lambda i, t: (t, 0, 0), memory_space=pltpu.VMEM,
    )
    out = pl.pallas_call(
        lambda *refs: _leaf_sum_kernel(*refs, tree_block=tt),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, fp), lambda i, t: (i, 0),
                         memory_space=pltpu.VMEM),
            table(fp, ip), table(1, ip), table(ip, lp),
            table(1, lp), table(1, lp),
        ],
        out_specs=pl.BlockSpec((bt, 1), lambda i, t: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        interpret=interpret,
    )(x, pf.sel, pf.thresh, pf.path, pf.target, pf.leaf_val)
    return out[:b, 0]


def pallas_predict_proba(
    pf: PallasForest, x: jnp.ndarray, **kw
) -> jnp.ndarray:
    """[B, F] → fraud probability [B] (bagging mean over real trees)."""
    return pallas_leaf_sum(pf, x, **kw) / pf.n_trees


# -- fused featurize→score step (round 9) -----------------------------------


def _fused_forest_kernel(
    c_bd_ref,  # int32 [Bt, NB] customer bucket days
    c_cnt_ref,  # f32 [Bt, NB]
    c_amt_ref,  # f32 [Bt, NB]
    t_bd_ref,  # int32 [Bt, NB] terminal bucket days
    t_cnt_ref,  # f32 [Bt, NB]
    t_frd_ref,  # f32 [Bt, NB]
    ivec_ref,  # int32 [Bt, 2] (day, tod_s)
    avec_ref,  # f32 [Bt, 1] (amount)
    svec_ref,  # f32 [2, Fp] rows: (mean, scale); pads (0, 1) are inert
    sel_ref,  # f32 [TT, Fp, Ip]
    thresh_ref,  # f32 [TT, 1, Ip]
    path_ref,  # z-dtype [TT, Ip, Lp]
    target_ref,  # f32 [TT, 1, Lp]
    leaf_ref,  # f32 [TT, 1, Lp]
    out_ref,  # f32 [Bt, 1] leaf sum out
    feats_ref,  # f32 [Bt, F] raw features out
    x_ref,  # VMEM scratch f32 [Bt, Fp] — scaled features, lives across
    #         the tree-block grid axis (allocated once per core)
    *,
    windows: Tuple[int, ...],
    delay: int,
    weekend_start: int,
    night_end: int,
    tree_block: int,
    n_feat: int,
):
    @pl.when(pl.program_id(1) == 0)
    def _featurize():
        # First tree block of this row tile: window aggregates → feature
        # assembly → standardize, all in VMEM. Later tree blocks reuse
        # the scaled block from scratch — the feature matrix never
        # round-trips HBM between featurization and the traversal (the
        # raw features are still written out once for the host plane).
        day = ivec_ref[:, 0:1]
        tod = ivec_ref[:, 1:2]
        amount = avec_ref[:, 0:1]
        feats = assemble_features(
            c_bd_ref[:], c_cnt_ref[:], c_amt_ref[:],
            t_bd_ref[:], t_cnt_ref[:], t_frd_ref[:],
            day, tod, amount,
            windows=windows, delay=delay, weekend_start=weekend_start,
            night_end=night_end,
        )
        feats_ref[:] = feats
        mean = svec_ref[0:1, :]
        scale = svec_ref[1:2, :]
        fp = x_ref.shape[1]
        if fp > n_feat:  # feature-lane padding: scaled pads are exactly 0
            feats = jnp.concatenate(
                [feats, jnp.zeros((feats.shape[0], fp - n_feat),
                                  jnp.float32)], axis=1)
        x_ref[:] = (feats - mean) / scale
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += _tree_block_leaf_sum(
        x_ref[:], sel_ref, thresh_ref, path_ref, target_ref, leaf_ref,
        tree_block)


def fused_forest_leaf_sum(
    pf: PallasForest,
    c_rows: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],  # (bd, cnt, amt)
    t_rows: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],  # (bd, cnt, frd)
    day: jnp.ndarray,  # int32 [B]
    tod_s: jnp.ndarray,  # int32 [B]
    amount: jnp.ndarray,  # f32 [B]
    scaler_mean: jnp.ndarray,  # f32 [F]
    scaler_scale: jnp.ndarray,  # f32 [F]
    windows: Sequence[int] = (1, 7, 30),
    delay: int = 7,
    weekend_start: int = 5,
    night_end: int = 6,
    block_rows: int = 1024,
    interpret: bool | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Gathered state rows → (Σ_t leaf value [B], raw features [B, F]).

    The fused featurize→score step: one kernel pass per row tile keeps
    the (scaled) feature block VMEM-resident from window read-out through
    the tree traversal, streaming tree blocks on the grid's second axis
    exactly like :func:`pallas_leaf_sum` — including its row-padding
    scheme, so any batch size works (padded rows read zeroed state rows,
    score garbage, and are sliced off).
    """
    c_bd, c_cnt, c_amt = c_rows
    t_bd, t_cnt, t_frd = t_rows
    bsz, nb = c_bd.shape
    tp, fp, ip = pf.sel.shape
    lp = pf.path.shape[2]
    tt = TREE_BLOCK
    n_feat = int(scaler_mean.shape[0])
    # Split bsz over the fewest blocks of ≤ block_rows, each the smallest
    # ×8 size that covers its share (same scheme as pallas_leaf_sum).
    nblk = max(1, -(-bsz // block_rows))
    bt = _ceil_to(-(-bsz // nblk), 8)
    bp = nblk * bt
    if bp != bsz:
        pad_rows = ((0, bp - bsz), (0, 0))
        c_bd = jnp.pad(c_bd, pad_rows)
        c_cnt = jnp.pad(c_cnt, pad_rows)
        c_amt = jnp.pad(c_amt, pad_rows)
        t_bd = jnp.pad(t_bd, pad_rows)
        t_cnt = jnp.pad(t_cnt, pad_rows)
        t_frd = jnp.pad(t_frd, pad_rows)
        pad_flat = (0, bp - bsz)
        day = jnp.pad(day, pad_flat)
        tod_s = jnp.pad(tod_s, pad_flat)
        amount = jnp.pad(amount, pad_flat)
    grid = (nblk, tp // tt)
    if interpret is None:
        interpret = not _on_tpu()

    ivec = jnp.stack([day.astype(jnp.int32), tod_s.astype(jnp.int32)],
                     axis=1)
    avec = amount.astype(jnp.float32)[:, None]
    # (mean, scale) padded to the kernel's feature lanes; pad cols carry
    # (0, 1) so padded features standardize to exactly 0 (and the padded
    # sel rows are all-zero anyway — doubly inert).
    svec = jnp.stack([
        jnp.pad(scaler_mean.astype(jnp.float32), (0, fp - n_feat)),
        jnp.pad(scaler_scale.astype(jnp.float32), (0, fp - n_feat),
                constant_values=1.0),
    ], axis=0)

    row_spec = lambda width: pl.BlockSpec(  # noqa: E731
        (bt, width), lambda i, t: (i, 0), memory_space=pltpu.VMEM,
    )
    table = lambda *dims: pl.BlockSpec(  # noqa: E731
        (tt, *dims), lambda i, t: (t, 0, 0), memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _fused_forest_kernel,
        windows=tuple(windows),
        delay=delay,
        weekend_start=weekend_start,
        night_end=night_end,
        tree_block=tt,
        n_feat=n_feat,
    )
    leaf, feats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_spec(nb), row_spec(nb), row_spec(nb),
            row_spec(nb), row_spec(nb), row_spec(nb),
            row_spec(2), row_spec(1),
            pl.BlockSpec((2, fp), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            table(fp, ip), table(1, ip), table(ip, lp),
            table(1, lp), table(1, lp),
        ],
        out_specs=(row_spec(1), row_spec(n_feat)),
        out_shape=(
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((bp, n_feat), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, fp), jnp.float32)],
        interpret=interpret,
    )(c_bd, c_cnt, c_amt, t_bd, t_cnt, t_frd, ivec, avec, svec,
      pf.sel, pf.thresh, pf.path, pf.target, pf.leaf_val)
    return leaf[:bsz, 0], feats[:bsz]
