"""Day-ringed count-min sketch — velocity features for unbounded keys.

The dense ``WindowState`` table is exact-per-slot but hashes keys modulo a
fixed capacity; when the key universe outgrows it (billions of cards), the
count-min sketch bounds memory with a provable overestimate-only error:
est ≥ true, P[est > true + εN] ≤ δ with width=⌈e/ε⌉, depth=⌈ln 1/δ⌉.

To support *windowed* velocity (count / amount over trailing days) each day
gets its own sketch slice in a ring of ``n_days`` slices; a slice is lazily
reset when its ring position is claimed by a newer day. Query = per-day
min-over-depth estimate, summed over the window — matching the window
semantics of :mod:`.windows` (trailing calendar days, inclusive).

This is BASELINE.json config 3 ("HBM-resident count-min sketch per-card /
per-merchant velocity features"); the reference has no equivalent (its
features are precomputed static joins, ``fraud_detection.py:100-123``).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax.numpy as jnp

from real_time_fraud_detection_system_tpu.ops.hashing import multi_hash


class CountMinSketch(NamedTuple):
    """Pytree: ring of daily CMS slices."""

    slice_day: jnp.ndarray  # int32 [ND] — absolute day held by each slice
    count: jnp.ndarray  # float32 [ND, depth, width]
    amount: jnp.ndarray  # float32 [ND, depth, width]

    @property
    def n_days(self) -> int:
        return int(self.slice_day.shape[0])

    @property
    def depth(self) -> int:
        return int(self.count.shape[1])

    @property
    def width(self) -> int:
        return int(self.count.shape[2])


def cms_init(depth: int, width: int, n_days: int = 40) -> CountMinSketch:
    return CountMinSketch(
        slice_day=jnp.full((n_days,), -1, dtype=jnp.int32),
        count=jnp.zeros((n_days, depth, width), dtype=jnp.float32),
        amount=jnp.zeros((n_days, depth, width), dtype=jnp.float32),
    )


def cms_update(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    amount: jnp.ndarray,  # float32 [B]
    day: jnp.ndarray,  # int32 [B]
    valid: jnp.ndarray,  # bool [B]
) -> CountMinSketch:
    nd, depth, width = sk.count.shape
    sl = jnp.remainder(day, nd)  # [B]
    day_in = jnp.where(valid, day, -1).astype(jnp.int32)
    new_slice_day = sk.slice_day.at[sl].max(day_in)

    # Reset slices that advanced to a newer day.
    advanced = (new_slice_day > sk.slice_day)[:, None, None]
    count = jnp.where(advanced, 0.0, sk.count)
    amt = jnp.where(advanced, 0.0, sk.amount)

    fresh = valid & (day_in == new_slice_day[sl])
    w = fresh.astype(jnp.float32)  # [B]
    cols = multi_hash(key, depth, width)  # [depth, B]
    rows = jnp.broadcast_to(jnp.arange(depth, dtype=jnp.int32)[:, None], cols.shape)
    slc = jnp.broadcast_to(sl[None, :], cols.shape)
    wb = jnp.broadcast_to(w[None, :], cols.shape)
    count = count.at[slc, rows, cols].add(wb)
    amt = amt.at[slc, rows, cols].add(wb * amount[None, :])
    return CountMinSketch(slice_day=new_slice_day, count=count, amount=amt)


def cms_query(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Windowed velocity estimates: (counts, amount_sums), each [B, NW].

    Window w sums the per-day min-over-depth estimates for days
    [day-w+1, day].
    """
    nd, depth, width = sk.count.shape
    max_w = max(windows)
    offsets = jnp.arange(max_w, dtype=jnp.int32)  # [W]
    wanted = day[:, None] - offsets[None, :]  # [B, W]
    sl = jnp.remainder(wanted, nd)  # [B, W]
    live = (sk.slice_day[sl] == wanted) & (wanted >= 0)  # [B, W]

    cols = multi_hash(key, depth, width)  # [depth, B]
    # Gather [depth, B, W] then min over depth.
    g_count = sk.count[sl[None, :, :], jnp.arange(depth)[:, None, None], cols[:, :, None]]
    g_amt = sk.amount[sl[None, :, :], jnp.arange(depth)[:, None, None], cols[:, :, None]]
    est_count = jnp.min(g_count, axis=0) * live  # [B, W]
    est_amt = jnp.min(g_amt, axis=0) * live

    sel = jnp.stack(
        [(offsets < w).astype(jnp.float32) for w in windows], axis=0
    )  # [NW, W]
    return est_count @ sel.T, est_amt @ sel.T
