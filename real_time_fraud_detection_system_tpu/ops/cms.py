"""Day-ringed count-min sketch — velocity features for unbounded keys.

The dense ``WindowState`` table is exact-per-slot but hashes keys modulo a
fixed capacity; when the key universe outgrows it (billions of cards), the
count-min sketch bounds memory with a provable overestimate-only error:
est ≥ true, P[est > true + εN] ≤ δ with width=⌈e/ε⌉, depth=⌈ln 1/δ⌉.

To support *windowed* velocity (count / amount over trailing days) each day
gets its own sketch slice in a ring of ``n_days`` slices; a slice is lazily
reset when its ring position is claimed by a newer day. Query = per-day
min-over-depth estimate, summed over the window — matching the window
semantics of :mod:`.windows` (trailing calendar days, inclusive).

This is BASELINE.json config 3 ("HBM-resident count-min sketch per-card /
per-merchant velocity features"); the reference has no equivalent (its
features are precomputed static joins, ``fraud_detection.py:100-123``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp

from real_time_fraud_detection_system_tpu.ops.hashing import multi_hash


class CountMinSketch(NamedTuple):
    """Pytree: ring of daily CMS slices.

    ``fraud`` is an OPTIONAL third column (fraud-label sums) used by the
    tiered feature store's sketch tier so terminal *risk* degrades
    gracefully when a key misses hot-tier admission. ``None`` (the
    default, and every pre-tiering config) keeps the pytree leaf
    structure — and therefore checkpoints — identical to the historical
    2-column sketch."""

    slice_day: jnp.ndarray  # int32 [ND] — absolute day held by each slice
    count: jnp.ndarray  # float32 [ND, depth, width]
    amount: jnp.ndarray  # float32 [ND, depth, width]
    fraud: Optional[jnp.ndarray] = None  # float32 [ND, depth, width] | None

    @property
    def n_days(self) -> int:
        return int(self.slice_day.shape[0])

    @property
    def depth(self) -> int:
        return int(self.count.shape[1])

    @property
    def width(self) -> int:
        return int(self.count.shape[2])


def cms_init(depth: int, width: int, n_days: int = 40,
             track_fraud: bool = False) -> CountMinSketch:
    return CountMinSketch(
        slice_day=jnp.full((n_days,), -1, dtype=jnp.int32),
        count=jnp.zeros((n_days, depth, width), dtype=jnp.float32),
        amount=jnp.zeros((n_days, depth, width), dtype=jnp.float32),
        fraud=jnp.zeros((n_days, depth, width), dtype=jnp.float32)
        if track_fraud else None,
    )


def cms_update(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    amount: jnp.ndarray,  # float32 [B]
    day: jnp.ndarray,  # int32 [B]
    valid: jnp.ndarray,  # bool [B]
    fraud: Optional[jnp.ndarray] = None,  # float32 [B] 0/1 (labeled rows)
) -> CountMinSketch:
    nd, depth, width = sk.count.shape
    sl = jnp.remainder(day, nd)  # [B]
    day_in = jnp.where(valid, day, -1).astype(jnp.int32)
    new_slice_day = sk.slice_day.at[sl].max(day_in)

    # Reset slices that advanced to a newer day.
    advanced = (new_slice_day > sk.slice_day)[:, None, None]
    count = jnp.where(advanced, 0.0, sk.count)
    amt = jnp.where(advanced, 0.0, sk.amount)

    fresh = valid & (day_in == new_slice_day[sl])
    w = fresh.astype(jnp.float32)  # [B]
    cols = multi_hash(key, depth, width)  # [depth, B]
    rows = jnp.broadcast_to(jnp.arange(depth, dtype=jnp.int32)[:, None], cols.shape)
    slc = jnp.broadcast_to(sl[None, :], cols.shape)
    wb = jnp.broadcast_to(w[None, :], cols.shape)
    count = count.at[slc, rows, cols].add(wb)
    amt = amt.at[slc, rows, cols].add(wb * amount[None, :])
    frd = sk.fraud
    if frd is not None:
        # Same slice-reset + fresh-mask discipline as count/amount; a
        # sketch without the column (every pre-tiering config) takes a
        # bit-identical count/amount path through this function.
        frd = jnp.where(advanced, 0.0, frd)
        f_in = (jnp.zeros_like(w) if fraud is None
                else fraud.astype(jnp.float32))
        frd = frd.at[slc, rows, cols].add(wb * f_in[None, :])
    return CountMinSketch(slice_day=new_slice_day, count=count, amount=amt,
                          fraud=frd)


def cms_add_fraud(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B] — the ORIGINAL transaction's day
    label: jnp.ndarray,  # int32/float32 [B] 0/1
    valid: jnp.ndarray,  # bool [B]
    owner: Optional[jnp.ndarray] = None,  # int32 [B] — shard per row
) -> CountMinSketch:
    """Late fraud-label feedback into the sketch tier: add fraud sums to
    the slice still holding ``day`` (counts unchanged — the row was
    already counted when it streamed through). Labels for days the ring
    has wrapped past are dropped, mirroring the dense tier's
    bounded-lateness policy.

    ``owner`` selects the sharded form: ``sk`` then carries STACKED
    per-shard tables (``[n_shards, ND, depth, width]``) and row i lands
    in shard ``owner[i]``'s replica — ONE bounded-lateness policy for
    the single-chip and sharded feedback paths."""
    if sk.fraud is None:
        return sk
    nd, depth, width = sk.count.shape[-3:]
    sl = jnp.remainder(day, nd)
    live_day = (sk.slice_day[sl] if owner is None
                else sk.slice_day[owner, sl])
    live = valid & (live_day == day)
    w = live.astype(jnp.float32) * label.astype(jnp.float32)
    cols = multi_hash(key, depth, width)  # [depth, B]
    rows = jnp.broadcast_to(
        jnp.arange(depth, dtype=jnp.int32)[:, None], cols.shape)
    slc = jnp.broadcast_to(sl[None, :], cols.shape)
    wb = jnp.broadcast_to(w[None, :], cols.shape)
    if owner is None:
        return sk._replace(fraud=sk.fraud.at[slc, rows, cols].add(wb))
    ob = jnp.broadcast_to(owner[None, :], cols.shape)
    return sk._replace(fraud=sk.fraud.at[ob, slc, rows, cols].add(wb))


def _cms_query_tables(
    sk: CountMinSketch,
    tables: Sequence[jnp.ndarray],  # each [ND, depth, width]
    key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, ...]:
    """Shared windowed min-over-depth estimator over N parallel tables.

    Window w sums the per-day estimates for days
    [day-delay-w+1, day-delay] — the same delay-shift semantics as
    :func:`..windows.query_windows` (``delay=0`` is the historical
    count/amount path, bit-identical arithmetic)."""
    nd, depth, width = sk.count.shape
    max_w = max(windows)
    offsets = jnp.arange(max_w, dtype=jnp.int32)  # [W]
    wanted = day[:, None] - jnp.int32(delay) - offsets[None, :]  # [B, W]
    sl = jnp.remainder(wanted, nd)  # [B, W]
    live = (sk.slice_day[sl] == wanted) & (wanted >= 0)  # [B, W]

    cols = multi_hash(key, depth, width)  # [depth, B]
    sel = jnp.stack(
        [(offsets < w).astype(jnp.float32) for w in windows], axis=0
    )  # [NW, W]
    out = []
    for t in tables:
        # Gather [depth, B, W] then min over depth.
        g = t[sl[None, :, :], jnp.arange(depth)[:, None, None],
              cols[:, :, None]]
        out.append((jnp.min(g, axis=0) * live) @ sel.T)
    return tuple(out)


def cms_query(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Windowed velocity estimates: (counts, amount_sums), each [B, NW].

    Window w sums the per-day min-over-depth estimates for days
    [day-delay-w+1, day-delay] (``delay=0``: [day-w+1, day], the
    historical behavior, bit-identical).
    """
    return _cms_query_tables(sk, (sk.count, sk.amount), key, day, windows,
                             delay)


def cms_query_fraud(
    sk: CountMinSketch,
    key: jnp.ndarray,  # uint32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """3-column windowed estimates: (counts, amount_sums, fraud_sums),
    each [B, NW]. Requires a fraud-tracking sketch (``cms_init(...,
    track_fraud=True)``). Both count and fraud are overestimate-only, so
    a risk RATIO derived from them is an estimate, not a bound — the
    documented sketch-tier degradation."""
    if sk.fraud is None:
        raise ValueError(
            "cms_query_fraud needs a fraud-tracking sketch "
            "(cms_init(..., track_fraud=True))")
    return _cms_query_tables(sk, (sk.count, sk.amount, sk.fraud), key, day,
                             windows, delay)
