"""HBM-resident rolling-window state: per-key day-bucket ring buffers.

This op family replaces the reference's *static* feature tables
(``nessie.payment.feature_customer`` / ``feature_terminal``, joined at score
time in ``fraud_detection.py:100-123``) with *online* state that lives in HBM
and is updated by every micro-batch — the windowed aggregates the offline
pipeline computed with pandas rolling windows
(``feature_transformation.ipynb · cells 17,25``).

Layout: for each of ``capacity`` key slots, ``n_buckets`` daily buckets in a
ring (``bucket = day % n_buckets``), each holding (count, amount-sum,
fraud-sum) for one absolute day, stamped with that day. A window query sums
the buckets whose stamp falls inside the window; stale buckets (overwritten
by the ring) simply don't match and contribute zero.

Canonical window semantics (documented deviation from the reference): windows
are **trailing calendar days including the current day** — window w at day d
covers days [d-w+1, d]; with ``delay`` (terminal risk label latency,
``feature_transformation.ipynb · cell 25``) it covers [d-delay-w+1, d-delay].
The reference's pandas ``rolling('Nd')`` is a trailing wall-clock window;
day-granular buckets are the streaming-friendly approximation, and training
uses the SAME kernel via replay, so there is zero train/serve skew.

All updates are O(B) scatters and all queries O(B × max_window) gathers —
fully vectorized, jit/shard_map friendly, no data-dependent shapes.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax.numpy as jnp


class WindowState(NamedTuple):
    """Ring-buffer day aggregates for one key space (pytree of [cap, NB])."""

    bucket_day: jnp.ndarray  # int32 [cap, NB]; -1 = empty
    count: jnp.ndarray  # float32 [cap, NB]
    amount: jnp.ndarray  # float32 [cap, NB] — sum of amounts that day
    fraud: jnp.ndarray  # float32 [cap, NB] — sum of fraud labels that day

    @property
    def capacity(self) -> int:
        return int(self.bucket_day.shape[0])

    @property
    def n_buckets(self) -> int:
        return int(self.bucket_day.shape[1])


def init_window_state(capacity: int, n_buckets: int) -> WindowState:
    return WindowState(
        bucket_day=jnp.full((capacity, n_buckets), -1, dtype=jnp.int32),
        count=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
        amount=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
        fraud=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
    )


def update_windows(
    state: WindowState,
    slot: jnp.ndarray,  # int32 [B] in [0, capacity)
    day: jnp.ndarray,  # int32 [B] absolute day index
    amount: jnp.ndarray,  # float32 [B]
    fraud: jnp.ndarray,  # float32 [B] — 0/1, or 0 when label unknown
    valid: jnp.ndarray,  # bool [B]
) -> WindowState:
    """Scatter one micro-batch into the ring buffers.

    Semantics: a bucket is (lazily) reset the first time a *newer* day maps
    onto it; rows older than what a bucket currently holds are dropped
    (bounded-lateness policy — the ring holds n_buckets days of history).
    Duplicate (slot, day) rows within the batch accumulate correctly
    (jnp scatter-add applies all duplicates).
    """
    nb = state.n_buckets
    cap = state.capacity
    bucket = jnp.remainder(day, nb)
    flat = (slot * nb + bucket).astype(jnp.int32)

    # Day stamp each touched bucket with max(existing, incoming) — invalid
    # rows stamp -1 which never wins.
    day_in = jnp.where(valid, day, -1).astype(jnp.int32)
    bd = state.bucket_day.reshape(-1)
    new_bd = bd.at[flat].max(day_in)

    # Buckets whose stamp advanced hold a stale (older) day: reset aggregates.
    advanced = new_bd > bd
    count = jnp.where(advanced, 0.0, state.count.reshape(-1))
    amt = jnp.where(advanced, 0.0, state.amount.reshape(-1))
    frd = jnp.where(advanced, 0.0, state.fraud.reshape(-1))

    # A row contributes only if its day is the bucket's (possibly new) stamp.
    fresh = valid & (day_in == new_bd[flat])
    w = fresh.astype(jnp.float32)
    count = count.at[flat].add(w)
    amt = amt.at[flat].add(amount * w)
    frd = frd.at[flat].add(fraud * w)

    return WindowState(
        bucket_day=new_bd.reshape(cap, nb),
        count=count.reshape(cap, nb),
        amount=amt.reshape(cap, nb),
        fraud=frd.reshape(cap, nb),
    )


def query_windows(
    state: WindowState,
    slot: jnp.ndarray,  # int32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Gather per-row window aggregates.

    Returns (counts, amount_sums, fraud_sums), each [B, len(windows)], where
    window w sums days [day-delay-w+1, day-delay].
    """
    nb = state.n_buckets
    max_w = max(windows)
    offsets = jnp.arange(max_w, dtype=jnp.int32)  # [W]
    wanted = day[:, None] - jnp.int32(delay) - offsets[None, :]  # [B, W]
    bucket = jnp.remainder(wanted, nb)
    flat = slot[:, None] * nb + bucket  # [B, W]

    live = (state.bucket_day.reshape(-1)[flat] == wanted) & (wanted >= 0)
    live_f = live.astype(jnp.float32)
    g_count = state.count.reshape(-1)[flat] * live_f  # [B, W]
    g_amount = state.amount.reshape(-1)[flat] * live_f
    g_fraud = state.fraud.reshape(-1)[flat] * live_f

    # Per-window masked prefix sums over the offset axis.
    sel = jnp.stack(
        [(offsets < w).astype(jnp.float32) for w in windows], axis=0
    )  # [NW, W]
    counts = g_count @ sel.T  # [B, NW]
    amounts = g_amount @ sel.T
    frauds = g_fraud @ sel.T
    return counts, amounts, frauds
