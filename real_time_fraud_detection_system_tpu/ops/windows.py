"""HBM-resident rolling-window state: per-key day-bucket ring buffers.

This op family replaces the reference's *static* feature tables
(``nessie.payment.feature_customer`` / ``feature_terminal``, joined at score
time in ``fraud_detection.py:100-123``) with *online* state that lives in HBM
and is updated by every micro-batch — the windowed aggregates the offline
pipeline computed with pandas rolling windows
(``feature_transformation.ipynb · cells 17,25``).

Layout: for each of ``capacity`` key slots, ``n_buckets`` daily buckets in a
ring (``bucket = day % n_buckets``), each holding (count, amount-sum,
fraud-sum) for one absolute day, stamped with that day. A window query sums
the buckets whose stamp falls inside the window; stale buckets (overwritten
by the ring) simply don't match and contribute zero.

Canonical window semantics (documented deviation from the reference): windows
are **trailing calendar days including the current day** — window w at day d
covers days [d-w+1, d]; with ``delay`` (terminal risk label latency,
``feature_transformation.ipynb · cell 25``) it covers [d-delay-w+1, d-delay].
The reference's pandas ``rolling('Nd')`` is a trailing wall-clock window;
day-granular buckets are the streaming-friendly approximation, and training
uses the SAME kernel via replay, so there is zero train/serve skew.

All updates are O(B) scatters and all queries O(B × max_window) gathers —
fully vectorized, jit/shard_map friendly, no data-dependent shapes.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax.numpy as jnp


class WindowState(NamedTuple):
    """Ring-buffer day aggregates for one key space (pytree of [cap, NB])."""

    bucket_day: jnp.ndarray  # int32 [cap, NB]; -1 = empty
    count: jnp.ndarray  # float32 [cap, NB]
    amount: jnp.ndarray  # float32 [cap, NB] — sum of amounts that day
    fraud: jnp.ndarray  # float32 [cap, NB] — sum of fraud labels that day

    @property
    def capacity(self) -> int:
        return int(self.bucket_day.shape[0])

    @property
    def n_buckets(self) -> int:
        return int(self.bucket_day.shape[1])


def init_window_state(capacity: int, n_buckets: int) -> WindowState:
    return WindowState(
        bucket_day=jnp.full((capacity, n_buckets), -1, dtype=jnp.int32),
        count=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
        amount=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
        fraud=jnp.zeros((capacity, n_buckets), dtype=jnp.float32),
    )


def update_windows(
    state: WindowState,
    slot: jnp.ndarray,  # int32 [B] in [0, capacity)
    day: jnp.ndarray,  # int32 [B] absolute day index
    amount: jnp.ndarray,  # float32 [B]
    fraud: jnp.ndarray,  # float32 [B] — 0/1, or 0 when label unknown
    valid: jnp.ndarray,  # bool [B]
    track_amount: bool = True,
    track_fraud: bool = True,
) -> WindowState:
    """Scatter one micro-batch into the ring buffers.

    Semantics: a bucket is (lazily) reset the first time a *newer* day maps
    onto it; rows older than what a bucket currently holds are dropped
    (bounded-lateness policy — the ring holds n_buckets days of history).
    Duplicate (slot, day) rows within the batch accumulate correctly
    (jnp scatter-add applies all duplicates).

    ``track_amount`` / ``track_fraud``: scatters are the hot path's most
    expensive op on TPU (~7 ms per 1M updates, serialized emitter;
    reformulations — segment_sum, sorted/unique hints, one wide scatter —
    all measured equal or worse). A table whose consumer never reads a
    column may skip its scatter: the 15-feature spec reads customer
    (count, amount) and terminal (count, fraud) only, so the engine drops
    one scatter per keyspace (§``features/online._update_state``). A
    skipped column still gets the (cheap, full-table) stale-bucket reset,
    so its buckets never mix days: it simply misses this batch's
    contributions — safe even if a later update re-enables tracking.
    """
    nb = state.n_buckets
    cap = state.capacity
    bucket = jnp.remainder(day, nb)
    flat = (slot * nb + bucket).astype(jnp.int32)

    # Day stamp each touched bucket with max(existing, incoming) — invalid
    # rows stamp -1 which never wins.
    day_in = jnp.where(valid, day, -1).astype(jnp.int32)
    bd = state.bucket_day.reshape(-1)
    new_bd = bd.at[flat].max(day_in)

    # Buckets whose stamp advanced hold a stale (older) day: reset aggregates.
    advanced = new_bd > bd
    count = jnp.where(advanced, 0.0, state.count.reshape(-1))

    # A row contributes only if its day is the bucket's (possibly new) stamp.
    fresh = valid & (day_in == new_bd[flat])
    w = fresh.astype(jnp.float32)
    count = count.at[flat].add(w)

    amt = jnp.where(advanced, 0.0, state.amount.reshape(-1))
    if track_amount:
        amt = amt.at[flat].add(amount * w)
    frd = jnp.where(advanced, 0.0, state.fraud.reshape(-1))
    if track_fraud:
        frd = frd.at[flat].add(fraud * w)
    amt = amt.reshape(cap, nb)
    frd = frd.reshape(cap, nb)

    return WindowState(
        bucket_day=new_bd.reshape(cap, nb),
        count=count.reshape(cap, nb),
        amount=amt,
        fraud=frd,
    )


def gather_state_rows(
    state: WindowState, slot: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One row-gather per table: (bucket_day, count, amount, fraud)[slot],
    each [B, NB]. The single embedding-style gather the query needs."""
    return (
        state.bucket_day[slot],
        state.count[slot],
        state.amount[slot],
        state.fraud[slot],
    )


def query_gathered(
    bucket_day: jnp.ndarray,  # int32 [B, NB]
    count: jnp.ndarray,  # float32 [B, NB]
    amount: jnp.ndarray,  # float32 [B, NB]
    fraud: jnp.ndarray,  # float32 [B, NB]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Window sums from pre-gathered state rows — age-mask formulation.

    A bucket holding absolute day s contributes to window w iff its age
    ``a = day - delay - s`` satisfies ``0 <= a < w`` (empty buckets carry
    stamp -1 and only match impossible ages). No per-window modulo gathers:
    one [B, NB] age computation + a [B, NB] @ [NB→NW] masked contraction,
    entirely VPU/MXU-friendly (and the form the Pallas fused kernel uses).
    """
    age = day[:, None] - jnp.int32(delay) - bucket_day  # [B, NB]
    live = (bucket_day >= 0) & (age >= 0)
    out_c, out_a, out_f = [], [], []
    for w in windows:
        sel = (live & (age < w)).astype(jnp.float32)
        out_c.append(jnp.sum(count * sel, axis=1))
        out_a.append(jnp.sum(amount * sel, axis=1))
        out_f.append(jnp.sum(fraud * sel, axis=1))
    return (
        jnp.stack(out_c, axis=1),
        jnp.stack(out_a, axis=1),
        jnp.stack(out_f, axis=1),
    )


def query_windows(
    state: WindowState,
    slot: jnp.ndarray,  # int32 [B]
    day: jnp.ndarray,  # int32 [B]
    windows: Sequence[int],
    delay: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Gather per-row window aggregates.

    Returns (counts, amount_sums, fraud_sums), each [B, len(windows)], where
    window w sums days [day-delay-w+1, day-delay]. One row-gather per table
    plus dense age-mask reductions (see :func:`query_gathered`) — TPU-
    friendlier than per-(row, day-offset) flat gathers.
    """
    bd, cnt, amt, frd = gather_state_rows(state, slot)
    return query_gathered(bd, cnt, amt, frd, day, windows, delay)
