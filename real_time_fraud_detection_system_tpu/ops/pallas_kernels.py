"""Pallas TPU kernels for the scoring hot path.

``fused_featurize_score``: one kernel for window-aggregate → 15-feature
assembly → standardize → linear classify. XLA already fuses much of this
chain; the kernel guarantees it — one VMEM-resident pass per batch tile,
zero intermediate HBM traffic between featurization and the classifier —
and is the template for deeper fusions (the state *gather* stays outside:
Mosaic has no vectorized dynamic row-gather, while XLA's TPU gather emitter
handles it well; the measured split keeps each side on its fastest path).

Everything inside is VPU/MXU-friendly: comparisons, selects, lane
reductions over the NB day-bucket axis, and a [B,15]·[15] contraction — no
data-dependent indexing, so the kernel lowers cleanly through Mosaic.

Replaces (with ``RuntimeConfig.use_pallas``) the jnp composition
``query_gathered`` (`ops/windows.py`) + ``_flags``+stack
(`features/online.py`) + ``scaler.transform``+``logreg_predict_proba``
(`models/`), which together re-implement the reference's per-batch Spark
chain: enrichment SQL + feature join (``fraud_detection.py:100-132``) +
``scale_and_predict_udf`` (``:183-195``).

On non-TPU backends the kernel runs in interpreter mode (slow, exact) so
CPU tests validate the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def assemble_features(
    c_bd, c_cnt, c_amt,  # [Bt, NB] customer rows (bucket_day, count, amount)
    t_bd, t_cnt, t_frd,  # [Bt, NB] terminal rows (bucket_day, count, fraud)
    day, tod, amount,  # [Bt, 1] per-row scalars (int32, int32, f32)
    *,
    windows: Tuple[int, ...],
    delay: int,
    weekend_start: int,
    night_end: int,
) -> jnp.ndarray:
    """Gathered state rows → raw [Bt, F] feature block (age-mask form).

    The in-kernel twin of ``ops/windows.py::query_gathered`` +
    ``features/online.py::_flags`` + column stack — pure VPU math
    (compares, selects, lane reductions over the NB axis), shared by the
    linear fused kernel below and the forest fused step
    (``ops/pallas_forest.py``). Feature order matches
    ``features/spec.py::FEATURE_NAMES``."""
    age_c = day - c_bd  # [Bt, NB]
    live_c = (c_bd >= 0) & (age_c >= 0)
    age_t = day - delay - t_bd
    live_t = (t_bd >= 0) & (age_t >= 0)

    cols = [amount]
    # flags
    weekday = jnp.remainder(day + 3, 7)
    cols.append((weekday >= weekend_start).astype(jnp.float32))
    cols.append((tod // 3600 <= night_end).astype(jnp.float32))
    for w in windows:
        sel = jnp.where(live_c & (age_c < w), 1.0, 0.0)
        cnt = jnp.sum(c_cnt * sel, axis=1, keepdims=True)
        amt = jnp.sum(c_amt * sel, axis=1, keepdims=True)
        cols.append(cnt)
        cols.append(jnp.where(cnt > 0, amt / jnp.maximum(cnt, 1.0), 0.0))
    for w in windows:
        sel = jnp.where(live_t & (age_t < w), 1.0, 0.0)
        cnt = jnp.sum(t_cnt * sel, axis=1, keepdims=True)
        frd = jnp.sum(t_frd * sel, axis=1, keepdims=True)
        cols.append(cnt)
        cols.append(jnp.where(cnt > 0, frd / jnp.maximum(cnt, 1.0), 0.0))
    return jnp.concatenate(cols, axis=1)  # [Bt, F]


def _score_kernel(
    c_bd_ref,  # int32 [Bt, NB] customer bucket days
    c_cnt_ref,  # f32 [Bt, NB]
    c_amt_ref,  # f32 [Bt, NB]
    t_bd_ref,  # int32 [Bt, NB] terminal bucket days
    t_cnt_ref,  # f32 [Bt, NB]
    t_frd_ref,  # f32 [Bt, NB]
    ivec_ref,  # int32 [Bt, 2] (day, tod_s)
    fvec_ref,  # f32 [Bt, 2] (amount, valid)
    pvec_ref,  # f32 [4, F] rows: (mean, scale, w, b-broadcast)
    probs_ref,  # f32 [Bt, 1] out
    feats_ref,  # f32 [Bt, F] out
    *,
    windows: Tuple[int, ...],
    delay: int,
    weekend_start: int,
    night_end: int,
):
    day = ivec_ref[:, 0:1]  # [Bt, 1]
    tod = ivec_ref[:, 1:2]
    amount = fvec_ref[:, 0:1]
    valid = fvec_ref[:, 1:2]

    feats = assemble_features(
        c_bd_ref[:], c_cnt_ref[:], c_amt_ref[:],
        t_bd_ref[:], t_cnt_ref[:], t_frd_ref[:],
        day, tod, amount,
        windows=windows, delay=delay, weekend_start=weekend_start,
        night_end=night_end,
    )
    feats_ref[:] = feats

    # --- standardize + logistic score
    mean = pvec_ref[0:1, :]
    scale = pvec_ref[1:2, :]
    w_row = pvec_ref[2:3, :]
    bias = pvec_ref[3:4, 0:1]
    x = (feats - mean) / scale
    z = jnp.sum(x * w_row, axis=1, keepdims=True) + bias
    probs_ref[:] = jax.nn.sigmoid(z) * valid


def fused_featurize_score(
    c_rows: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],  # (bd, cnt, amt)
    t_rows: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],  # (bd, cnt, frd)
    day: jnp.ndarray,  # int32 [B]
    tod_s: jnp.ndarray,  # int32 [B]
    amount: jnp.ndarray,  # f32 [B]
    valid: jnp.ndarray,  # bool [B]
    scaler_mean: jnp.ndarray,  # f32 [F]
    scaler_scale: jnp.ndarray,  # f32 [F]
    w: jnp.ndarray,  # f32 [F]
    b: jnp.ndarray,  # f32 scalar
    windows: Sequence[int] = (1, 7, 30),
    delay: int = 7,
    weekend_start: int = 5,
    night_end: int = 6,
    block_rows: int = 1024,
    interpret: bool | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (probs [B], features [B, F]); batch tiled over a 1-D grid."""
    c_bd, c_cnt, c_amt = c_rows
    t_bd, t_cnt, t_frd = t_rows
    bsz, nb = c_bd.shape
    n_feat = scaler_mean.shape[0]
    bt = min(block_rows, bsz)
    if bsz % bt != 0:  # static shapes: caller pads batches to buckets
        raise ValueError(f"batch {bsz} not divisible by block_rows {bt}")
    grid = (bsz // bt,)
    if interpret is None:
        interpret = not _on_tpu()

    ivec = jnp.stack([day.astype(jnp.int32), tod_s.astype(jnp.int32)], axis=1)
    fvec = jnp.stack(
        [amount.astype(jnp.float32), valid.astype(jnp.float32)], axis=1
    )
    pvec = jnp.stack(
        [
            scaler_mean.astype(jnp.float32),
            scaler_scale.astype(jnp.float32),
            w.astype(jnp.float32),
            jnp.full((n_feat,), b, dtype=jnp.float32),
        ],
        axis=0,
    )

    row_spec = lambda width: pl.BlockSpec(  # noqa: E731
        (bt, width), lambda i: (i, 0), memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _score_kernel,
        windows=tuple(windows),
        delay=delay,
        weekend_start=weekend_start,
        night_end=night_end,
    )
    probs, feats = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            row_spec(nb), row_spec(nb), row_spec(nb),
            row_spec(nb), row_spec(nb), row_spec(nb),
            row_spec(2), row_spec(2),
            pl.BlockSpec((4, n_feat), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(row_spec(1), row_spec(n_feat)),
        out_shape=(
            jax.ShapeDtypeStruct((bsz, 1), jnp.float32),
            jax.ShapeDtypeStruct((bsz, n_feat), jnp.float32),
        ),
        interpret=interpret,
    )(c_bd, c_cnt, c_amt, t_bd, t_cnt, t_frd, ivec, fvec, pvec)
    return probs[:, 0], feats
