"""Background source prefetch — the input-side mirror of the async sink.

PR 3 moved sink writes off the serving loop (``io/sink.py::AsyncSink``);
this module does the same for the *input* half. The round-5 TPU session
measured the device step at ~10 ms per 65k-row batch while the loop
delivered a batch every ~280 ms — the wall was host-side poll + envelope
decode serialized between device steps (the "host/serialization overheads
dominate" failure mode of arXiv:1612.01437, and the stream/compute
overlap argument of the parallel-and-stream accelerator line of work).

:class:`PrefetchSource` wraps any ``poll_batch``/``offsets``/``seek``
source: a producer thread polls (and therefore decodes) ahead of the
loop into a bounded queue, so the loop thread's ``source_poll`` phase
collapses to a dequeue while decode overlaps device compute.

Contracts, in the order people get them wrong:

- **Offsets commit on consumption, not on poll.** ``offsets`` reports
  the position after the last batch *returned from* ``poll_batch`` —
  never the producer's read-ahead position. A checkpoint therefore
  replays prefetched-but-unconsumed batches after a crash instead of
  skipping them; ``commit()`` forwards the consumed offsets to inner
  sources that take them (Kafka), so broker offsets can't lead the
  framework checkpoint either.
- **Errors propagate with their original type.** A producer-side
  failure (a flaky poll, a dead broker) is re-raised on the consumer
  thread at the next ``poll_batch`` — the supervisor's type-based
  ``recover_on`` policy sees exactly what a synchronous poll would have
  thrown.
- **Poison isolation runs unprefetched.** ``set_sync(True)`` stops the
  producer, rewinds the inner source to the consumed position (the
  queued read-ahead is discarded and re-served synchronously), and
  serves polls inline — the supervisor flips this around
  ``_run_poison_isolation`` so diagnosis sees the same batch boundaries
  a replay will.
- **``seek`` fences the producer.** Checkpoint resume stops the current
  producer generation, drops its queue, seeks the inner source, and
  starts a fresh generation; a producer wedged inside a hung poll is
  abandoned with its (orphaned) queue and cannot pollute the new
  generation — the same zombie-fencing stance as
  ``runtime/faults.py``. Prefer a fresh source per incarnation
  (``make_source``) for full fencing, exactly as documented there.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional, Sequence

from real_time_fraud_detection_system_tpu.utils.metrics import get_registry


class _End:
    """Queue sentinel: the inner source returned None (exhausted)."""


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchSource:
    """Poll-and-decode ahead of the serving loop into a bounded queue.

    ``max_batches`` bounds host memory (a stalled loop backpressures the
    producer, never the reverse); queue occupancy rides
    ``rtfds_prefetch_queue_depth`` and consumer blocked-time rides
    ``rtfds_prefetch_wait_seconds_total`` — a prefetcher that can't keep
    the loop fed is visible, not silent.
    """

    def __init__(self, inner, max_batches: int = 4, registry=None):
        if inner is None:
            raise ValueError("PrefetchSource needs an inner source")
        self.inner = inner
        self.depth = max(1, int(max_batches))
        reg = registry if registry is not None else get_registry()
        self._m_depth = reg.gauge(
            "rtfds_prefetch_queue_depth",
            "micro-batches decoded ahead of the serving loop")
        self._m_wait = reg.counter(
            "rtfds_prefetch_wait_seconds_total",
            "loop-thread seconds blocked waiting on the prefetch queue")
        # Consumed position (what checkpoints record). Initialized from
        # the inner source so a zero-batch run checkpoints honestly.
        self._offsets: List[int] = list(inner.offsets)
        self._sync = False
        self._exhausted = False
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start_producer()

    # -- producer (its own generation of stop-event + queue) ------------

    def _start_producer(self) -> None:
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(
            target=self._produce, args=(self._stop, self._q),
            daemon=True, name="rtfds-prefetch")
        self._thread.start()

    def _produce(self, stop: threading.Event, q: "queue.Queue") -> None:
        def put(item) -> bool:
            # bounded put that a generation fence can interrupt
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    self._m_depth.set(q.qsize())
                    return True
                except queue.Full:
                    continue
            return False

        try:
            while not stop.is_set():
                cols = self.inner.poll_batch()
                if stop.is_set():
                    return  # fenced mid-poll: the new generation re-seeks
                if cols is None:
                    put(_End())
                    return
                # Offsets snapshot BELONGS to this batch: consuming it
                # advances the consumed position to exactly here.
                if not put((cols, list(self.inner.offsets))):
                    return
        # rtfdslint: disable=broad-exception-catch (thread-boundary transport: the producer ships the ORIGINAL exception to the consumer thread, which re-raises it typed for the supervisor)
        except BaseException as e:  # re-raised on the consumer thread
            put(_Err(e))

    def _stop_producer(self) -> None:
        """Fence the current producer generation: signal stop, orphan its
        queue (a producer blocked in ``put`` exits via the timeout loop;
        one wedged inside a hung inner poll is abandoned — its late put
        lands in the orphaned queue nothing reads). An abandoned zombie
        still SHARES the inner source: when its hung poll eventually
        releases it consumes (and discards) one batch from the inner
        cursor — the same at-most-one-batch double-fault race
        ``runtime/faults.py`` documents for shared sources, with the
        same fix: give each incarnation a fresh source (``make_source``)
        so a zombie owns a dead private session. Warn-logged so a
        lineage gap after a stall is attributable."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if t.is_alive():
                from real_time_fraud_detection_system_tpu.utils import (
                    get_logger,
                )

                get_logger("prefetch").warning(
                    "prefetch producer did not exit within 5s (inner "
                    "poll wedged); abandoning it. If the hang releases, "
                    "its in-flight poll consumes one batch from the "
                    "shared inner source — prefer a fresh source per "
                    "incarnation (make_source) to fence this entirely")
        self._thread = None

    # -- source protocol (loop thread) ----------------------------------

    def poll_batch(self) -> Optional[dict]:
        if self._sync:
            cols = self.inner.poll_batch()
            if cols is not None:
                self._offsets = list(self.inner.offsets)
            return cols
        if self._exhausted:
            return None
        t0 = time.perf_counter()
        q, thread = self._q, self._thread
        while True:
            try:
                item = q.get(timeout=0.5)
                break
            except queue.Empty:
                if thread is None or not thread.is_alive():
                    # producer died without a sentinel (should not
                    # happen; belt under the braces) — honest end
                    self._exhausted = True
                    return None
        waited = time.perf_counter() - t0
        if waited > 1e-4:  # an uncontended get is ~µs; count only blocks
            self._m_wait.inc(waited)
        self._m_depth.set(q.qsize())
        if isinstance(item, _Err):
            # Original-typed re-raise; recovery seeks (resetting the
            # producer), so this generation stays dead afterwards.
            self._exhausted = True
            raise item.exc
        if isinstance(item, _End):
            self._exhausted = True
            return None
        cols, offs = item
        self._offsets = offs
        return cols

    @property
    def offsets(self) -> List[int]:
        """Position after the last CONSUMED batch (never the producer's
        read-ahead) — what checkpoints must record for replay-not-skip."""
        if self._sync:
            return list(self.inner.offsets)
        return list(self._offsets)

    def seek(self, offsets: Sequence[int]) -> None:
        """Checkpoint resume: fence the producer, seek the inner source,
        restart a fresh generation from the restored position."""
        self._stop_producer()
        self.inner.seek(offsets)
        self._offsets = list(self.inner.offsets)
        self._exhausted = False
        if not self._sync:
            self._start_producer()

    def set_sync(self, flag: bool) -> None:
        """Toggle synchronous (unprefetched) serving.

        ``True`` stops the producer and REWINDS the inner source to the
        consumed position — queued read-ahead is discarded and re-served
        inline, so the caller (poison isolation) sees every unconsumed
        row at the same batch boundaries a checkpoint replay would.
        ``False`` resumes prefetching from wherever consumption stands.
        """
        flag = bool(flag)
        if flag == self._sync:
            return
        if flag:
            self._stop_producer()
            self.inner.seek(self._offsets)
            self._sync = True
            self._exhausted = False
            self._m_depth.set(0)
        else:
            self._sync = False
            self._exhausted = False
            self._start_producer()

    def commit(self) -> None:
        """Forward a broker-side commit with the CONSUMED offsets (the
        producer's read-ahead must never reach the broker: committed
        offsets trail the framework checkpoint, which trails
        consumption). Inner sources without ``commit`` are a no-op; ones
        whose ``commit`` takes no offsets get a plain call only in sync
        mode, where polled == consumed."""
        commit = getattr(self.inner, "commit", None)
        if commit is None:
            return
        import inspect

        try:
            takes_offsets = "offsets" in inspect.signature(
                commit).parameters
        except (TypeError, ValueError):  # builtins/c-impls: be safe
            takes_offsets = False
        if takes_offsets:
            commit(offsets=self._offsets)
        elif self._sync:
            commit()
        # else: skipping the commit is the safe side — the framework
        # checkpoint already persisted the consumed offsets, and a
        # committed read-ahead position could SKIP rows on a replay.

    def close(self) -> None:
        self._stop_producer()
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()
