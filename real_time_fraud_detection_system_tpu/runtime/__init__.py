from real_time_fraud_detection_system_tpu.runtime.sources import (  # noqa: F401
    InProcBroker,
    KafkaSource,
    OwnershipFloorSource,
    PartitionAffineSource,
    RawTableSource,
    ReplaySource,
    SyntheticSource,
    make_kafka_source,
)
from real_time_fraud_detection_system_tpu.runtime.elastic import (  # noqa: F401
    ClusterSignals,
    ElasticConfig,
    ElasticPolicy,
    ResizeFsm,
    fleet_metrics,
    signals_from_snapshots,
)
from real_time_fraud_detection_system_tpu.runtime.cms_exchange import (  # noqa: F401
    SketchExchange,
)
from real_time_fraud_detection_system_tpu.runtime.distributed import (  # noqa: F401
    ProcessTopology,
    bootstrap_distributed,
)
from real_time_fraud_detection_system_tpu.runtime.engine import (  # noqa: F401
    EngineState,
    ScoringEngine,
)
from real_time_fraud_detection_system_tpu.runtime.sharded_engine import (  # noqa: F401
    ShardedScoringEngine,
)
from real_time_fraud_detection_system_tpu.runtime.faults import (  # noqa: F401
    FlakySource,
    FlakyStore,
    HangingSource,
    Heartbeat,
    PoisonRowError,
    PoisonSource,
    RetryPolicy,
    StallError,
    TornStore,
    TransientError,
    corrupt_messages,
    poison_messages,
    run_with_recovery,
    with_retries,
)
from real_time_fraud_detection_system_tpu.runtime.autobatch import (  # noqa: F401
    AutoBatchController,
)
from real_time_fraud_detection_system_tpu.runtime.overload import (  # noqa: F401
    LadderActions,
    OverloadController,
)
from real_time_fraud_detection_system_tpu.runtime.prefetch import (  # noqa: F401
    PrefetchSource,
)
from real_time_fraud_detection_system_tpu.runtime.pipeline import (  # noqa: F401
    run_demo,
)
from real_time_fraud_detection_system_tpu.runtime.feedback import (  # noqa: F401
    FEEDBACK_TOPIC,
    FeatureCache,
    FeedbackLoop,
    KafkaFeedbackSource,
    decode_feedback_envelopes,
    encode_feedback_envelopes,
)
