from real_time_fraud_detection_system_tpu.runtime.sources import (  # noqa: F401
    InProcBroker,
    ReplaySource,
    SyntheticSource,
)
from real_time_fraud_detection_system_tpu.runtime.engine import (  # noqa: F401
    EngineState,
    ScoringEngine,
)
from real_time_fraud_detection_system_tpu.runtime.sharded_engine import (  # noqa: F401
    ShardedScoringEngine,
)
from real_time_fraud_detection_system_tpu.runtime.faults import (  # noqa: F401
    FlakySource,
    Heartbeat,
    RetryPolicy,
    TransientError,
    corrupt_messages,
    run_with_recovery,
    with_retries,
)
from real_time_fraud_detection_system_tpu.runtime.pipeline import (  # noqa: F401
    run_demo,
)
from real_time_fraud_detection_system_tpu.runtime.feedback import (  # noqa: F401
    FEEDBACK_TOPIC,
    FeatureCache,
    FeedbackLoop,
    decode_feedback_envelopes,
    encode_feedback_envelopes,
)
