from real_time_fraud_detection_system_tpu.runtime.sources import (  # noqa: F401
    InProcBroker,
    ReplaySource,
    SyntheticSource,
)
from real_time_fraud_detection_system_tpu.runtime.engine import (  # noqa: F401
    EngineState,
    ScoringEngine,
)
