"""Stream sources: partitioned in-process broker, replay, live synthesis.

The reference's transport is Kafka topics fed by Debezium
(``docker-compose.yml:14-51``); partitioning is its data-parallel unit
(SURVEY §2.3). For dev/test/bench without Docker the framework provides:

- :class:`InProcBroker` — a Kafka-semantics in-process log: topics ×
  partitions, append-only, offset-addressed, key-hash partition assignment.
  Producers/consumers share it; consumers poll (partition, offset) ranges.
- :class:`ReplaySource` — replays a generated :class:`Transactions` table
  through the broker as Debezium envelopes (exercising the codec) or as
  raw columnar slices (the zero-parse benchmark path).
- :class:`SyntheticSource` — paced live generator, the ``datagen`` container
  analogue (``datagen/data_gen.py:116-135``, one tx/10 s demo rate, here
  configurable up to line rate).

A real ``KafkaSource`` (confluent-kafka/kafka-python) plugs in behind the
same ``poll_batch`` interface; the client libraries are not present in this
image, so it is import-gated.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.core.envelope import (
    decode_transaction_envelopes_fast,
    encode_transaction_envelopes,
)
from real_time_fraud_detection_system_tpu.data.generator import (
    Transactions,
)
from real_time_fraud_detection_system_tpu.utils.metrics import get_registry


class _SourceTelemetry:
    """Shared per-source instrumentation: poll latency histogram, rows
    ingested counter, seek/replay counter, and (for sources that know
    their backlog) the ``rtfds_source_lag_rows`` gauge that ``/healthz``
    applies its lag threshold to. Series resolve once at construction."""

    def _init_source_metrics(self, source_kind: str) -> None:
        from real_time_fraud_detection_system_tpu.utils.trace import (
            get_tracer,
        )

        reg = get_registry()
        self._tracer = get_tracer()
        self._source_kind = source_kind
        self._m_poll = reg.histogram(
            "rtfds_source_poll_seconds", "source poll_batch wall time",
            source=source_kind)
        self._m_ingested = reg.counter(
            "rtfds_source_rows_total", "rows ingested", source=source_kind)
        self._m_seeks = reg.counter(
            "rtfds_source_seeks_total",
            "checkpoint-resume / replay seeks", source=source_kind)
        # The lag gauge is registered LAZILY on first set: a source that
        # cannot compute a backlog (Kafka) must not create a permanent-0
        # series, or /healthz's lag threshold would check the fake zero
        # and report healthy while the consumer falls behind. Unlabeled
        # on purpose: /healthz reads it without knowing which source
        # implementation is serving.
        self._m_lag = None

    def _observe_poll(self, t0: float, cols: Optional[dict],
                      lag: Optional[int] = None) -> None:
        t1 = time.perf_counter()
        self._m_poll.observe(t1 - t0)
        n = 0
        if cols is not None:
            n = len(next(iter(cols.values()), ()))
            if n:
                self._m_ingested.inc(n)
        if self._tracer.enabled:
            # Timeline-only (batch=""): the engine's source_poll span
            # carries the batch attribution; with pipelining this poll
            # may serve a LATER batch than the tracer's current one, so
            # claiming the current id would lie. On the Perfetto
            # timeline the span still nests under source_poll by time.
            self._tracer.add_span(f"source/{self._source_kind}", t0, t1,
                                  batch="", rows=n)
        if lag is not None:
            if self._m_lag is None:
                self._m_lag = get_registry().gauge(
                    "rtfds_source_lag_rows",
                    "known backlog: rows available but not yet served")
            self._m_lag.set(lag)


@dataclass
class _Record:
    offset: int
    ts_ms: int
    key: bytes
    value: bytes


class InProcBroker:
    """Partitioned append-only log with Kafka offset semantics."""

    def __init__(self, n_partitions: int = 8):
        self.n_partitions = n_partitions
        self._topics: Dict[str, List[List[_Record]]] = {}
        self._lock = threading.Lock()

    def _topic(self, name: str) -> List[List[_Record]]:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = [[] for _ in range(self.n_partitions)]
            return self._topics[name]

    def partition_of(self, key: bytes) -> int:
        # FNV-1a over the key bytes — stable across runs/processes.
        h = 2166136261
        for byte in key:
            h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
        return h % self.n_partitions

    def produce(
        self, topic: str, key: bytes, value: bytes, ts_ms: int = 0,
        partition: Optional[int] = None,
    ) -> Tuple[int, int]:
        part = self.partition_of(key) if partition is None else partition
        log = self._topic(topic)[part]
        with self._lock:
            off = len(log)
            log.append(_Record(off, ts_ms, key, value))
        return part, off

    def produce_many(
        self, topic: str, keys: Sequence[bytes], values: Sequence[bytes],
        ts_ms: Optional[Sequence[int]] = None,
    ) -> None:
        for i, (k, v) in enumerate(zip(keys, values)):
            self.produce(topic, k, v, ts_ms[i] if ts_ms is not None else 0)

    def poll(
        self, topic: str, partition: int, offset: int, max_records: int
    ) -> List[_Record]:
        log = self._topic(topic)[partition]
        with self._lock:
            return log[offset : offset + max_records]

    def end_offsets(self, topic: str) -> List[int]:
        t = self._topic(topic)
        with self._lock:
            return [len(p) for p in t]


class ReplaySource(_SourceTelemetry):
    """Serves micro-batches from a transactions table.

    ``mode='columnar'`` returns numpy column dicts directly (zero-parse
    benchmark path); ``mode='envelope'`` round-trips rows through Debezium
    JSON envelopes in an :class:`InProcBroker`, exercising decode exactly as
    a Kafka deployment would.
    """

    def __init__(
        self,
        txs: Transactions,
        start_epoch_s: int,
        batch_rows: int = 4096,
        mode: str = "columnar",
        n_partitions: int = 8,
        with_labels: bool = False,
    ):
        self.txs = txs
        self.start_epoch_s = start_epoch_s
        self.batch_rows = batch_rows
        self.mode = mode
        self.with_labels = with_labels
        self.n_partitions = n_partitions
        self._pos = 0
        self._init_source_metrics("replay")
        if mode == "envelope":
            self.broker = InProcBroker(n_partitions)
            t_us = txs.epoch_us(start_epoch_s)
            msgs = encode_transaction_envelopes(
                txs.tx_id, t_us, txs.customer_id, txs.terminal_id,
                txs.amount_cents,
            )
            keys = [str(int(c)).encode() for c in txs.customer_id]
            self.broker.produce_many(
                "debezium.payment.transactions", keys, msgs,
                ts_ms=(t_us // 1000).tolist(),
            )
            self._offsets = [0] * n_partitions

    def poll_batch(self) -> Optional[dict]:
        """Next micro-batch as a column dict (None when exhausted)."""
        t0 = time.perf_counter()
        cols = self._poll_inner()
        if self.mode == "columnar":
            lag = self.txs.n - self._pos
        else:
            lag = sum(self.broker.end_offsets(
                "debezium.payment.transactions")) - sum(self._offsets)
        self._observe_poll(t0, cols, lag=lag)
        return cols

    def _poll_inner(self) -> Optional[dict]:
        if self.mode == "columnar":
            n = self.txs.n
            if self._pos >= n:
                return None
            s, e = self._pos, min(self._pos + self.batch_rows, self.txs.n)
            self._pos = e
            part = self.txs.slice(slice(s, e))
            cols = {
                "tx_id": part.tx_id,
                "tx_datetime_us": part.epoch_us(self.start_epoch_s),
                "customer_id": part.customer_id,
                "terminal_id": part.terminal_id,
                "tx_amount_cents": part.amount_cents,
                "kafka_ts_ms": part.epoch_us(self.start_epoch_s) // 1000,
            }
            if self.with_labels:
                cols["label"] = part.tx_fraud.astype(np.int32)
            return cols

        # envelope mode: round-robin partition polling up to batch_rows
        per = max(1, self.batch_rows // self.n_partitions)
        msgs: List[bytes] = []
        ts: List[int] = []
        for p in range(self.n_partitions):
            recs = self.broker.poll(
                "debezium.payment.transactions", p, self._offsets[p], per
            )
            self._offsets[p] += len(recs)
            msgs += [r.value for r in recs]
            ts += [r.ts_ms for r in recs]
        if not msgs:
            return None
        cols, invalid = decode_transaction_envelopes_fast(msgs, ts)
        if invalid.any():
            keep = ~invalid
            cols = {k: v[keep] for k, v in cols.items()}
        return cols

    @property
    def offsets(self) -> List[int]:
        if self.mode == "columnar":
            return [self._pos]
        return list(self._offsets)

    def seek(self, offsets: Sequence[int]) -> None:
        """Restore consumption position (checkpoint resume)."""
        self._m_seeks.inc()
        if self.mode == "columnar":
            self._pos = int(offsets[0])
        else:
            self._offsets = list(offsets)


class SyntheticSource(_SourceTelemetry):
    """Paced live generator — the ``datagen`` container analogue.

    Yields batches at ``rate_tps`` transactions/second of wall-clock (or as
    fast as possible when 0), drawing from a pre-generated table.
    Telemetry lands under ``source="synthetic"`` (poll latency includes
    the pacing sleep — that IS this source's poll behavior); the inner
    replay cursor is polled via ``_poll_inner`` so rows are not
    double-counted under ``source="replay"``.
    """

    def __init__(
        self,
        txs: Transactions,
        start_epoch_s: int,
        rate_tps: float = 0.0,
        batch_rows: int = 4096,
    ):
        self._replay = ReplaySource(txs, start_epoch_s, batch_rows, "columnar")
        self.rate_tps = rate_tps
        self._init_source_metrics("synthetic")

    def poll_batch(self) -> Optional[dict]:
        t0 = time.perf_counter()
        cols = self._replay._poll_inner()
        if cols is not None and self.rate_tps > 0:
            time.sleep(len(cols["tx_id"]) / self.rate_tps)
        self._observe_poll(t0, cols,
                           lag=self._replay.txs.n - self._replay._pos)
        return cols

    @property
    def offsets(self) -> List[int]:
        return self._replay.offsets

    def seek(self, offsets: Sequence[int]) -> None:
        self._m_seeks.inc()
        # inner seek counts under source="replay" too; its counter exists
        # but stays untouched here (we never call the inner poll_batch)
        self._replay._pos = int(offsets[0])


class RawTableSource(_SourceTelemetry):
    """Stream the persistent raw-transactions table back through the
    engine — backfill / re-score-after-retrain.

    The reference's scorer stream-reads the Iceberg transactions table,
    history included (``fraud_detection.py:91-93``:
    ``readStream.format("iceberg").load("nessie.payment.transactions")``),
    so re-running it after retraining re-scores everything already
    landed. This source gives the framework the same workflow over its
    own day-partitioned Parquet table (:class:`~.io.tables.
    RawTransactionsTable`).

    The table snapshot is loaded once at construction (latest-wins
    across parts), sorted into temporal order — window features require
    time-ordered ingestion — optionally restricted to
    ``[from_day, to_day]`` (inclusive ``YYYY-MM-DD`` strings), then
    served as ``batch_rows`` micro-batches behind the standard
    ``poll_batch``/``offsets``/``seek`` protocol. Rows written to the
    table after construction are not seen (snapshot isolation, matching
    the read_all contract).

    Checkpoint-resume across re-constructions is watermark-guarded:
    ``offsets`` carries ``[pos, n_snapshot, max_ts, max_tx_id]``, and
    ``seek`` verifies the first ``n_snapshot`` sorted rows still match
    that construction-time watermark. Appends beyond the watermark are
    safe (they sort after the snapshot and get served once the resumed
    stream reaches them); late data at-or-below it raises instead of
    silently corrupting the resume positions.
    """

    def __init__(
        self,
        directory: str,
        batch_rows: int = 4096,
        from_day: Optional[str] = None,
        to_day: Optional[str] = None,
    ):
        from real_time_fraud_detection_system_tpu.io.tables import (
            RawTransactionsTable,
        )

        cols = RawTransactionsTable(directory).read_all()
        if not cols:
            raise FileNotFoundError(
                f"no raw-transactions partitions under {directory!r} "
                "(expected tx_date=*/part-*.parquet)"
            )
        if from_day or to_day:
            from real_time_fraud_detection_system_tpu.core.batch import (
                US_PER_DAY,
            )
            from real_time_fraud_detection_system_tpu.utils.timing import (
                date_to_epoch_s,
            )

            def _day_num(s: str) -> int:
                try:
                    return date_to_epoch_s(s) // 86400
                except ValueError as e:
                    raise ValueError(
                        f"bad day filter {s!r} (want YYYY-MM-DD): {e}"
                    ) from None

            days = cols["tx_datetime_us"] // US_PER_DAY
            keep = np.ones(len(days), dtype=bool)
            if from_day:
                keep &= days >= _day_num(from_day)
            if to_day:
                keep &= days <= _day_num(to_day)
            cols = {k: v[keep] for k, v in cols.items()}
        order = np.lexsort((cols["tx_id"], cols["tx_datetime_us"]))
        self._cols = {k: np.ascontiguousarray(v[order])
                      for k, v in cols.items()}
        self.batch_rows = batch_rows
        self._pos = 0
        # Snapshot watermark for checkpoint-resume: offsets are positions
        # into THIS lexsort, so they stay valid across a re-construction
        # only if the first n_snap sorted rows are unchanged. Rows appended
        # later with (ts, tx_id) beyond the watermark sort strictly after
        # every snapshot row (resume correct, new rows served at the end);
        # late data at-or-before it shifts positions — seek() detects that
        # and raises instead of silently skipping/re-serving rows.
        n = len(self._cols["tx_id"])
        if n:
            self._snapshot = (n, int(self._cols["tx_datetime_us"][-1]),
                              int(self._cols["tx_id"][-1]))
        else:
            self._snapshot = (0, -1, -1)
        self._init_source_metrics("raw_table")

    @property
    def n(self) -> int:
        return len(self._cols["tx_id"])

    def poll_batch(self) -> Optional[dict]:
        t0 = time.perf_counter()
        if self._pos >= self.n:
            self._observe_poll(t0, None, lag=0)
            return None
        s, e = self._pos, min(self._pos + self.batch_rows, self.n)
        self._pos = e
        out = {k: v[s:e] for k, v in self._cols.items()}
        # replayed history: event time doubles as the transport timestamp
        out["kafka_ts_ms"] = out["tx_datetime_us"] // 1000
        self._observe_poll(t0, out, lag=self.n - self._pos)
        return out

    @property
    def offsets(self) -> List[int]:
        n_snap, wts, wtx = self._snapshot
        return [self._pos, n_snap, wts, wtx]

    def seek(self, offsets: Sequence[int]) -> None:
        if len(offsets) >= 4:
            _, n_snap, wts, wtx = (int(x) for x in offsets[:4])
            ts = self._cols["tx_datetime_us"]
            tid = self._cols["tx_id"]
            in_snap = (ts < wts) | ((ts == wts) & (tid <= wtx))
            got = int(in_snap.sum())
            if got != n_snap or not bool(in_snap[:got].all()):
                raise ValueError(
                    "RawTableSource resume: the table changed at or below "
                    f"the checkpoint watermark (ts={wts}, tx_id={wtx}): "
                    f"expected {n_snap} snapshot rows, found {got}. Late "
                    "or rewritten data shifts sort positions, so resuming "
                    "by offset would skip or re-serve rows — re-run the "
                    "backfill from scratch (or bound it with "
                    "from_day/to_day)."
                )
        self._m_seeks.inc()
        self._pos = int(offsets[0])


class PartitionAffineSource(_SourceTelemetry):
    """Residue slice of an inner source — multi-host partition-affine
    ingest for sources that have no broker partitions to assign.

    Each fleet process wraps the SAME underlying stream (a replay table,
    a synthetic generator, a raw-table backfill) and serves only the
    rows whose customer residue its :class:`~.distributed.
    ProcessTopology` block owns; the other rows are someone else's
    traffic and are dropped here, host-side, before any decode-adjacent
    work the engine would pay (``rtfds_affine_skipped_rows_total``
    counts them — at production scale the broker's partition assignment
    replaces this wrapper precisely so that polling cost disappears).

    Replay-identical boundaries per owner: the filter is a pure function
    of the inner batch, so a checkpoint resume (``seek`` passes through
    to the inner source, offsets ARE the inner offsets) re-serves
    exactly the same per-process micro-batches — poison bisection and
    sink-lineage fencing work per process, unchanged.
    """

    def __init__(self, inner, topology):
        self.inner = inner
        self.topology = topology
        self._init_source_metrics("affine")
        self._m_skipped = get_registry().counter(
            "rtfds_affine_skipped_rows_total",
            "polled rows owned by another process (residue-sliced "
            "ingest; a broker-partitioned fleet never polls them at "
            "all)", process=str(topology.process_id))

    def poll_batch(self) -> Optional[dict]:
        t0 = time.perf_counter()
        cols = self.inner.poll_batch()
        if cols is not None and len(next(iter(cols.values()), ())):
            mine = self.topology.owns(cols["customer_id"])
            n_skip = int((~mine).sum())
            if n_skip:
                self._m_skipped.inc(n_skip)
                cols = {k: v[mine] for k, v in cols.items()}
        # a fully-filtered batch surfaces as 0 rows, which the engine
        # treats as an idle poll and polls again — the inner cursor has
        # advanced, so the stream still terminates
        self._observe_poll(t0, cols)
        return cols

    @property
    def offsets(self) -> List[int]:
        return list(self.inner.offsets)

    def seek(self, offsets: Sequence[int]) -> None:
        self._m_seeks.inc()
        self.inner.seek(offsets)

    def commit(self, offsets: Optional[Sequence[int]] = None) -> None:
        commit = getattr(self.inner, "commit", None)
        if commit is not None:
            if offsets is None:
                commit()
            else:
                commit(offsets=offsets)

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


class OwnershipFloorSource(_SourceTelemetry):
    """Per-old-owner resume floors after a fleet SHRINK merge.

    When P processes merge into P′ < P, each old process p had its own
    stream cursor; the merged checkpoint can only carry ONE offset, the
    MINIMUM of the per-process floors (anything earlier is scored by
    everyone). Rows between that minimum and old-owner p's floor were
    already scored and sunk by p — re-scoring them would duplicate
    ``tx_id``s in the global sink. This wrapper re-derives each polled
    row's OLD owner (the pre-resize residue block over ``customer_id``)
    and drops the row iff its global stream position is still below that
    owner's floor; once the cursor passes ``max(floors)`` it is pure
    passthrough. Sits INSIDE any :class:`PartitionAffineSource` (floors
    are positions in the shared stream, so they must be applied before
    the new topology's residue filter re-indexes nothing — the affine
    wrapper drops rows without advancing positions).

    Single-cursor sources only (columnar replay / synthetic / raw-table:
    ``offsets == [pos]``); a broker-partitioned fleet carries per-
    partition committed offsets through the resize instead and never
    needs this wrapper.
    """

    def __init__(self, inner, floors: Sequence[int], old_processes: int,
                 old_local_devices: int):
        from real_time_fraud_detection_system_tpu.runtime.distributed import (
            _fold_u32,
        )

        if len(inner.offsets) != 1:
            raise ValueError(
                "OwnershipFloorSource requires a single-cursor inner "
                f"source, got {len(inner.offsets)} offsets")
        if len(floors) != old_processes:
            raise ValueError(
                f"{len(floors)} floors for {old_processes} old processes")
        self.inner = inner
        self.floors = np.asarray([int(f) for f in floors], dtype=np.int64)
        self._hi = int(self.floors.max())
        self._fold = _fold_u32
        self._n_total = old_processes * old_local_devices
        self._l = old_local_devices
        self._init_source_metrics("floor")
        self._m_floor_skipped = get_registry().counter(
            "rtfds_resume_floor_skipped_rows_total",
            "rows dropped on resume because the pre-resize owner "
            "process had already scored them (per-owner resume floors "
            "after a fleet shrink merge)")

    def poll_batch(self) -> Optional[dict]:
        t0 = time.perf_counter()
        pos = int(self.inner.offsets[0])  # global position of next row
        cols = self.inner.poll_batch()
        n = 0 if cols is None else len(next(iter(cols.values()), ()))
        if n and pos < self._hi:
            owner = (self._fold(np.asarray(
                cols["customer_id"], dtype=np.uint32))
                % np.uint32(self._n_total)).astype(np.int64) // self._l
            keep = (pos + np.arange(n, dtype=np.int64)) >= self.floors[owner]
            n_skip = int((~keep).sum())
            if n_skip:
                self._m_floor_skipped.inc(n_skip)
                cols = {k: v[keep] for k, v in cols.items()}
        self._observe_poll(t0, cols)
        return cols

    @property
    def offsets(self) -> List[int]:
        return list(self.inner.offsets)

    def seek(self, offsets: Sequence[int]) -> None:
        self._m_seeks.inc()
        self.inner.seek(offsets)

    def commit(self, offsets: Optional[Sequence[int]] = None) -> None:
        commit = getattr(self.inner, "commit", None)
        if commit is not None:
            if offsets is None:
                commit()
            else:
                commit(offsets=offsets)

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


def raise_for_kafka_error(ck, err) -> bool:
    """Shared poll-error policy for all Kafka consumers in this runtime.

    Returns True for the end-of-partition marker (caller skips it);
    raises ``ConnectionError`` for retriable transport/broker errors (the
    type :func:`~.faults.run_with_recovery`'s default ``recover_on``
    restarts through — and an honest signal for un-supervised callers,
    who must not mistake a dead broker for a quiet topic); raises
    ``KafkaException`` for fatal errors (auth, config)."""
    if getattr(err, "code", lambda: None)() == getattr(
        ck.KafkaError, "_PARTITION_EOF", -191
    ):
        return True
    if getattr(err, "retriable", lambda: False)():
        raise ConnectionError(f"kafka transient error: {err}")
    raise ck.KafkaException(err)


class KafkaSource(_SourceTelemetry):
    """Real Kafka consumer → columnar micro-batches.

    The production ingress of the reference is the Debezium transaction
    topic (``docker-compose.yml:14-34``, consumed by Spark at
    ``kafka_s3_sink_transactions.py:51-56``). This source subscribes to the
    same topic, polls up to ``batch_rows`` Debezium-JSON messages per
    micro-batch, and decodes them in one vectorized pass
    (:func:`decode_transaction_envelopes_fast`) into the engine's column
    dict.

    Offset contract (aligned with :class:`io.checkpoint.Checkpointer`):

    - ``offsets`` is a dense per-partition list of NEXT offsets to consume
      (Kafka commit semantics); ``-1`` marks a partition this consumer has
      never consumed (left to the broker's ``auto.offset.reset``).
    - ``seek(offsets)`` re-assigns those positions — checkpoint resume.
    - ``commit()`` commits the tracked offsets to the broker
      (at-least-once; exactly-once lands in the engine's
      checkpoint + latest-wins dedup, which absorbs replayed rows the
      same way the reference's ROW_NUMBER/MERGE does).

    Auto-commit is disabled: the broker's committed offsets trail the
    framework checkpoint, never lead it, so a crash can only replay —
    never skip — rows.

    Two assignment modes:

    - ``partitions=None`` (default): consumer-group ``subscribe`` with a
      rebalance callback; on assignment, partitions we hold checkpointed
      offsets for are seeked back to them (so a rebalance can't skip
      uncheckpointed rows).
    - explicit ``partitions=[...]``: manual ``assign`` — the
      partition→device-affinity mode used by the sharded engine, where the
      framework owns placement (SURVEY §2.3 item 1).

    ``consumer_factory`` defaults to ``confluent_kafka.Consumer``; tests
    inject a fake ``confluent_kafka`` module via ``sys.modules``.
    """

    TOPIC_DEFAULT = "debezium.payment.transactions"

    def __init__(
        self,
        bootstrap_servers: str,
        topic: str = TOPIC_DEFAULT,
        group_id: str = "rtfds-scorer",
        batch_rows: int = 4096,
        poll_timeout_s: float = 1.0,
        idle_timeout_s: Optional[float] = None,
        partitions: Optional[Sequence[int]] = None,
        n_partitions: Optional[int] = None,
        config: Optional[dict] = None,
        consumer_factory=None,
    ):
        import confluent_kafka as ck

        self._ck = ck
        self.topic = topic
        self.batch_rows = batch_rows
        self.poll_timeout_s = poll_timeout_s
        self.idle_timeout_s = idle_timeout_s
        conf = {
            "bootstrap.servers": bootstrap_servers,
            "group.id": group_id,
            "enable.auto.commit": False,
            "auto.offset.reset": "earliest",
            **(config or {}),
        }
        factory = consumer_factory or ck.Consumer
        self._consumer = factory(conf)
        self._init_source_metrics("kafka")
        self._next: Dict[int, int] = {}  # partition -> next offset
        self._n_partitions = n_partitions
        self._manual = partitions is not None
        if self._manual:
            self._assigned = sorted(int(p) for p in partitions)
            self._consumer.assign(
                [ck.TopicPartition(topic, p) for p in self._assigned]
            )
        else:
            self._assigned = []
            self._consumer.subscribe(
                [topic], on_assign=self._on_assign, on_revoke=self._on_revoke
            )

    # -- rebalance callbacks (subscribe mode) --------------------------
    def _on_assign(self, consumer, tps) -> None:
        for tp in tps:
            p = tp.partition
            if p not in self._assigned:
                self._assigned.append(p)
            if p in self._next:
                # We own the offset state: resume from the checkpointed
                # position, not the group's committed one.
                tp.offset = self._next[p]
        self._assigned.sort()
        consumer.assign(tps)

    def _on_revoke(self, consumer, tps) -> None:
        for tp in tps:
            if tp.partition in self._assigned:
                self._assigned.remove(tp.partition)
        # _next is kept: if the partition comes back we resume correctly,
        # and `offsets` keeps reporting progress made while we owned it.

    # -- source protocol ----------------------------------------------
    def poll_batch(self) -> Optional[dict]:
        """Poll up to ``batch_rows`` messages, decode, return columns.

        Returns whatever arrived within ``poll_timeout_s`` (a partial
        batch keeps latency bounded at low traffic). ``None`` — the
        engine's end-of-stream signal — only when ``idle_timeout_s`` is
        set and no message arrives within it; an unbounded live source
        (the default) returns an empty poll as a zero-row wait instead,
        by polling again on the next engine trigger.
        """
        t0 = time.perf_counter()
        cols = self._poll_inner()
        # no lag gauge: a broker high-watermark query per poll is an
        # extra RPC on the hot path; scrape consumer-group lag from the
        # broker's own exporter instead
        self._observe_poll(t0, cols)
        return cols

    def _poll_inner(self) -> Optional[dict]:
        import time as _time

        msgs: List[bytes] = []
        ts_ms: List[int] = []
        deadline = _time.monotonic() + self.poll_timeout_s
        idle_deadline = (
            _time.monotonic() + self.idle_timeout_s
            if self.idle_timeout_s is not None
            else None
        )
        while len(msgs) < self.batch_rows:
            now = _time.monotonic()
            if msgs and now >= deadline:
                break
            if not msgs and idle_deadline is not None and now >= idle_deadline:
                return None
            msg = self._consumer.poll(
                min(self.poll_timeout_s, 0.1) if msgs else self.poll_timeout_s
            )
            if msg is None:
                if msgs:
                    break
                if idle_deadline is None:
                    break  # empty poll: engine will trigger again
                continue
            err = msg.error()
            if err is not None:
                if getattr(err, "code", lambda: None)() == getattr(
                    self._ck.KafkaError, "_PARTITION_EOF", -191
                ):
                    continue  # end-of-partition marker, not an error
                if msgs:
                    # Never discard buffered rows (their offsets are
                    # already tracked in _next — dropping them here would
                    # turn a transient error into silent row loss when
                    # those offsets get committed). Return the partial
                    # batch; a persistent error re-surfaces on the next
                    # poll with an empty buffer.
                    break
                raise_for_kafka_error(self._ck, err)
            if msg.value() is None:
                # Tombstone (CDC delete). Deletes of transactions don't
                # re-score anything; advance past it.
                self._next[msg.partition()] = msg.offset() + 1
                continue
            self._next[msg.partition()] = msg.offset() + 1
            msgs.append(msg.value())
            t = msg.timestamp()
            ts_ms.append(int(t[1]) if t and t[1] and t[1] > 0 else 0)
        if not msgs:
            if idle_deadline is not None:
                return None
            # Zero-row batch with the decoder's exact column contract
            # (same keys/dtypes as the non-empty path below).
            return decode_transaction_envelopes_fast([], [])[0]
        cols, invalid = decode_transaction_envelopes_fast(msgs, ts_ms)
        if invalid.any():
            keep = ~invalid
            cols = {k: v[keep] for k, v in cols.items()}
        return cols

    @property
    def offsets(self) -> List[int]:
        """Dense next-offset list, length = max partition seen + 1 (or
        ``n_partitions`` when given); -1 = never consumed."""
        n = self._n_partitions
        if n is None:
            seen = list(self._next) + list(self._assigned)
            n = (max(seen) + 1) if seen else 0
        out = [-1] * n
        for p, off in self._next.items():
            if p < n:
                out[p] = off
        return out

    def seek(self, offsets: Sequence[int]) -> None:
        """Restore consumption positions (checkpoint resume).

        Manual-assignment mode re-``assign``s with explicit offsets —
        librdkafka only allows ``seek()`` on a partition whose fetcher has
        started (first ``poll`` after assign), so a resume-before-poll must
        go through ``assign``. Subscribe mode records the offsets; they are
        applied by the rebalance callback on (re-)assignment, and with
        ``seek()`` on partitions already being consumed.
        """
        self._m_seeks.inc()
        ck = self._ck
        for p, off in enumerate(offsets):
            if int(off) >= 0:
                self._next[p] = int(off)
        if self._manual:
            parts = sorted(set(self._assigned) | set(self._next))
            self._consumer.assign([
                ck.TopicPartition(self.topic, p, self._next.get(p, -1001))
                for p in parts
            ])
            self._assigned = parts
            return
        for p in list(self._assigned):
            if p in self._next:
                self._consumer.seek(
                    ck.TopicPartition(self.topic, p, self._next[p])
                )

    def commit(self, offsets: Optional[Sequence[int]] = None) -> None:
        """Commit next-offsets to the broker (post-checkpoint).

        ``offsets`` (dense list, -1 = skip, same layout as the
        ``offsets`` property) overrides the tracked positions — the
        prefetcher passes its CONSUMED offsets here so a broker commit
        never records the producer's read-ahead (committed offsets must
        trail the framework checkpoint, or a crash could skip rows)."""
        ck = self._ck
        if offsets is not None:
            pairs = [(p, int(off)) for p, off in enumerate(offsets)
                     if int(off) >= 0]
        else:
            pairs = sorted(self._next.items())
        tps = [ck.TopicPartition(self.topic, p, off) for p, off in pairs]
        if tps:
            self._consumer.commit(offsets=tps, asynchronous=False)

    def close(self) -> None:
        self._consumer.close()


def make_kafka_source(
    bootstrap_servers: str, **kwargs
) -> "KafkaSource":
    """Factory for the production Kafka ingress (import-gated).

    The confluent-kafka client is not baked into this image; in
    production images it is, and tests inject a fake module.
    """
    try:
        import confluent_kafka  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "confluent-kafka is not installed in this environment; use "
            "InProcBroker/ReplaySource for dev, or install a Kafka client "
            "in production images."
        ) from e
    return KafkaSource(bootstrap_servers, **kwargs)
