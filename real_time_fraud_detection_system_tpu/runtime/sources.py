"""Stream sources: partitioned in-process broker, replay, live synthesis.

The reference's transport is Kafka topics fed by Debezium
(``docker-compose.yml:14-51``); partitioning is its data-parallel unit
(SURVEY §2.3). For dev/test/bench without Docker the framework provides:

- :class:`InProcBroker` — a Kafka-semantics in-process log: topics ×
  partitions, append-only, offset-addressed, key-hash partition assignment.
  Producers/consumers share it; consumers poll (partition, offset) ranges.
- :class:`ReplaySource` — replays a generated :class:`Transactions` table
  through the broker as Debezium envelopes (exercising the codec) or as
  raw columnar slices (the zero-parse benchmark path).
- :class:`SyntheticSource` — paced live generator, the ``datagen`` container
  analogue (``datagen/data_gen.py:116-135``, one tx/10 s demo rate, here
  configurable up to line rate).

A real ``KafkaSource`` (confluent-kafka/kafka-python) plugs in behind the
same ``poll_batch`` interface; the client libraries are not present in this
image, so it is import-gated.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.core.envelope import (
    decode_transaction_envelopes_fast,
    encode_transaction_envelopes,
)
from real_time_fraud_detection_system_tpu.data.generator import (
    Transactions,
)


@dataclass
class _Record:
    offset: int
    ts_ms: int
    key: bytes
    value: bytes


class InProcBroker:
    """Partitioned append-only log with Kafka offset semantics."""

    def __init__(self, n_partitions: int = 8):
        self.n_partitions = n_partitions
        self._topics: Dict[str, List[List[_Record]]] = {}
        self._lock = threading.Lock()

    def _topic(self, name: str) -> List[List[_Record]]:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = [[] for _ in range(self.n_partitions)]
            return self._topics[name]

    def partition_of(self, key: bytes) -> int:
        # FNV-1a over the key bytes — stable across runs/processes.
        h = 2166136261
        for byte in key:
            h = ((h ^ byte) * 16777619) & 0xFFFFFFFF
        return h % self.n_partitions

    def produce(
        self, topic: str, key: bytes, value: bytes, ts_ms: int = 0,
        partition: Optional[int] = None,
    ) -> Tuple[int, int]:
        part = self.partition_of(key) if partition is None else partition
        log = self._topic(topic)[part]
        with self._lock:
            off = len(log)
            log.append(_Record(off, ts_ms, key, value))
        return part, off

    def produce_many(
        self, topic: str, keys: Sequence[bytes], values: Sequence[bytes],
        ts_ms: Optional[Sequence[int]] = None,
    ) -> None:
        for i, (k, v) in enumerate(zip(keys, values)):
            self.produce(topic, k, v, ts_ms[i] if ts_ms is not None else 0)

    def poll(
        self, topic: str, partition: int, offset: int, max_records: int
    ) -> List[_Record]:
        log = self._topic(topic)[partition]
        with self._lock:
            return log[offset : offset + max_records]

    def end_offsets(self, topic: str) -> List[int]:
        t = self._topic(topic)
        with self._lock:
            return [len(p) for p in t]


class ReplaySource:
    """Serves micro-batches from a transactions table.

    ``mode='columnar'`` returns numpy column dicts directly (zero-parse
    benchmark path); ``mode='envelope'`` round-trips rows through Debezium
    JSON envelopes in an :class:`InProcBroker`, exercising decode exactly as
    a Kafka deployment would.
    """

    def __init__(
        self,
        txs: Transactions,
        start_epoch_s: int,
        batch_rows: int = 4096,
        mode: str = "columnar",
        n_partitions: int = 8,
        with_labels: bool = False,
    ):
        self.txs = txs
        self.start_epoch_s = start_epoch_s
        self.batch_rows = batch_rows
        self.mode = mode
        self.with_labels = with_labels
        self.n_partitions = n_partitions
        self._pos = 0
        if mode == "envelope":
            self.broker = InProcBroker(n_partitions)
            t_us = txs.epoch_us(start_epoch_s)
            msgs = encode_transaction_envelopes(
                txs.tx_id, t_us, txs.customer_id, txs.terminal_id,
                txs.amount_cents,
            )
            keys = [str(int(c)).encode() for c in txs.customer_id]
            self.broker.produce_many(
                "debezium.payment.transactions", keys, msgs,
                ts_ms=(t_us // 1000).tolist(),
            )
            self._offsets = [0] * n_partitions

    def poll_batch(self) -> Optional[dict]:
        """Next micro-batch as a column dict (None when exhausted)."""
        if self.mode == "columnar":
            n = self.txs.n
            if self._pos >= n:
                return None
            s, e = self._pos, min(self._pos + self.batch_rows, self.txs.n)
            self._pos = e
            part = self.txs.slice(slice(s, e))
            cols = {
                "tx_id": part.tx_id,
                "tx_datetime_us": part.epoch_us(self.start_epoch_s),
                "customer_id": part.customer_id,
                "terminal_id": part.terminal_id,
                "tx_amount_cents": part.amount_cents,
                "kafka_ts_ms": part.epoch_us(self.start_epoch_s) // 1000,
            }
            if self.with_labels:
                cols["label"] = part.tx_fraud.astype(np.int32)
            return cols

        # envelope mode: round-robin partition polling up to batch_rows
        per = max(1, self.batch_rows // self.n_partitions)
        msgs: List[bytes] = []
        ts: List[int] = []
        for p in range(self.n_partitions):
            recs = self.broker.poll(
                "debezium.payment.transactions", p, self._offsets[p], per
            )
            self._offsets[p] += len(recs)
            msgs += [r.value for r in recs]
            ts += [r.ts_ms for r in recs]
        if not msgs:
            return None
        cols, invalid = decode_transaction_envelopes_fast(msgs, ts)
        if invalid.any():
            keep = ~invalid
            cols = {k: v[keep] for k, v in cols.items()}
        return cols

    @property
    def offsets(self) -> List[int]:
        if self.mode == "columnar":
            return [self._pos]
        return list(self._offsets)

    def seek(self, offsets: Sequence[int]) -> None:
        """Restore consumption position (checkpoint resume)."""
        if self.mode == "columnar":
            self._pos = int(offsets[0])
        else:
            self._offsets = list(offsets)


class SyntheticSource:
    """Paced live generator — the ``datagen`` container analogue.

    Yields batches at ``rate_tps`` transactions/second of wall-clock (or as
    fast as possible when 0), drawing from a pre-generated table.
    """

    def __init__(
        self,
        txs: Transactions,
        start_epoch_s: int,
        rate_tps: float = 0.0,
        batch_rows: int = 4096,
    ):
        self._replay = ReplaySource(txs, start_epoch_s, batch_rows, "columnar")
        self.rate_tps = rate_tps

    def poll_batch(self) -> Optional[dict]:
        import time

        cols = self._replay.poll_batch()
        if cols is not None and self.rate_tps > 0:
            time.sleep(len(cols["tx_id"]) / self.rate_tps)
        return cols

    @property
    def offsets(self) -> List[int]:
        return self._replay.offsets

    def seek(self, offsets: Sequence[int]) -> None:
        self._replay.seek(offsets)


def make_kafka_source(*args, **kwargs):  # pragma: no cover - gated
    """Real Kafka consumer (not available in this image)."""
    try:
        import confluent_kafka  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "confluent-kafka is not installed in this environment; use "
            "InProcBroker/ReplaySource for dev, or install a Kafka client "
            "in production images."
        ) from e
    raise NotImplementedError
