"""Host-side cross-process exchange of terminal CMS aggregates.

The fleet partitions the stream by ``customer_id`` residue, so customer
state is co-partitioned — every key's full history lives on exactly one
process, and the P→1 checkpoint merge sums disjoint contributions
exactly. Terminal ids are NOT co-partitioned: one terminal's traffic
spreads across the whole fleet, so each process's serving
``terminal_cms`` holds only a PARTIAL view of any terminal's counts.
This module closes that gap at checkpoint/resize boundaries without a
network dependency: each process publishes its cumulative LOCAL
contributions as an atomically-renamed npz partial next to the shared
checkpoint root, adopts whatever peer partials are present under the
same newest-day rule as :func:`~..parallel.mesh._merge_sketch`, and —
critically — checkpoints ALWAYS store the partial (locals-only) form, so
``merge_process_states``'s same-day SUM over per-process sketches stays
exact no matter how stale any exchange round was. Resize exactness is
therefore independent of exchange timing; the exchange only improves
SERVING freshness between resizes.

The accounting invariant that makes this safe is an overlay ``O`` of
adopted peer content per process:

- serving logical sketch  ``S = locals ⊕ O`` (newest-day semantics)
- published partial       ``P_self = S ⊖ O``  (locals only, cumulative)
- after a merge M of all partials: install ``M`` into the serving
  sketch and set ``O' = M ⊖ P_self`` — published partials stay
  locals-only forever, so any process may merge any vintage of any
  peer's file at any time (a stale file just means slightly stale peer
  counts until the next round).

``⊖`` is day-guarded subtraction: counts subtract only where slice days
match; a newer-day slice is taken whole (the older content was — or
would have been — zeroed by the ring). On a stacked multi-shard sketch
the peer content is installed into SHARD 0 only: the logical merge over
shards sums same-day shards, so replicating peer content across shards
would multiply it (the warm-start inflation ``_merge_sketch``
documents). Single-local-device fleets — the elastic smoke topology —
serve the full merged view; with more local devices, shards 1+ keep
serving locals-only partials for sketch-tier reads, exactly the
pre-exchange behavior.
"""

from __future__ import annotations

import os
import time
from typing import NamedTuple, Optional

import numpy as np

from real_time_fraud_detection_system_tpu.utils.metrics import get_registry


class _Logical(NamedTuple):
    """Single-layout host-side sketch view: days [ND], tables
    [ND, depth, width] (fraud optional)."""

    days: np.ndarray
    count: np.ndarray
    amount: np.ndarray
    fraud: Optional[np.ndarray]


def _logical_of(cms) -> _Logical:
    """Newest-day merge of a (possibly [n_shards]-stacked) sketch into
    one single-layout numpy view — the host mirror of
    :func:`~..parallel.mesh._merge_sketch`."""
    days = np.asarray(cms.slice_day)
    count = np.asarray(cms.count)
    amount = np.asarray(cms.amount)
    fraud = None if cms.fraud is None else np.asarray(cms.fraud)
    if days.ndim == 1:
        return _Logical(days.copy(), count.copy(), amount.copy(),
                        None if fraud is None else fraud.copy())
    max_day = days.max(axis=0)
    fresh = (days == max_day[None]).astype(count.dtype)[..., None, None]
    return _Logical(
        max_day,
        (count * fresh).sum(axis=0),
        (amount * fresh).sum(axis=0),
        None if fraud is None else (fraud * fresh).sum(axis=0))


def _subtract(a: _Logical, b: _Logical) -> _Logical:
    """Day-guarded ``a ⊖ b``: subtract counts where slice days match,
    keep ``a`` whole where its day is newer (``b``'s older content was
    retired by the ring). ``b`` newer than ``a`` cannot arise from this
    module's invariants (``a`` is always a superset merge) and reads as
    no-subtraction."""
    sub = (a.days == b.days)[..., None, None].astype(a.count.dtype)

    def tbl(x, y):
        if x is None:
            return None
        return x - (y * sub if y is not None else 0.0)

    return _Logical(a.days.copy(), tbl(a.count, b.count),
                    tbl(a.amount, b.amount), tbl(a.fraud, b.fraud))


def _merge(parts) -> _Logical:
    """Newest-day merge over logical sketches: per slice, take the
    newest day stamp and SUM the holders (disjoint locals-only partials
    make same-day sums exact)."""
    days = np.stack([p.days for p in parts])
    max_day = days.max(axis=0)
    fresh = (days == max_day[None]).astype(parts[0].count.dtype)

    def tbl(name):
        first = getattr(parts[0], name)
        if first is None:
            return None
        return sum(getattr(p, name) * fresh[i][..., None, None]
                   for i, p in enumerate(parts))

    return _Logical(max_day, tbl("count"), tbl("amount"), tbl("fraud"))


def _is_zero(lg: Optional[_Logical]) -> bool:
    return lg is None or bool((lg.days < 0).all())


class SketchExchange:
    """One process's half of the file-based terminal-CMS exchange.

    ``root`` is a directory shared by the fleet (next to the checkpoint
    root). :meth:`exchange` publishes this process's partial and merges
    peers'; :meth:`checkpoint_cms` strips adopted peer content back out
    so the checkpointed sketch is locals-only. ``timeout_s`` bounds how
    long a round waits for missing peer files — rounds that merge a
    subset count as ``outcome="partial"`` and the next round catches
    up (published partials are cumulative)."""

    def __init__(self, root: str, process_id: int, n_processes: int,
                 timeout_s: float = 2.0):
        self.root = root
        self.process_id = int(process_id)
        self.n_processes = int(n_processes)
        self.timeout_s = float(timeout_s)
        os.makedirs(root, exist_ok=True)
        self._seq = 0
        self._overlay: Optional[_Logical] = None
        reg = get_registry()
        self._m_rounds = {
            o: reg.counter(
                "rtfds_cms_exchange_rounds_total",
                "terminal-sketch exchange rounds (merged = every peer "
                "partial present; partial = some peers missing within "
                "the timeout — cumulative partials make the next round "
                "catch up)", outcome=o)
            for o in ("merged", "partial")}

    # -- wire format -------------------------------------------------------

    def _path(self, pid: int) -> str:
        return os.path.join(self.root, f"cms-p{pid:02d}.npz")

    def _publish(self, part: _Logical) -> None:
        tmp = self._path(self.process_id) + ".tmp"
        payload = {"seq": np.int64(self._seq), "days": part.days,
                   "count": part.count, "amount": part.amount}
        if part.fraud is not None:
            payload["fraud"] = part.fraud
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(self.process_id))

    def _load_peer(self, pid: int) -> Optional[_Logical]:
        try:
            with np.load(self._path(pid)) as z:
                return _Logical(z["days"], z["count"], z["amount"],
                                z["fraud"] if "fraud" in z.files else None)
        except (OSError, ValueError, KeyError):
            return None

    # -- rounds ------------------------------------------------------------

    def exchange(self, cms) -> Optional[_Logical]:
        """Run one exchange round against the serving sketch ``cms``
        (the engine's ``terminal_cms`` pytree). Returns the merged
        logical view to install (via :func:`install_logical`), or None
        when there is nothing to adopt (single process, or no peer
        content yet)."""
        self._seq += 1
        local = _logical_of(cms)
        p_self = local if self._overlay is None \
            else _subtract(local, self._overlay)
        self._publish(p_self)
        peers = [p for p in range(self.n_processes)
                 if p != self.process_id]
        parts = {self.process_id: p_self}
        deadline = time.monotonic() + self.timeout_s
        while True:
            for p in peers:
                if p not in parts:
                    got = self._load_peer(p)
                    if got is not None:
                        parts[p] = got
            if len(parts) > self.n_processes - 1 or \
                    time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        complete = len(parts) == self.n_processes
        self._m_rounds["merged" if complete else "partial"].inc()
        if len(parts) == 1:
            # nothing from any peer: serving state is already exact
            # locals (overlay unchanged — prior adoptions still stand)
            return None
        merged = _merge(list(parts.values()))
        self._overlay = _subtract(merged, p_self)
        return merged

    def checkpoint_cms(self, cms):
        """The locals-only form of the serving sketch for a checkpoint:
        adopted peer content (the overlay) subtracted back out of shard
        0 — the shard it was installed into. Returns None when no peer
        content was ever adopted (checkpoint the state as-is)."""
        if _is_zero(self._overlay):
            return None
        days = np.asarray(cms.slice_day)
        if days.ndim == 1:
            part = _subtract(_logical_of(cms), self._overlay)
            return cms._replace(
                slice_day=part.days.astype(days.dtype),
                count=part.count, amount=part.amount, fraud=part.fraud)
        shard0 = _Logical(
            days[0], np.asarray(cms.count)[0], np.asarray(cms.amount)[0],
            None if cms.fraud is None else np.asarray(cms.fraud)[0])
        part = _subtract(shard0, self._overlay)

        def put0(stack, new):
            if stack is None:
                return None
            out = np.asarray(stack).copy()
            out[0] = new
            return out

        return cms._replace(
            slice_day=put0(days, part.days.astype(days.dtype)),
            count=put0(cms.count, part.count),
            amount=put0(cms.amount, part.amount),
            fraud=None if cms.fraud is None else put0(cms.fraud,
                                                      part.fraud))


def install_logical(cms, merged: _Logical):
    """Install a merged logical view into the serving sketch layout.

    Unstacked sketches adopt the merged view wholesale. Stacked
    ([n_shards]-leading) sketches put the whole merged view in SHARD 0
    and retire other shards' stale slices (day < merged day → zeroed at
    the merged day, mirroring what the ring would have done had that
    day's traffic reached the shard); same-day content on shards 1+ is
    already counted inside ``merged``, so shard 0 holds ``merged`` MINUS
    those shards' same-day contributions to keep the cross-shard sum
    exact. Returns numpy leaves; the caller re-places them on device."""
    days = np.asarray(cms.slice_day)
    if days.ndim == 1:
        return cms._replace(
            slice_day=merged.days.astype(days.dtype),
            count=merged.count.astype(np.asarray(cms.count).dtype),
            amount=merged.amount.astype(np.asarray(cms.amount).dtype),
            fraud=None if cms.fraud is None else merged.fraud)

    n = days.shape[0]
    new_days = days.copy()
    count = np.asarray(cms.count).copy()
    amount = np.asarray(cms.amount).copy()
    fraud = None if cms.fraud is None else np.asarray(cms.fraud).copy()
    stale = days < merged.days[None]  # [n, ND]
    for d in range(1, n):
        idx = np.where(stale[d])[0]
        if idx.size:
            new_days[d, idx] = merged.days[idx]
            count[d, idx] = 0.0
            amount[d, idx] = 0.0
            if fraud is not None:
                fraud[d, idx] = 0.0
    # shard 0 := merged ⊖ (same-day content living on shards 1+), so the
    # cross-shard same-day SUM reproduces exactly ``merged``
    same = (new_days[1:] == merged.days[None]).astype(
        merged.count.dtype)[..., None, None]
    rest = _Logical(
        merged.days,
        (count[1:] * same).sum(axis=0),
        (amount[1:] * same).sum(axis=0),
        None if fraud is None else (fraud[1:] * same).sum(axis=0))
    shard0 = _subtract(merged, _Logical(merged.days, rest.count,
                                        rest.amount, rest.fraud))
    new_days[0] = merged.days
    count[0] = shard0.count
    amount[0] = shard0.amount
    if fraud is not None:
        fraud[0] = shard0.fraud
    return cms._replace(slice_day=new_days.astype(days.dtype),
                        count=count, amount=amount, fraud=fraud)
