"""Labeled-feedback topic → online-SGD updates (BASELINE.json config 4).

In production, fraud labels arrive days after the transaction (chargebacks,
investigations) on their own stream — the reference models this delay in the
offline features (7-day terminal-risk shift,
``feature_transformation.ipynb · cell 25``) but has no online learning at
all (its only live successor is the dormant torch training loop,
``shared_functions.py:1312-1707``). Here the loop is closed:

1. the :class:`~.engine.ScoringEngine` caches each scored row's feature
   vector in a bounded :class:`FeatureCache` (tx_id → float32[15]);
2. label events ``{tx_id, label}`` arrive on a ``payment.feedback`` topic
   (:func:`encode_feedback_envelopes` / :func:`decode_feedback_envelopes`);
3. :class:`FeedbackLoop` polls the topic, joins labels to cached features,
   and applies one jitted SGD step per poll via
   :meth:`~.engine.ScoringEngine.apply_feedback` — gradients on the SAME
   loss the in-band online path uses, padded to fixed buckets to keep the
   jit cache warm.

The join is by tx_id. Duplicate/replayed label events are safe: the cache
tracks which cached transactions already had their label landed in the
risk-window state (``mark_labeled``), so the state update — which is an
additive scatter and NOT naturally idempotent — runs at most once per cached
transaction; rows whose label arrived in-band at scoring time are marked at
insert. Duplicate SGD updates (for rows still cached) are likewise skipped
with the same mask. Labels for evicted rows always miss, so nothing is ever
double-counted.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.features.spec import N_FEATURES
from real_time_fraud_detection_system_tpu.ops.dedup import latest_wins_mask_np
from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import get_registry

log = get_logger("feedback")

FEEDBACK_TOPIC = "payment.feedback"


def encode_feedback_envelopes(
    tx_ids: Sequence[int],
    labels: Sequence[int],
    ts_ms: int = 0,
) -> List[bytes]:
    """Label events as minimal JSON envelopes (no Debezium wrapper: the
    feedback stream is app-produced, not CDC)."""
    return [
        json.dumps(
            {"tx_id": int(t), "label": int(y), "ts_ms": int(ts_ms)},
            separators=(",", ":"),
        ).encode()
        for t, y in zip(tx_ids, labels)
    ]


def decode_feedback_envelopes(
    messages: Iterable[bytes],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """→ (tx_ids int64 [n], labels int32 [n], ts_ms int64 [n]); malformed
    events dropped. A missing/bad ``ts_ms`` defaults to 0 (not a decode
    failure — only tx_id and label are required)."""
    ids: List[int] = []
    ys: List[int] = []
    ts: List[int] = []
    for m in messages:
        try:
            d = json.loads(m)
            # Parse required fields before appending any, or a message with
            # a valid tx_id but bad label would misalign the lists.
            t, y = int(d["tx_id"]), int(d["label"])
        except (ValueError, KeyError, TypeError):
            continue
        try:
            s = int(d.get("ts_ms", 0))
        except (ValueError, TypeError):
            s = 0
        ids.append(t)
        ys.append(y)
        ts.append(s)
    return (np.asarray(ids, dtype=np.int64),
            np.asarray(ys, dtype=np.int32),
            np.asarray(ts, dtype=np.int64))


class FeatureCache:
    """Bounded tx_id → feature-row cache, direct-mapped (slot = tx_id mod
    capacity), fully vectorized — zero Python-per-row cost on the scoring
    hot path.

    The scorer inserts every row it scores; the feedback join looks rows up
    when their labels arrive. Capacity bounds host memory (default 1M rows
    × 15 f32 ≈ 60 MB). A colliding insert evicts the previous occupant —
    with the generator's sequential tx_ids that is exactly a sliding window
    of the most recent ``capacity`` transactions; evicted rows miss and the
    loop skips their labels (too old to learn from cheaply).
    """

    def __init__(self, capacity: int = 1_000_000,
                 n_features: int = N_FEATURES):
        self.capacity = int(capacity)
        self._feat = np.zeros((self.capacity, n_features), dtype=np.float32)
        self._ids = np.full(self.capacity, -1, dtype=np.int64)
        # Telemetry: shadow/feedback quality silently degrades when
        # labeled rows miss this cache (their labels are dropped, so the
        # live precision/recall windows starve) — the operator needs the
        # occupancy/eviction/hit-rate picture, not a guess. Occupancy is
        # tracked incrementally (a 1M-slot scan per batch would not be).
        reg = get_registry()
        reg.gauge("rtfds_feature_cache_capacity",
                  "feature cache slot capacity").set(self.capacity)
        self._g_occupancy = reg.gauge(
            "rtfds_feature_cache_occupancy",
            "feature cache slots currently holding a scored row")
        self._m_evictions = reg.counter(
            "rtfds_feature_cache_evictions_total",
            "cached rows overwritten by a colliding insert before their "
            "label arrived (labels for evicted rows are dropped)")
        self._m_lookups = {
            o: reg.counter(
                "rtfds_feature_cache_lookups_total",
                "feedback label → cache joins by outcome (a rising miss "
                "share means labels arrive after eviction: raise "
                "capacity)", outcome=o)
            for o in ("hit", "miss")
        }
        self._occupancy = 0
        # Aux columns for state-level feedback (terminal risk windows need
        # the original transaction's terminal + day, features/online.py::
        # apply_feedback).
        self._terminal = np.zeros(self.capacity, dtype=np.int64)
        self._day = np.zeros(self.capacity, dtype=np.int32)
        # True once this transaction's label has been landed in the risk
        # state (either in-band at scoring time or via a feedback event) —
        # the idempotence guard for the additive state scatter.
        self._labeled = np.zeros(self.capacity, dtype=bool)

    def __len__(self) -> int:
        return int((self._ids >= 0).sum())

    def put_batch(
        self,
        tx_ids: np.ndarray,
        features: np.ndarray,
        terminal_ids: np.ndarray = None,
        days: np.ndarray = None,
        labeled: np.ndarray = None,
    ) -> None:
        """Insert scored rows. ``labeled`` marks rows whose label was known
        in-band at scoring time (already scattered into the risk state).
        Aux columns are always (over)written so an evicting insert can
        never leave the previous occupant's terminal/day bound to the new
        tx_id."""
        tx_ids = np.asarray(tx_ids, dtype=np.int64)
        n = len(tx_ids)
        slots = tx_ids % self.capacity
        if n:
            # Occupancy/eviction accounting against the PRE-insert state,
            # per distinct slot (fancy assignment below is last-wins for
            # colliding slots within one batch — mirror that): a slot
            # that was empty fills, a slot holding a DIFFERENT live tx
            # evicts it (that row's label can now never land).
            uslots, first_rev = np.unique(slots[::-1], return_index=True)
            new_ids = tx_ids[n - 1 - first_rev]
            prev = self._ids[uslots]
            self._occupancy += int((prev < 0).sum())
            self._g_occupancy.set(self._occupancy)
            evicted = int(((prev >= 0) & (prev != new_ids)).sum())
            if evicted:
                self._m_evictions.inc(evicted)
        self._ids[slots] = tx_ids
        self._feat[slots] = features
        self._terminal[slots] = (
            np.zeros(n, np.int64) if terminal_ids is None else terminal_ids
        )
        self._day[slots] = np.zeros(n, np.int32) if days is None else days
        self._labeled[slots] = (
            np.zeros(n, bool) if labeled is None else labeled
        )

    def mark_labeled(self, tx_ids: np.ndarray) -> None:
        """Record that these transactions' labels reached the risk state."""
        tx_ids = np.asarray(tx_ids, dtype=np.int64)
        slots = tx_ids % self.capacity
        own = (self._ids[slots] == tx_ids) & (tx_ids >= 0)
        self._labeled[slots[own]] = True

    def get_batch(
        self, tx_ids: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """→ (features [m, F], hit_mask [n]) for the cached subset."""
        feats, _, _, hit, _ = self.get_batch_full(tx_ids)
        return feats, hit

    def get_batch_full(
        self, tx_ids: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """→ (features [m, F], terminal_ids [m], days [m], hit_mask [n],
        already_labeled [m])."""
        tx_ids = np.asarray(tx_ids, dtype=np.int64)
        slots = tx_ids % self.capacity
        # tx_ids < 0 would alias the empty-slot sentinel: always a miss.
        hit = (self._ids[slots] == tx_ids) & (tx_ids >= 0)
        n_hit = int(hit.sum())
        if n_hit:
            self._m_lookups["hit"].inc(n_hit)
        if len(tx_ids) - n_hit:
            self._m_lookups["miss"].inc(len(tx_ids) - n_hit)
        sel = slots[hit]
        return (self._feat[sel], self._terminal[sel], self._day[sel], hit,
                self._labeled[sel])


class KafkaFeedbackSource:
    """Production feedback-topic ingress (confluent-kafka, import-gated).

    Exposes ``poll_messages(max_events) → list[bytes]`` — the transport
    hook :class:`FeedbackLoop` uses when present — over a consumer-group
    subscription. Delivery is at-least-once: auto-commit is DISABLED and
    :class:`FeedbackLoop` calls :meth:`commit` only after a drained batch
    has been applied, so a crash between poll and apply replays the
    labels instead of dropping them; the loop's idempotence
    (``mark_labeled`` + latest-wins dedup) absorbs the replays. Transient
    broker errors raise ``ConnectionError`` (the same escalation policy
    as the transaction :class:`~.sources.KafkaSource`) — a dead broker
    must not masquerade as a quiet topic.
    """

    def __init__(self, bootstrap_servers: str, topic: str = FEEDBACK_TOPIC,
                 group_id: str = "rtfds-feedback",
                 poll_timeout_s: float = 0.2, config: dict = None,
                 consumer_factory=None):
        import confluent_kafka as ck

        self._ck = ck
        self.topic = topic
        conf = {
            "bootstrap.servers": bootstrap_servers,
            "group.id": group_id,
            "enable.auto.commit": False,
            "auto.offset.reset": "earliest",
            **(config or {}),
        }
        factory = consumer_factory or ck.Consumer
        self._consumer = factory(conf)
        self._consumer.subscribe([topic])
        self.poll_timeout_s = poll_timeout_s

    def poll_messages(self, max_events: int) -> List[bytes]:
        from real_time_fraud_detection_system_tpu.runtime.sources import (
            raise_for_kafka_error,
        )

        out: List[bytes] = []
        while len(out) < max_events:
            msg = self._consumer.poll(self.poll_timeout_s if not out else 0.0)
            if msg is None:
                break
            err = msg.error()
            if err is not None:
                raise_for_kafka_error(self._ck, err)  # EOF → skip
                continue
            if msg.value() is not None:
                out.append(msg.value())
        return out

    def commit(self) -> None:
        """Commit consumed positions (called by the loop AFTER apply)."""
        self._consumer.commit(asynchronous=False)

    def close(self) -> None:
        self._consumer.close()


class FeedbackLoop:
    """Polls the feedback topic and applies SGD updates to the engine.

    One instance per engine; call :meth:`poll_and_apply` from the host
    loop, BETWEEN micro-batches. The engine's state is not synchronized —
    calling from another thread races with ``process_batch``'s
    read-modify-write of ``state.params`` and can silently drop updates.

    ``broker`` is either an :class:`~.sources.InProcBroker` (dev/test) or
    any object with ``poll_messages(max_events) → list[bytes]`` — e.g.
    :class:`KafkaFeedbackSource` in production.

    ``cache`` defaults to the engine's own ``feature_cache``.
    """

    def __init__(self, engine, broker, cache: FeatureCache = None,
                 topic: str = FEEDBACK_TOPIC, max_events: int = 65536,
                 auto_commit: bool = True):
        self.engine = engine
        self.broker = broker
        self.cache = cache if cache is not None else engine.feature_cache
        if self.cache is None:
            raise ValueError(
                "FeedbackLoop needs a FeatureCache: pass one here or "
                "construct the engine with feature_cache="
            )
        self.topic = topic
        self.max_events = max_events
        # auto_commit=False defers broker commits to an external caller —
        # the engine serving loop sets this when a checkpointer is in
        # play, so committed feedback offsets TRAIL the state checkpoint
        # (labels applied since the last checkpoint must be redelivered
        # after a crash; mark_labeled idempotence absorbs the replay).
        self.auto_commit = auto_commit
        self._offsets = (
            [0] * broker.n_partitions
            if hasattr(broker, "n_partitions") else []
        )
        # Decomposition: events == duplicates + missed + (cache hits);
        # applied ⊆ hits (the rest were already labeled or label < 0).
        self.stats = {"events": 0, "applied": 0, "missed": 0,
                      "duplicates": 0}
        # Registry twin of self.stats (process-lifetime, scrapeable)
        # with DISJOINT outcome labels so sum() over the family equals
        # total events: applied + skipped (cache hit, but already
        # labeled or label < 0) + missed (evicted/never scored) +
        # duplicates (within-poll dedup). A rising missed share is the
        # operator's cue that labels arrive after cache eviction (raise
        # FeatureCache capacity).
        reg = get_registry()
        self._m_stats = {
            k: reg.counter("rtfds_feedback_events_total",
                           "feedback label events by disjoint outcome",
                           outcome=k)
            for k in ("applied", "skipped", "missed", "duplicates")
        }

    def _drain(self) -> List[bytes]:
        poll_messages = getattr(self.broker, "poll_messages", None)
        if poll_messages is not None:
            return poll_messages(self.max_events)
        msgs: List[bytes] = []
        for p in range(self.broker.n_partitions):
            recs = self.broker.poll(self.topic, p, self._offsets[p],
                                    self.max_events)
            self._offsets[p] += len(recs)
            msgs += [r.value for r in recs]
        return msgs

    def poll_and_apply(self) -> int:
        """Drain available label events; returns number of rows learned."""
        from real_time_fraud_detection_system_tpu.utils.trace import (
            get_tracer,
        )

        tracer = get_tracer()
        with tracer.span("feedback_poll"):
            msgs = self._drain()
        if not msgs:
            return 0
        # its own span (attributed to the current batch's trace id): a
        # label burst landing between device steps is serving latency
        # the per-phase decomposition alone cannot explain
        with tracer.span("feedback_apply", events=len(msgs)):
            applied = self._apply(msgs)
            # At-least-once transports (KafkaFeedbackSource) commit only
            # after apply succeeded: a crash in between replays, never
            # drops.
            if self.auto_commit:
                self.commit()
        return applied

    def commit(self) -> None:
        """Commit consumed feedback offsets (transports that have them)."""
        commit = getattr(self.broker, "commit", None)
        if commit is not None:
            commit()

    def close(self) -> None:
        """Close the underlying transport session (if it has one)."""
        close = getattr(self.broker, "close", None)
        if close is not None:
            close()

    def _apply(self, msgs: List[bytes]) -> int:
        tx_ids, labels, ts_ms = decode_feedback_envelopes(msgs)
        self.stats["events"] += len(tx_ids)
        if len(tx_ids):
            # Within-poll dedup, latest-wins: the `done` guard below only
            # protects across polls (mark_labeled runs only after apply), so
            # a tx_id appearing twice in one drained batch would run the
            # additive fraud scatter + SGD step once per copy. Winner is
            # the greatest event ts_ms (drain position breaks ties) — NOT
            # bare drain position, which across a multi-partition topic
            # orders by partition number, not recency. Same latest-wins
            # rule and helper as the ingest MERGE path.
            keep = latest_wins_mask_np(tx_ids, ts_ms)
            dup = int(len(tx_ids) - keep.sum())
            self.stats["duplicates"] += dup
            self._m_stats["duplicates"].inc(dup)
            tx_ids, labels = tx_ids[keep], labels[keep]
        shadow = getattr(self.engine, "shadow", None)
        if shadow is not None and len(tx_ids):
            # Join the labels to BOTH models' cached decisions (the
            # shadow keeps its own tx_id → (champion, candidate) score
            # cache): this is what makes rtfds_live_precision/recall
            # live. Its cache consumes each entry once, so re-delivered
            # labels can't double-count the confusion windows.
            shadow.observe_labels(tx_ids, labels)
        feats, term_ids, days, hit, done = self.cache.get_batch_full(tx_ids)
        n_hit = int(hit.sum())
        self.stats["missed"] += len(tx_ids) - n_hit
        self._m_stats["missed"].inc(len(tx_ids) - n_hit)
        if n_hit == 0:
            return 0
        # Idempotence: rows whose label already reached the state (in-band
        # at scoring time, or an earlier feedback event) are skipped — the
        # state scatter is additive and must run at most once per tx.
        fresh = (labels[hit] >= 0) & ~done
        self._m_stats["skipped"].inc(n_hit - int(fresh.sum()))
        if not fresh.any():
            return 0
        y = labels[hit][fresh]
        # 1) state update: land the fraud labels in the terminal risk
        #    windows (delay-shifted queries will see them, matching the
        #    reference's delayed-risk semantics). Works for EVERY model
        #    kind — risk features are model-independent.
        self.engine.apply_state_feedback(term_ids[fresh], days[fresh], y)
        # 2) model update (SGD on the cached serving features), only for
        #    differentiable kinds — tree ensembles learn via retraining.
        if self.engine.supports_online_sgd:
            self.engine.apply_feedback(feats[fresh], y)
        # 3) streaming learner tap: the SAME (raw features, label) rows
        #    the champion just learned from go to the candidate's replay
        #    window — one bounded-queue enqueue, never a block.
        tap = getattr(self.engine, "feedback_tap", None)
        if tap is not None:
            tap(feats[fresh], y)
        self.cache.mark_labeled(tx_ids[hit][fresh])
        n_labeled = int(len(y))
        self.stats["applied"] += n_labeled
        self._m_stats["applied"].inc(n_labeled)
        return n_labeled
