"""Multi-host serving topology: process bootstrap + shard ownership.

The reference scales by adding Spark executors behind one Kafka topic
(SURVEY §2.3); here the unit of scale-out is an OS process (one per TPU
host), each serving a ``ShardedScoringEngine`` over its LOCAL device
mesh. The classic risk is distributed coordination cost eating the
speedup (PAPERS: *Understanding and Optimizing the Performance of
Distributed ML Applications on Apache Spark*); this module's answer is
to make the host plane embarrassingly parallel:

- **Residue-block ownership**: the global shard space has
  ``n_shards_total = num_processes × local_devices`` shards; process p
  owns the contiguous residue block ``key % n_total ∈ [p·L, (p+1)·L)``.
  Because ``p·L ≡ 0 (mod L)``, a key in p's block satisfies
  ``key % L == (key % n_total) − p·L`` — the per-process engine's
  internal ``key % L`` placement lands each key on exactly the device
  the global ``key % n_total`` layout would, so the fleet's shard
  layout is the single-engine layout cut into process blocks and the
  engine runs UNCHANGED.
- **Partition-affine ingest**: each process polls only the traffic its
  residues own (:class:`~.sources.PartitionAffineSource` for residue
  slices, broker partition blocks for Kafka), so no row ever crosses a
  process boundary on the host plane; the in-step owner exchange stays
  on the device fabric (local ICI today; DCN×ICI once the backend has
  cross-process collectives — see
  :func:`~..parallel.mesh.make_process_mesh`).

:func:`bootstrap_distributed` wires ``jax.distributed.initialize`` from
:class:`~..config.DistributedConfig` (the ``--coordinator /
--num-processes / --process-id`` flags) and returns the
:class:`ProcessTopology` every layer threads: the engine labels its
shards globally, sources slice their polls, checkpoints stamp the
writer's topology.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from real_time_fraud_detection_system_tpu.config import DistributedConfig


def _fold_u32(ids: np.ndarray) -> np.ndarray:
    """uint32 key fold (``core.batch.fold_key``, re-derived here to keep
    this module import-light for the launcher): identity for ids <
    2**32, so residue math matches the host partitioner's raw modulo on
    every realistic id space."""
    v = np.asarray(ids).astype(np.uint64)
    return ((v ^ (v >> np.uint64(32)))
            & np.uint64(0xFFFFFFFF)).astype(np.uint32)


@dataclass(frozen=True)
class ProcessTopology:
    """One process's place in the fleet — the residue-block ownership
    contract shared by ingest, the engine, checkpoints and telemetry.

    ``strict_affinity``: when True the engine refuses polled rows whose
    customer residue it does not own (a mis-wired launcher fails fast
    instead of silently splitting a key's history across processes).
    """

    n_processes: int
    process_id: int
    local_devices: int
    coordinated: bool = False  # jax.distributed actually initialized
    strict_affinity: bool = True

    def __post_init__(self):
        if self.n_processes < 1:
            raise ValueError(
                f"n_processes must be >= 1, got {self.n_processes}")
        if not 0 <= self.process_id < self.n_processes:
            raise ValueError(
                f"process_id {self.process_id} out of range for "
                f"{self.n_processes} process(es)")
        if self.local_devices < 1:
            raise ValueError(
                f"local_devices must be >= 1, got {self.local_devices}")

    # -- the shard-space geometry ---------------------------------------

    @property
    def n_shards_total(self) -> int:
        return self.n_processes * self.local_devices

    @property
    def shard_offset(self) -> int:
        """Global id of this process's first local shard: local shard j
        serves global shard ``shard_offset + j`` — and, by the
        residue-block construction, exactly the keys the single
        (n_total)-device engine would route to that global shard."""
        return self.process_id * self.local_devices

    @property
    def owned_shards(self) -> range:
        return range(self.shard_offset,
                     self.shard_offset + self.local_devices)

    def owner_process(self, ids: np.ndarray) -> np.ndarray:
        """Owning process id per key (uint32-folded, matching the
        engine's device-side key domain)."""
        res = _fold_u32(ids) % np.uint32(self.n_shards_total)
        return (res // np.uint32(self.local_devices)).astype(np.int64)

    def owns(self, ids: np.ndarray) -> np.ndarray:
        """Boolean mask: which rows' customer keys this process owns."""
        return self.owner_process(ids) == self.process_id

    def kafka_partitions(self, n_partitions: int) -> List[int]:
        """The broker partitions this process consumes: contiguous
        blocks, mirroring the residue blocks (partition-affine ingest —
        a customer's rows stay in one partition, hence one process).
        Every partition is owned by exactly one process; remainders go
        to the low process ids."""
        if n_partitions < self.n_processes:
            raise ValueError(
                f"{n_partitions} Kafka partition(s) cannot feed "
                f"{self.n_processes} processes — repartition the topic "
                "(>= one partition per process) or shrink the fleet")
        per, rem = divmod(n_partitions, self.n_processes)
        start = self.process_id * per + min(self.process_id, rem)
        width = per + (1 if self.process_id < rem else 0)
        return list(range(start, start + width))

    def describe(self) -> dict:
        return {
            "num_processes": self.n_processes,
            "process_id": self.process_id,
            "local_devices": self.local_devices,
            "n_shards_total": self.n_shards_total,
            "owned_shards": [self.owned_shards.start,
                             self.owned_shards.stop],
            "coordinated": self.coordinated,
        }


def bootstrap_distributed(
    dcfg: DistributedConfig,
    local_devices: int = 0,
) -> Optional[ProcessTopology]:
    """Bootstrap this process's place in a multi-host fleet.

    Single-process configs (``num_processes == 1`` and no coordinator)
    return None — the same binary serves a laptop and a fleet. With a
    coordinator, ``jax.distributed.initialize`` runs first (barrier on
    every process; Cloud TPU autodetects peers, CPU/Gloo uses the
    explicit triple), so ``jax.local_devices()`` is correct before any
    mesh is built. Without one (``coordinator == ""``), the topology is
    taken purely from the config — an *uncoordinated* fleet: no
    cross-process jax state exists, which is exactly what makes
    per-worker restarts safe (README: multi-host failure semantics).

    ``local_devices``: the mesh width this process will serve (the
    ``--devices`` flag); 0 = every local device. Resolved AFTER any
    distributed init so TPU backends report per-host counts.
    """
    n_proc = dcfg.num_processes
    pid = dcfg.process_id
    if pid < 0:
        env_pid = os.environ.get("JAX_PROCESS_ID")
        if env_pid is None and n_proc > 1:
            # Never default a fleet member's identity: two workers both
            # claiming process 0 would serve the same residue block and
            # write the same proc-00 lineages — and in uncoordinated
            # mode nothing else would ever notice (a coordinator at
            # least rejects the duplicate registration).
            raise ValueError(
                "multi-host bootstrap needs this process's identity: "
                "pass --process-id (or set JAX_PROCESS_ID) — "
                f"num_processes={n_proc} with no id would silently "
                "serve residue block 0 on every worker")
        pid = int(env_pid or "0")
    if n_proc <= 1 and not dcfg.coordinator:
        return None
    coordinated = False
    if dcfg.coordinator:
        from real_time_fraud_detection_system_tpu.parallel.distributed \
            import initialize_distributed

        import jax

        coordinated = initialize_distributed(
            dcfg.coordinator, n_proc, pid,
            init_timeout_s=dcfg.init_timeout_s)
        if coordinated:
            got = jax.process_count()
            if got != n_proc:
                raise ValueError(
                    f"jax.distributed reports {got} process(es), config "
                    f"says {n_proc} — launcher/flag mismatch")
            pid = jax.process_index()
    if local_devices <= 0:
        import jax

        local_devices = jax.local_device_count()
    return ProcessTopology(
        n_processes=n_proc,
        process_id=pid,
        local_devices=local_devices,
        coordinated=coordinated,
        strict_affinity=dcfg.strict_affinity,
    )
