"""Overload survival: a hysteresis-driven degradation ladder.

The paper's pipeline has no overload taxonomy at all — Spark micro-batches
fall behind and Kafka lag grows without bound (exactly the coordination-
cost failure mode arXiv:1612.01437 documents for Spark ML pipelines). The
loop already survives poison input (PR 4), corrupt state (PR 6) and model
regressions (PR 7); this module gives **sustained traffic above capacity**
the same treatment, in the overlap-don't-stall spirit of the
parallel-and-stream accelerator line of work: degrade optional work first,
shed durably last, never die.

:class:`OverloadController` is an explicit state machine driven by the
registry signals the engine already emits — windowed p50 batch latency vs
``runtime.latency_slo_ms``, ``rtfds_source_lag_rows``, prefetch/sink
queue fill — normalized into one scalar **pressure** (max of the
normalized components, so the worst signal owns the verdict). Distinct
climb/descend thresholds plus per-direction dwell counts make the ladder
flap-proof: one spike can neither climb nor descend it.

The rungs, each reversible:

1. **Shed optional work** — pause shadow scoring and learner training
   through the existing pause hooks; drop the flight recorder to sampled
   batch records (events always land).
2. **Degrade the data plane** — force the adaptive batcher to the
   largest AOT bucket (per-batch fixed costs amortize best there) and
   switch to alerts-only emission. Both switches are HOST-side only:
   every dispatch stays a signature already in the PR 11
   ``dispatch_inventory()`` (the compiled step is untouched — the
   feature matrix simply stays in HBM unfetched), so a full
   climb+descend cycle pays **zero mid-stream recompiles**, provable by
   ``rtfds verify-device`` and asserted from
   ``rtfds_xla_recompiles_total``.
3. **Admission control** — defer whole micro-batches to a durable
   overflow spill (the PR 4 dead-letter machinery, ``reason=shed``,
   idempotent by tx_id) instead of dispatching them. Deferral is
   whole-batch and strictly FIFO; when pressure subsides the queue
   replays **in order through the normal scoring path before live
   traffic resumes**, so the window/feature state is bit-identical to a
   never-overloaded run that saw the same rows later. No row ever skips
   a state update and none is silently lost:
   ``scored + deferred-pending == polled`` (see :meth:`invariant`).

Every transition is a flight-record event (``overload_climb`` /
``overload_descend``; deferral and replay land as ``shed`` / ``replay``)
and rides ``rtfds_overload_rung`` /
``rtfds_overload_transitions_total{direction}`` /
``rtfds_shed_rows_total`` / ``rtfds_shed_replayed_rows_total`` /
``rtfds_shed_pending_rows``.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import (
    MetricsRegistry,
    get_registry,
)

log = get_logger("overload")

RUNG_MAX = 3


def _noop_flag(on: bool) -> None:
    return None


@dataclass
class LadderActions:
    """The engine-side effects of each rung, as injectable callables —
    the controller decides, the serving loop applies. Every action takes
    ``on`` and must be idempotent + reversible (the ladder descends).

    ``shed_optional`` (rung >= 1): pause shadow scoring + learner
    training via the existing pause hooks; sample the flight recorder.
    ``degrade_emission`` (rung >= 2): alerts-only emission, host-side
    only (the compiled step never changes).
    ``force_max_batch`` (rung >= 2): pin autobatch to the largest AOT
    bucket. Rung 3 has no action of its own — deferral is the serving
    loop consulting :meth:`OverloadController.should_defer`.
    """

    shed_optional: Callable[[bool], None] = _noop_flag
    degrade_emission: Callable[[bool], None] = _noop_flag
    force_max_batch: Callable[[bool], None] = _noop_flag


@dataclass
class DeferredBatch:
    """One rung-3 deferred micro-batch, exactly as assembled."""

    seq: int                 # monotone deferral sequence (spill part id)
    cols: dict               # the polled column dict, order preserved
    offsets: List[int]       # source offsets AFTER this batch's polls
    rows: int                # len(cols) at deferral time


class OverloadController:
    """The ladder state machine. One instance per ``engine.run``.

    The serving loop calls, in its own order: :meth:`want_replay` /
    :meth:`next_replay` before polling, :meth:`should_defer` +
    :meth:`defer` after assembling a batch, :meth:`observe_batch` (and
    :meth:`note_replayed`) per finished batch, and :meth:`deactivate`
    on the way out. Everything runs on the loop thread — no locks, no
    cross-thread state (the spill sink has its own lock).
    """

    def __init__(self, rcfg, registry: Optional[MetricsRegistry] = None,
                 actions: Optional[LadderActions] = None,
                 recorder_fn: Optional[Callable] = None):
        ocfg = rcfg.overload
        self.ocfg = ocfg
        self.rcfg = rcfg
        self.actions = actions if actions is not None else LadderActions()
        self._recorder_fn = recorder_fn if recorder_fn is not None else (
            lambda: None)
        self.reg = registry if registry is not None else get_registry()
        self.rung = 0
        self.slo_s = max(0.0, float(rcfg.latency_slo_ms)) / 1e3
        self._lat: deque = deque(
            maxlen=max(1, int(ocfg.latency_window_batches)))
        self._climb_streak = 0
        self._descend_streak = 0
        # rung-3 drain mode: descend dwell was met, the deferred queue
        # replays in order; the 3->2 transition lands when it EMPTIES
        self._draining = False
        self._outstanding_replays = 0
        self.max_deferred = int(ocfg.max_deferred_batches)
        # Bounded by max_deferred_batches: should_defer()/want_replay()
        # replay the head to make room at the cap, so membership never
        # exceeds it (the remaining backlog stays in the source/broker).
        # rtfdslint: disable=unbounded-queue (loop-thread-only FIFO, capped at overload.max_deferred_batches by the defer/replay admission logic one screen down; deque(maxlen=) would silently DROP the head on overflow — the one thing a no-silent-loss spill must never do)
        self._deferred: deque = deque()
        self._seq = 0
        # lag-trend EMA state (rows/s; negative = draining)
        self._last_lag: Optional[Tuple[float, float]] = None  # (t, lag)
        self._trend: Optional[float] = None
        self.spill = None
        if ocfg.spill_path:
            from real_time_fraud_detection_system_tpu.io.sink import (
                make_dead_letter_sink,
            )

            # Private registry + muted recorder: the spill reuses the
            # dead-letter file machinery (durability, tx_id idempotence)
            # but shed rows are NOT a triage backlog — they must not
            # trip the DLQ degraded state, tile, or counters. The
            # controller emits its own shed/replay telemetry.
            self.spill = make_dead_letter_sink(
                ocfg.spill_path, registry=MetricsRegistry(),
                recorder_fn=lambda: None)
        else:
            log.warning(
                "overload ladder enabled without a spill path: rung-3 "
                "deferral is memory-only (a crash relies on checkpoint "
                "replay alone to recover deferred rows)")
        reg = self.reg
        self._m_rung = reg.gauge(
            "rtfds_overload_rung",
            "active overload-ladder rung (0 = normal serving; 1 = "
            "optional work shed; 2 = degraded data plane; 3 = admission "
            "control / deferral)")
        self._m_rung.set(0.0)
        self._m_trans = {
            d: reg.counter(
                "rtfds_overload_transitions_total",
                "overload-ladder rung transitions by direction",
                direction=d)
            for d in ("climb", "descend")
        }
        self._m_shed = reg.counter(
            "rtfds_shed_rows_total",
            "rows deferred to the overload spill (whole batches, "
            "replayed in order once pressure subsides)")
        self._m_replayed = reg.counter(
            "rtfds_shed_replayed_rows_total",
            "deferred rows replayed through the normal scoring path")
        self._m_pending = reg.gauge(
            "rtfds_shed_pending_rows",
            "deferred rows not yet replayed (healthz degrades while > 0)")
        self._m_lag_trend = reg.gauge(
            "rtfds_source_lag_trend_rows_per_s",
            "EMA slope of rtfds_source_lag_rows (negative = the backlog "
            "is draining)")
        # Raw normalized pressure (the max over components the ladder
        # judges), exported for the elastic autoscaler: the launcher's
        # policy watches the worst-process value alongside the rung —
        # the rung says what the ladder DID, the pressure says how far
        # past (or under) the thresholds the process is running.
        self._m_pressure = reg.gauge(
            "rtfds_overload_pressure",
            "normalized overload pressure (max component; >= "
            "climb threshold sustains a rung climb, autoscaler input)")

    # -- signals -----------------------------------------------------------

    def _pressure(self, include_latency: bool) -> Tuple[float, dict]:
        """Normalized pressure components; the max owns the verdict.

        ``include_latency=False`` while rung-3 deferral is the only
        activity: no batches finish there, so the latency window is
        stale-high by construction and would wedge the ladder at the
        top — descent is then judged on lag/queue signals alone.
        """
        comps = {}
        if include_latency and self.slo_s > 0 and len(self._lat) >= min(
                3, self._lat.maxlen):
            s = sorted(self._lat)
            comps["latency"] = s[len(s) // 2] / self.slo_s
        lag_high = int(self.ocfg.lag_high_rows)
        lag = self.reg.get("rtfds_source_lag_rows")
        if lag is not None:
            self._note_lag(lag.value)
            if lag_high > 0:
                comps["lag"] = lag.value / lag_high
        pf_cap = int(self.rcfg.prefetch_batches)
        if pf_cap > 0:
            depth = self.reg.get("rtfds_prefetch_queue_depth")
            if depth is not None:
                comps["prefetch_fill"] = depth.value / pf_cap
        sink_cap = int(self.rcfg.sink_queue_batches)
        if sink_cap > 0:
            depth_total = self.reg.family_total("rtfds_sink_queue_depth")
            if depth_total is not None:
                comps["sink_fill"] = depth_total / sink_cap
        # Cold-promotion storm (features.cold_store): a promoter backlog
        # pinned at its bounded queue depth means returning keys are
        # arriving faster than promotions can land — the sketch serves
        # them degraded meanwhile, and the host is doing segment reads at
        # full tilt. Same normalized fill shape as the queue signals.
        q_limit = self.reg.get("rtfds_feature_cold_promote_queue_limit")
        if q_limit is not None and q_limit.value > 0:
            backlog = self.reg.get("rtfds_feature_cold_promote_backlog")
            if backlog is not None:
                comps["cold_promote"] = backlog.value / q_limit.value
        return (max(comps.values()) if comps else 0.0), comps

    def _note_lag(self, lag: float) -> None:
        now = time.perf_counter()
        if self._last_lag is not None:
            t0, l0 = self._last_lag
            dt = now - t0
            if dt > 1e-6:
                slope = (lag - l0) / dt
                self._trend = slope if self._trend is None else (
                    0.5 * slope + 0.5 * self._trend)
                self._m_lag_trend.set(self._trend)
        self._last_lag = (now, lag)

    # -- hysteresis core ---------------------------------------------------

    def _evaluate(self, include_latency: bool) -> None:
        pressure, comps = self._pressure(include_latency)
        self._m_pressure.set(pressure)
        if pressure >= self.ocfg.climb_pressure:
            self._descend_streak = 0
            self._climb_streak += 1
            if self._climb_streak >= self.ocfg.climb_dwell_batches:
                self._climb_streak = 0
                if self.rung < RUNG_MAX:
                    self._transition(+1, pressure, comps)
                elif self._draining:
                    # pressure came back mid-drain: pause the replay
                    # (new polls defer again); NOT a rung transition
                    self._draining = False
                    log.info("overload: drain paused, pressure %.2f "
                             "re-climbed (%s)", pressure, comps)
        elif pressure <= self.ocfg.descend_pressure:
            self._climb_streak = 0
            self._descend_streak += 1
            if self._descend_streak >= self.ocfg.descend_dwell_batches:
                self._descend_streak = 0
                if self.rung == RUNG_MAX and (
                        self._deferred or self._outstanding_replays):
                    if not self._draining:
                        self._draining = True
                        log.info("overload: pressure %.2f subsided, "
                                 "replaying %d deferred batch(es) in "
                                 "order before live traffic", pressure,
                                 len(self._deferred))
                elif self.rung > 0:
                    self._transition(-1, pressure, comps)
        else:
            # hysteresis dead band: streaks reset, nothing moves
            self._climb_streak = 0
            self._descend_streak = 0

    def _transition(self, di: int, pressure: float, comps: dict) -> None:
        old, new = self.rung, self.rung + di
        self.rung = new
        direction = "climb" if di > 0 else "descend"
        self._m_trans[direction].inc()
        self._m_rung.set(new)
        # apply/revert the rung's actions (idempotent, reversible)
        if direction == "climb":
            if new == 1:
                self.actions.shed_optional(True)
            elif new == 2:
                self.actions.force_max_batch(True)
                self.actions.degrade_emission(True)
            # new == 3: behavioral — should_defer() turns True
        else:
            if old == 2:
                self.actions.degrade_emission(False)
                self.actions.force_max_batch(False)
            elif old == 1:
                self.actions.shed_optional(False)
            elif old == RUNG_MAX:
                self._draining = False
        rec = self._recorder_fn()
        if rec is not None:
            rec.record_event(
                f"overload_{direction}", rung=new, from_rung=old,
                pressure=round(pressure, 4),
                **{k: round(v, 4) for k, v in comps.items()})
        log.info("overload: %s to rung %d (pressure %.2f: %s)",
                 direction, new, pressure,
                 {k: round(v, 2) for k, v in comps.items()} or "idle")

    # -- serving-loop API --------------------------------------------------

    def observe_batch(self, rows: int, latency_s: float) -> None:
        """One finished (scored) batch — the ladder's main clock."""
        if latency_s > 0:
            self._lat.append(float(latency_s))
        self._evaluate(include_latency=True)

    def idle_tick(self) -> None:
        """A zero-row idle poll — the ladder's clock when the source
        goes quiet. Without this, a burst followed by silence would
        latch every degrade forever: no batches finish, so
        observe_batch never runs, descend dwell never accumulates, and
        deferred rows wait for traffic that may not return. The quiet
        period is exactly when the ladder should descend and replay —
        judged on lag/queue signals alone (the latency window is stale
        by definition when nothing is being scored)."""
        self._evaluate(include_latency=False)

    def should_defer(self) -> bool:
        """True while rung 3 admission control holds and the queue is
        not draining: the just-assembled batch must be deferred, not
        dispatched (dispatching it would reorder it past the deferred
        FIFO and diverge the feature state)."""
        return self.rung >= RUNG_MAX and not self._draining

    def defer(self, cols: dict, offsets: List[int]) -> DeferredBatch:
        """Defer one whole assembled micro-batch: durable spill write
        (idempotent by tx_id) + FIFO enqueue + counters + flight event.
        The batch consumes no batch_index and advances no offsets — the
        sink lineage stays gap-free and a crash replays these rows from
        the checkpoint."""
        n = len(cols["tx_id"])
        item = DeferredBatch(seq=self._seq, cols=cols,
                             offsets=list(offsets), rows=n)
        self._seq += 1
        if self.spill is not None:
            self.spill.put_rows(
                cols, reason="shed",
                error="deferred by overload admission control (rung 3); "
                      "replayed in order on descent",
                batch_index=item.seq)
        self._deferred.append(item)
        self._m_shed.inc(n)
        self._m_pending.set(self.pending_rows)
        rec = self._recorder_fn()
        if rec is not None:
            rec.record_event("shed", rows=n, seq=item.seq,
                             deferred_batches=len(self._deferred))
        # deferral is the only activity at rung 3: evaluate on its
        # cadence, latency signal excluded (no batches finish to feed it)
        self._evaluate(include_latency=False)
        return item

    def want_replay(self) -> bool:
        """True when the loop's next unit of work is a deferred batch:
        either the ladder is draining (descent from rung 3), or the
        spill hit its memory cap — the head then replays through
        scoring to make room (order preserved: head first, new polls
        keep deferring behind the tail)."""
        if not self._deferred:
            return False
        return self._draining or len(self._deferred) >= self.max_deferred

    def next_replay(self) -> Optional[DeferredBatch]:
        item = self._deferred.popleft() if self._deferred else None
        if item is None:
            return None
        self._outstanding_replays += 1
        rec = self._recorder_fn()
        if rec is not None:
            rec.record_event("replay", rows=item.rows, seq=item.seq,
                             deferred_batches=len(self._deferred))
        return item

    def note_replayed(self, rows: int) -> None:
        """A replayed batch FINISHED scoring (counters must reflect
        state updates that actually landed, not dispatches)."""
        self._outstanding_replays = max(0, self._outstanding_replays - 1)
        self._m_replayed.inc(rows)
        self._m_pending.set(self.pending_rows)
        if (self._draining and not self._deferred
                and self._outstanding_replays == 0):
            # queue fully drained and landed: the 3 -> 2 descent
            self._transition(-1, 0.0, {"drained": 1.0})

    def finish_stream(self) -> None:
        """Source exhausted with batches still deferred: force-drain —
        the stream is ending and every polled row must be scored
        (``scored == polled`` at quiescence). Rung descent still runs
        through note_replayed, so counters stay exact."""
        if self._deferred or self._outstanding_replays:
            self._draining = True

    def deactivate(self) -> None:
        """End-of-run cleanup: revert every engine-side action so a
        later ``run()`` on this engine starts undegraded. Rung/counters
        are left as they stand — a stream that ENDED while degraded
        should say so in the registry, not cosmetically reset."""
        if self.rung >= 2:
            self.actions.degrade_emission(False)
            self.actions.force_max_batch(False)
        if self.rung >= 1:
            self.actions.shed_optional(False)
        if self.rung != 0:
            log.warning(
                "overload: stream ended at rung %d with %d deferred "
                "batch(es) pending (%s)", self.rung, len(self._deferred),
                "spilled durably" if self.spill is not None
                else "memory only — rely on checkpoint replay")

    # -- accounting --------------------------------------------------------

    @property
    def pending_rows(self) -> int:
        return int(self._m_shed.value - self._m_replayed.value)

    @property
    def deferred_batches(self) -> int:
        return len(self._deferred)

    def invariant(self) -> dict:
        """The no-silent-loss ledger, read from the REGISTRY (the same
        series an operator scrapes): at any quiescent point (no batch in
        flight), ``scored + deferred-pending == polled`` up to dedup
        (``rtfds_rows_total`` counts post-dedup rows; with unique tx_ids
        the identity is exact). Single-incarnation semantics: a
        supervisor restart re-polls replayed rows and re-scores them,
        inflating both sides consistently."""
        polled = self.reg.family_total("rtfds_source_rows_total") or 0.0
        scored = self.reg.family_total("rtfds_rows_total") or 0.0
        pending = float(self.pending_rows)
        return {
            "polled_rows": polled,
            "scored_rows": scored,
            "deferred_pending_rows": pending,
            "shed_rows": float(self._m_shed.value),
            "replayed_rows": float(self._m_replayed.value),
            "balanced": bool(scored + pending == polled),
        }
