"""Adaptive micro-batch controller — the coalesce target as a feedback loop.

The engine's batching policy was static: ``runtime.coalesce_rows`` fixed
one assembly target for the whole run, whatever the traffic or the
latency budget. This module closes the loop: the controller watches the
same per-batch decomposition the registry's ``rtfds_phase_seconds``
histograms aggregate (the engine feeds it each finished batch's rows and
latency) and moves the coalesce target BETWEEN the configured
``runtime.batch_buckets`` — never to an unbucketed size, so every target
it can pick is a warm (or precompiled) jit cache entry.

Two objectives, picked by configuration:

- **Latency SLO** (``latency_slo_ms > 0``): hold the windowed p50
  micro-batch latency at or under the target. Above the SLO → step down
  one bucket; comfortably under (``headroom`` × SLO) → step up one.
- **Throughput** (no SLO): hill-climb rows/s over the bucket ladder.
  Each bucket's observed rows/s is tracked as an EMA; unexplored
  neighbors are tried first, then the controller moves only for a
  meaningfully better estimate (``improve`` factor) so it settles
  instead of ping-ponging.

Decisions happen every ``decide_every`` observed ON-TARGET batches:
each observation is attributed to the bucket its rows actually padded
to, so in-flight stragglers assembled at a previous target (pipeline
depth > 1) and undersized tail polls update THEIR bucket's EMA instead
of smearing the current one, and never count toward the decision
window. The current target rides ``rtfds_autobatch_target_rows``; every
move counts in ``rtfds_autobatch_adjustments_total{direction}``.
"""

from __future__ import annotations

from typing import Optional, Sequence

from real_time_fraud_detection_system_tpu.utils.metrics import (
    MetricsRegistry,
    get_registry,
)


def _p50(values) -> float:
    s = sorted(values)
    return s[len(s) // 2] if s else 0.0


class AutoBatchController:
    """Feedback controller over the bucket ladder.

    The engine calls :meth:`observe` once per finished batch and
    :meth:`target_rows` once per assembly pass; both are O(1) (one deque
    append / one list index) — hot-loop safe.
    """

    def __init__(
        self,
        buckets: Sequence[int],
        latency_slo_ms: float = 0.0,
        decide_every: int = 8,
        headroom: float = 0.6,
        improve: float = 1.05,
        ema_alpha: float = 0.5,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.buckets = sorted({int(b) for b in buckets})
        if not self.buckets:
            raise ValueError("autobatch needs at least one batch bucket")
        self.slo_s = max(0.0, float(latency_slo_ms)) / 1e3
        self.decide_every = max(1, int(decide_every))
        self.headroom = float(headroom)
        self.improve = float(improve)
        self.ema_alpha = float(ema_alpha)
        # SLO mode starts at the smallest bucket (meet the target first,
        # then grow into the budget); throughput mode starts at the
        # largest (per-batch fixed costs amortize best there, and the
        # climb explores downward if the estimate disagrees).
        self._i = 0 if self.slo_s > 0 else len(self.buckets) - 1
        self._window: list = []  # (rows, latency_s) at the CURRENT target
        self._rate_ema = {}  # bucket -> EMA rows/s
        self.adjustments = 0
        # Overload rung-2 override (runtime/overload.py): while forced,
        # the target is pinned to the largest bucket and the decision
        # loop is suspended — the ladder, not the SLO follower, owns the
        # batching policy under overload (the SLO is already blown; the
        # follower would fight the ladder by stepping DOWN).
        self._forced = False
        reg = registry if registry is not None else get_registry()
        self._m_target = reg.gauge(
            "rtfds_autobatch_target_rows",
            "current adaptive coalesce target (rows)")
        self._m_adjust = {
            d: reg.counter(
                "rtfds_autobatch_adjustments_total",
                "bucket-ladder moves by the adaptive batch controller",
                direction=d)
            for d in ("up", "down")
        }
        self._m_target.set(self.target_rows())

    # -- engine-facing API -------------------------------------------------

    def target_rows(self) -> int:
        """The coalesce target the next assembly pass should aim for."""
        return self.buckets[self._i]

    def force_max(self) -> None:
        """Pin the target to the LARGEST bucket (overload rung 2): the
        per-batch fixed costs amortize best there, and every dispatch
        stays inside the precompiled AOT inventory. The move counts in
        the adjustment metrics like any other; decisions stay suspended
        until :meth:`release_force`."""
        if self._forced:
            return
        self._forced = True
        self._window = []
        self._move(len(self.buckets) - 1 - self._i)

    def release_force(self) -> None:
        """Resume adaptive control from the largest bucket (the ladder
        descends one rung at a time, so the follower re-explores from
        where overload left it rather than snapping back)."""
        self._forced = False
        self._window = []

    def _bucket_for(self, rows: int) -> int:
        """The jit bucket ``rows`` actually padded to (smallest bucket
        that fits; largest when none does) — the batch's OWN bucket, not
        the current target."""
        for b in self.buckets:
            if rows <= b:
                return b
        return self.buckets[-1]

    def observe(self, rows: int, latency_s: float) -> None:
        """Feed one finished batch; may move the target (every
        ``decide_every`` on-target observations).

        Observations are attributed to the batch's OWN bucket: with
        ``pipeline_depth`` > 1, batches assembled at the PREVIOUS target
        are still landing after a move (and tail polls run smaller than
        any target) — crediting them to the current target would pollute
        its EMA and re-trigger SLO moves off stale latencies."""
        if rows <= 0:
            return
        b = self._bucket_for(int(rows))
        if latency_s > 0:
            rate = rows / latency_s
            prev = self._rate_ema.get(b)
            self._rate_ema[b] = rate if prev is None else (
                self.ema_alpha * rate + (1 - self.ema_alpha) * prev)
        if self._forced:
            return  # overload rung 2 owns the target; EMAs stay fresh
        if b != self.target_rows():
            return  # in-flight stragglers from an older target / tails
        self._window.append((int(rows), float(latency_s)))
        if len(self._window) >= self.decide_every:
            self._decide()
            self._window = []

    # -- decision logic ----------------------------------------------------

    def _move(self, di: int) -> None:
        j = min(max(self._i + di, 0), len(self.buckets) - 1)
        if j == self._i:
            return
        self._m_adjust["up" if j > self._i else "down"].inc()
        self.adjustments += 1
        self._i = j
        self._m_target.set(self.target_rows())

    def _decide(self) -> None:
        if self.slo_s > 0:
            p50 = _p50([lat for _, lat in self._window])
            if p50 > self.slo_s:
                self._move(-1)
            elif p50 < self.headroom * self.slo_s:
                self._move(+1)
            return
        # throughput mode: explore unmeasured neighbors first, then move
        # only for a meaningfully better rows/s estimate
        cur = self._rate_ema.get(self.target_rows(), 0.0)
        for di in (+1, -1):
            j = self._i + di
            if 0 <= j < len(self.buckets) \
                    and self.buckets[j] not in self._rate_ema:
                self._move(di)
                return
        best_di, best_rate = 0, cur * self.improve
        for di in (+1, -1):
            j = self._i + di
            if 0 <= j < len(self.buckets):
                r = self._rate_ema.get(self.buckets[j], 0.0)
                if r > best_rate:
                    best_di, best_rate = di, r
        if best_di:
            self._move(best_di)
