"""Multi-chip streaming engine: the sharded serving loop.

Round 1 proved the sharded *step* (``parallel/step.py``: customer-sharded
window state, terminal ``all_to_all`` exchange, psum'd online SGD) on
single dry-run steps; this module makes it a *serving engine* — the same
source → dedup → step → sink → checkpoint stream contract as
:class:`~.engine.ScoringEngine`, but the step runs under ``shard_map``
over a ``jax.sharding.Mesh``. This is the TPU-native analogue of the
reference's scaled-out deployment (8-partition Kafka stream feeding
parallel Spark executors, SURVEY §2.3 items 1-2;
``fraud_detection.py:204-211`` is the loop being replaced).

Row → device placement is ``customer_id % n_devices`` (the broker's
key-hash partition analogue), computed host-side by
:func:`~..parallel.step.partition_batch_spill`; a hot-key shard overflow
spills into follow-on sub-steps instead of failing the stream.

``key_mode="exact"`` (the tiered device-resident feature store) serves
sharded too: ownership keeps the stable modulo above, but the slot
WITHIN a shard comes from that shard's private key directory —
per-shard ``keydir`` + hot tier + sketch replica, per-shard recency
compaction as the ``("compact",)`` dispatch variant, and per-shard
tier/occupancy telemetry (the ``shard`` label). With each shard's hot
tier sized to hold its keys, sharded exact is bit-identical to
single-engine exact (tests/test_sharded_exact.py).

The engine inherits the single-chip engine's run loop, feedback-SGD path,
and feature-cache plumbing; it overrides batch processing (partition →
sharded step → re-assemble) and state feedback (the terminal table lives
in owner-partitioned layout: global row = owner * cap_local + local_slot).
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.core.batch import (
    fold_key,
    make_batch,
    pack_batch,
)
from real_time_fraud_detection_system_tpu.core.batch import bucket_size
from real_time_fraud_detection_system_tpu.features.online import (
    apply_feedback_at_slot,
    init_feature_state,
)
from real_time_fraud_detection_system_tpu.features.spec import N_FEATURES
from real_time_fraud_detection_system_tpu.models.scaler import Scaler
from real_time_fraud_detection_system_tpu.ops.dedup import (
    latest_wins_mask_host,
)
from real_time_fraud_detection_system_tpu.parallel.mesh import (
    make_mesh,
    shard_feature_state,
)
from real_time_fraud_detection_system_tpu.parallel.step import (
    make_sharded_step,
    partition_batch_spill,
)
from real_time_fraud_detection_system_tpu.runtime.engine import (
    BatchResult,
    ScoringEngine,
    loss_fn_for,
)
from real_time_fraud_detection_system_tpu.utils.xla_telemetry import (
    step_signature,
)


class ShardedScoringEngine(ScoringEngine):
    """Streaming engine over an n-device mesh.

    Same interface as :class:`ScoringEngine` (``process_batch`` /
    ``run`` / ``apply_feedback`` / ``apply_state_feedback`` / checkpoint
    state), so sources, sinks, the feedback loop, and
    :func:`~.faults.run_with_recovery` compose unchanged.

    ``rows_per_shard`` fixes the per-device step width (static shapes keep
    the jit cache to ONE entry); a micro-batch is absorbed as
    ceil(max_shard_load / rows_per_shard) sub-steps.
    """

    def __init__(
        self,
        cfg: Config,
        kind: str,
        params,
        scaler: Scaler,
        mesh: Optional[Mesh] = None,
        n_devices: int = 0,
        rows_per_shard: int = 0,
        axis: "str | tuple" = "data",
        online_lr: float = 0.0,
        feature_cache=None,
        feature_state=None,
        feature_state_n_old: Optional[int] = None,
        metrics=None,
        dead_letter=None,
        topology=None,
    ):
        """``feature_state``: a pre-built state for elastic recovery of a
        checkpoint taken at a different device count. Pass
        ``feature_state_n_old`` (the checkpoint's device count; 1 for a
        single-chip checkpoint) and the engine reshards it to THIS mesh
        itself via :func:`~.parallel.mesh.reshard_feature_state` /
        :func:`~.parallel.sequence_step.reshard_history_state` — the
        safest path, since window layouts are shape-identical
        permutations that nothing else can tell apart. Omit
        ``feature_state_n_old`` only when the state is already in this
        mesh's layout. Default: fresh state.

        ``topology``: this process's place in a multi-host fleet
        (:class:`~.distributed.ProcessTopology`). The engine itself runs
        UNCHANGED — ingest affinity guarantees every polled key's
        residue is local, so the local ``key % n_dev`` placement equals
        the global layout's (the residue-block construction) — but the
        mesh is built from the process's OWN devices (a fleet under
        ``jax.distributed`` sees every process's devices in
        ``jax.devices()``), shard telemetry carries global shard ids +
        a ``process`` label, strict ingest refuses rows this process
        does not own, and checkpoints stamp the writer's topology."""
        if topology is not None and kind == "sequence":
            raise ValueError(
                "multi-host serving is not wired for kind='sequence' "
                "(history-state process adoption does not exist yet); "
                "serve the sequence scorer single-process")
        if cfg.runtime.nan_guard:
            # The sharded step donates state inside shard_map and a batch
            # spans several chunk steps — there is no pre-batch anchor to
            # roll back to. Poison/non-finite isolation for mesh serving
            # goes through the supervisor's bisection path instead
            # (run_with_recovery --dead-letter), which replays whole
            # batches through process_batch.
            raise ValueError(
                "runtime.nan_guard is not wired for the sharded engine; "
                "serve single-chip with --nan-guard, or rely on the "
                "supervisor's crash-loop bisection (--dead-letter)")
        if mesh is None:
            if topology is not None:
                # multi-host: THIS process's devices only — jax.devices()
                # spans the fleet under jax.distributed, and a mesh over
                # non-addressable devices turns every step into a
                # cross-process computation
                from real_time_fraud_detection_system_tpu.parallel.mesh \
                    import make_local_mesh

                mesh = make_local_mesh(
                    n_devices or topology.local_devices)
            else:
                mesh = make_mesh(n_devices)
        n_mesh = int(mesh.devices.size)
        if topology is not None and n_mesh != topology.local_devices:
            raise ValueError(
                f"mesh is {n_mesh} device(s) wide but the topology says "
                f"this process serves {topology.local_devices} — the "
                "residue-block ownership is sized n_processes × "
                "local_devices, so the two must agree")
        # state_bytes accounting needs the width BEFORE the base
        # constructor runs its budget check / bytes gauges; topology
        # likewise (the state-telemetry override labels per-shard series
        # with global shard ids inside the base constructor)
        self.topology = topology
        self.n_dev = n_mesh
        exact = cfg.features.key_mode == "exact" and kind != "sequence"
        if exact:
            # Per-shard tiered store: validate the partition up front
            # (the base class would only catch it after building state).
            for nm in ("customer_capacity", "terminal_capacity"):
                cap = getattr(cfg.features, nm)
                local = cap // n_mesh if cap % n_mesh == 0 else 0
                if local <= 0 or (local & (local - 1)):
                    raise ValueError(
                        f"key_mode='exact' on a {n_mesh}-wide mesh needs "
                        f"{nm} / n_devices to be a power of two, got "
                        f"{cap} / {n_mesh}")
        if exact and feature_state is not None \
                and feature_state_n_old is None:
            # Exact-mode layouts are shape-carrying (stacked per-shard
            # directories), so a mislaid state is detectable — refuse
            # with the fix named instead of serving split key histories.
            kd = feature_state.terminal_dir
            # metadata only — .ndim/.shape exist on numpy AND jax
            # arrays, so no device-to-host copy of a possibly-huge
            # directory leaf just to read its layout
            lead = (int(kd.keys.shape[0])
                    if kd is not None
                    and getattr(kd.keys, "ndim", 1) == 2 else 1)
            if kd is None or lead != n_mesh:
                raise ValueError(
                    f"provided exact feature_state is laid out for "
                    f"{lead} shard(s), mesh has {n_mesh} — pass "
                    "feature_state_n_old to let the engine re-home the "
                    "directory entries (elastic reshard)")
        if feature_state is not None and feature_state_n_old is not None:
            from real_time_fraud_detection_system_tpu.parallel.mesh import (
                reshard_engine_state,
            )

            feature_state = reshard_engine_state(
                kind, feature_state, cfg, feature_state_n_old, n_mesh,
                stacked=True)
        elif feature_state is not None and kind != "sequence":
            # Claimed mesh layout: cross-check what little IS checkable
            # (layout permutations are shape-identical, so only a
            # device-axis-carrying CMS betrays a width mismatch).
            cms = feature_state.cms
            if cms is not None and np.asarray(cms.slice_day).ndim > 1 \
                    and np.asarray(cms.slice_day).shape[0] != n_mesh:
                raise ValueError(
                    f"feature_state CMS is laid out for "
                    f"{np.asarray(cms.slice_day).shape[0]} devices, mesh "
                    f"has {n_mesh} — pass feature_state_n_old to let the "
                    "engine reshard it")
        pre_state = None
        if kind == "sequence" and feature_state is not None:
            from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
                shard_history_state,
            )

            pre_state = shard_history_state(feature_state, mesh, axis=axis)
        elif kind == "sequence":
            # build the owner-sharded state FIRST and hand it to the base
            # constructor — a throwaway full-size single-chip HistoryState
            # would transiently double the state's HBM footprint
            from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
                init_sharded_history_state,
            )

            pre_state = init_sharded_history_state(cfg, mesh, axis=axis)
        if kind != "sequence":
            # hand any provided state straight to the base constructor —
            # letting it build a throwaway full-size fresh state would
            # transiently double the footprint (same reasoning as the
            # sequence pre_state above)
            pre_state = feature_state
            if exact and pre_state is None:
                # exact mode's directory shapes are width-dependent
                # (per-shard key directories): build the SHARDED layout
                # first, never the single-chip one
                pre_state = init_feature_state(cfg.features,
                                               n_shards=n_mesh)
        super().__init__(
            cfg, kind, params, scaler, feature_state=pre_state,
            online_lr=online_lr, feature_cache=feature_cache,
            metrics=metrics, dead_letter=dead_letter,
        )
        self.mesh = mesh
        self.axis = axis
        self.n_dev = int(self.mesh.devices.size)
        self.state.layout_devices = self.n_dev
        if self.topology is not None:
            # the writer's topology travels WITH the state: a per-process
            # checkpoint holds only its residue block's keys
            self.state.process_count = self.topology.n_processes
            self.state.process_id = self.topology.process_id
        # Mesh-level telemetry: per-shard row placement (imbalance is THE
        # sharded-serving failure mode worth watching), replicated-leaf
        # commits, and sharded-step (re)builds — a retrace inside the
        # serving loop costs ~1 s and should be visible, not inferred.
        self._m_shard_rows = [
            self.metrics.gauge(
                "rtfds_shard_rows",
                "rows routed to this shard in the last batch",
                **self._shard_labels(i))
            for i in range(self.n_dev)
        ]
        self._m_commits = self.metrics.counter(
            "rtfds_replicated_commits_total",
            "params/scaler trees committed to the mesh (each avoided a "
            "silent in-loop retrace)")
        self._m_step_builds = self.metrics.counter(
            "rtfds_sharded_step_builds_total",
            "sharded step compilations (local + routed variants)")
        # Commit replicated leaves (params, scaler) to the mesh NOW: the
        # step's out_specs return them mesh-committed, so leaving the
        # build-time copies on the default device makes the SECOND step
        # call see different input shardings and silently retrace — ~1 s
        # of recompile paid inside the serving loop (measured: the first
        # post-warmup batch at width 1 cost 969 ms vs 8 ms steady-state).
        self._commit_replicated()
        if cfg.features.customer_capacity % self.n_dev:
            raise ValueError("customer_capacity must divide by n_devices")
        # Default: 2× the balanced per-device load, so ordinary partition
        # imbalance stays in ONE chunk (shared by both engine kinds).
        self.rows_per_shard = rows_per_shard or max(
            2 * -(-cfg.runtime.max_batch_rows // self.n_dev), 16
        )
        if kind == "sequence":
            # Long-context serving over the mesh: customer-owner-sharded
            # history state, same partition/spill machinery, routed spill
            # chunks exchange rows to their owner over ICI.
            from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
                make_sharded_sequence_step,
            )

            # feature_state is already the owner-sharded HistoryState
            # (pre_state above)
            self._seq_step = make_sharded_sequence_step(
                cfg, self.mesh, axis=self.axis)
            self._seq_step_routed = make_sharded_sequence_step(
                cfg, self.mesh, axis=self.axis, route=True)
            return
        if cfg.features.terminal_capacity % self.n_dev:
            raise ValueError("terminal_capacity must divide by n_devices")
        # the base constructor holds either the provided state or a fresh
        # one — place it over the mesh (no second allocation)
        self.state.feature_state = shard_feature_state(
            self.state.feature_state, self.mesh, axis=self.axis,
        )
        # self._predict, not a fresh predict_fn_for(kind): the base
        # constructor may have swapped in the fused Pallas tree scorer
        # (use_pallas) — the mesh engine must serve the same kernel.
        self._sharded_build = make_sharded_step(
            cfg,
            self._predict,
            loss_fn=loss_fn_for(kind),
            online_lr=online_lr,
            mesh=self.mesh,
            axis=self.axis,
            packed=True,  # one H2D copy per chunk (see _start_batch)
        )
        # Dense-spill variant (customers routed to owner like terminals);
        # compiled lazily on the first hot-key overflow.
        self._sharded_build_routed = make_sharded_step(
            cfg,
            self._predict,
            loss_fn=loss_fn_for(kind),
            online_lr=online_lr,
            mesh=self.mesh,
            axis=self.axis,
            route_customers=True,
            packed=True,
        )
        self._sharded_step = None  # built on first batch (needs templates)
        self._sharded_step_routed = None
        self._sharded_sf = None
        self._sharded_sf_exact = None
        if self._exact:
            # replace the base class's single-chip compaction jit with
            # the shard_map'd per-shard pass (same ("compact",) dispatch
            # key, same donation, per-shard reclaim counts out)
            from real_time_fraud_detection_system_tpu.parallel.step import (
                make_sharded_compact,
                make_sharded_promote,
            )

            self._compact = make_sharded_compact(
                cfg, self.mesh, axis=self.axis,
                demote_slots=self._demote_slots)
            if self._demote_slots:
                # and the promote-merge's sharded twin: owner-grouped
                # payload blocks, purely shard-local admission
                self._promote = make_sharded_promote(cfg, self.mesh,
                                                     axis=self.axis)

    # -- per-shard feature-state telemetry ---------------------------------

    def _state_shards(self) -> int:
        # set before super().__init__ so the base budget check and bytes
        # gauges account the per-device sketch replicas
        return int(getattr(self, "n_dev", 1) or 1)

    def _shard_labels(self, local_shard: int) -> dict:
        """Label set of per-shard series: single-process keeps the
        historical ``shard=<local>``; a fleet labels GLOBALLY
        (``shard = shard_offset + local``, matching the shard id the
        single (P·L)-device engine would use for the same keys) and adds
        the ``process`` label, so a coordinator-side aggregation over
        every worker's registry reads as ONE engine's shard space."""
        topo = getattr(self, "topology", None)
        if topo is None or topo.n_processes <= 1:
            return {"shard": str(local_shard)}
        return {"shard": str(topo.shard_offset + local_shard),
                "process": str(topo.process_id)}

    def _init_state_telemetry(self) -> None:
        """Base series (the healthz/global view) PLUS the per-shard
        breakdown — skew is the failure mode modulo ownership hides, so
        every tier/occupancy/reclaim series also exists with a
        ``shard`` label."""
        super()._init_state_telemetry()
        self._m_tier_shard = None
        self._m_slots_occ_shard = None
        self._m_slots_rec_shard = None
        if not self._exact:
            return
        reg = self.metrics
        n = self._state_shards()
        fcfg = self.cfg.features
        tables = [t for t, present in
                  (("customer", fcfg.customer_source != "cms"),
                   ("terminal", True)) if present]
        self._m_tier_shard = {
            (t, s): reg.counter(
                "rtfds_feature_tier_rows_total",
                "row x keyspace feature reads served per tier "
                "(dense = private hot-tier slot; cms = count-min "
                "sketch fallback after an admission miss)",
                tier=t, **self._shard_labels(s))
            for t in ("dense", "cms") for s in range(n)
        }
        self._m_slots_occ_shard = {
            (t, s): reg.gauge(
                "rtfds_feature_slots_occupied",
                "hot-tier slots currently owned by a key "
                "(updated at compaction cadence)",
                table=t, **self._shard_labels(s))
            for t in tables for s in range(n)
        }
        self._m_slots_rec_shard = {
            (t, s): reg.counter(
                "rtfds_feature_slots_reclaimed_total",
                "hot-tier slots reclaimed by recency compaction "
                "(the slot held only history older than "
                "delay + max(window))",
                table=t, **self._shard_labels(s))
            for t in tables for s in range(n)
        }

    def _record_compaction(self, fstate, reclaimed) -> None:
        """Per-shard compaction metering: ``reclaimed`` arrives
        ``[n_dev, 2]`` ([customer, terminal] per shard) from the
        shard_map'd pass; occupancy reads come from the stacked
        ``free_top`` leaves. The base (table-level) series are fed the
        shard sums, so the single-chip healthz/dashboard contracts hold
        unchanged on the mesh."""
        rec = np.asarray(reclaimed)  # [n_dev, 2]
        occupied = {}
        occupied_per_shard = [0] * self.n_dev
        cap_total = 0
        for i, table in enumerate(("customer", "terminal")):
            if table in (self._m_slots_rec or {}):
                self._m_slots_rec[table].inc(int(rec[:, i].sum()))
            kd = getattr(fstate, f"{table}_dir")
            if kd is None:
                continue
            cap_local = int(kd.free.shape[1])
            cap_total += cap_local * self.n_dev
            tops = np.asarray(kd.free_top)  # [n_dev]
            occ_t = 0
            for s in range(self.n_dev):
                occ = cap_local - int(tops[s])
                occ_t += occ
                occupied_per_shard[s] += occ
                if self._m_slots_occ_shard is not None:
                    self._m_slots_occ_shard[(table, s)].set(occ)
                if self._m_slots_rec_shard is not None:
                    self._m_slots_rec_shard[(table, s)].inc(
                        int(rec[s, i]))
            if table in (self._m_slots_occ or {}):
                self._m_slots_occ[table].set(occ_t)
            occupied[table] = occ_t
        from real_time_fraud_detection_system_tpu.utils.metrics import (
            active_recorder,
        )

        recorder = self.recorder if self.recorder is not None \
            else active_recorder()
        if recorder is not None:
            tiers = {t: m.value for t, m in (self._m_tier or {}).items()}
            extra = {}
            if self._cold is not None:
                # cold-tier depth + promotion backlog ride the same
                # flight event the dashboard Feature-store tile reads
                extra = {
                    "cold_keys": int(self._cold.keys_count),
                    "cold_bytes": int(self._cold.bytes),
                    "promote_backlog": int(self._promoter.backlog()),
                }
            recorder.record_event(
                "feature_state", reclaimed=int(rec.sum()),
                occupied=sum(occupied.values()),
                capacity=cap_total,
                occupied_per_shard=occupied_per_shard,
                dense_rows=tiers.get("dense", 0.0),
                cms_rows=tiers.get("cms", 0.0),
                batch=self.state.batches_done, **extra)

    # -- cold tier over the mesh -------------------------------------------

    def _promote_payload_sds(self) -> dict:
        """Stacked per-shard promote-payload template: ``[n_dev, K]``
        keys / ``[n_dev, K, NB]`` rows per present table (the shard_map
        splits the leading device axis)."""
        k = self._demote_slots
        nb = self.cfg.features.n_day_buckets
        n = self.n_dev
        tables = self._cold_tables()

        def tbl():
            return (
                jax.ShapeDtypeStruct((n, k), jnp.uint32),
                jax.ShapeDtypeStruct((n, k, nb), jnp.int32),
                jax.ShapeDtypeStruct((n, k, nb), jnp.float32),
                jax.ShapeDtypeStruct((n, k, nb), jnp.float32),
                jax.ShapeDtypeStruct((n, k, nb), jnp.float32),
            )

        return {t: (tbl() if t in tables else None)
                for t in ("customer", "terminal")}

    def _build_promote_payload(self, rows_by_table: dict) -> dict:
        """Owner-modulo-grouped promote payload: key ``k`` lands in
        shard ``k % n_dev``'s lane block — the same stable modulo the
        ingest partitioner and the owner exchange route by, so a key
        demoted by shard *i* promotes back into shard *i*'s directory.
        ``poll_ready(max_items=K)`` bounds total keys at the per-shard
        lane width, so even a fully-skewed ready set fits one block."""
        k = self._demote_slots
        nb = self.cfg.features.n_day_buckets
        n = self.n_dev
        tables = self._cold_tables()
        payload = {}
        for table in ("customer", "terminal"):
            if table not in tables:
                payload[table] = None
                continue
            keys = np.full((n, k), 0xFFFFFFFF, np.uint32)
            bd = np.full((n, k, nb), -1, np.int32)
            cnt = np.zeros((n, k, nb), np.float32)
            amt = np.zeros((n, k, nb), np.float32)
            frd = np.zeros((n, k, nb), np.float32)
            fill = [0] * n
            for key, r in (rows_by_table.get(table) or {}).items():
                s = int(key) % n
                i = fill[s]
                fill[s] = i + 1
                keys[s, i] = key
                bd[s, i], cnt[s, i], amt[s, i], frd[s, i] = r
            payload[table] = (keys, bd, cnt, amt, frd)
        return payload

    # -- sharding upkeep ---------------------------------------------------

    def _ensure_layout(self) -> None:
        """Adopt a restored checkpoint written at a different width or
        process topology: convert to THIS mesh's layout via the elastic
        reshards (exact for the window/history tables)."""
        n_old = int(getattr(self.state, "layout_devices", 1) or 1)
        restored_pc = int(getattr(self.state, "process_count", 1) or 1)
        my_pc = (self.topology.n_processes
                 if self.topology is not None else 1)
        if self.topology is not None and restored_pc == my_pc \
                and my_pc > 1 and n_old != self.n_dev:
            # Defense in depth behind Checkpointer._check_topology's
            # refusal (states can arrive without a checkpoint restore):
            # a per-process width change at fixed P moves residue
            # blocks between processes — no per-process reshard is
            # sound.
            raise ValueError(
                f"restored state was laid out at {n_old} device(s) per "
                f"process; this engine serves {self.n_dev} — in a "
                f"{my_pc}-process fleet that changes residue-block "
                "ownership (key % (P·L)): merge the fleet's "
                "checkpoints (parallel.mesh.merge_process_states) and "
                "re-slice at the new topology")
        if self.topology is not None and restored_pc != my_pc:
            # Checkpointer.restore refuses every other topology change;
            # the one that reaches here is the sanctioned 1→P adoption
            # (a global single-process checkpoint re-sliced per process).
            if restored_pc != 1:
                raise ValueError(
                    f"restored state was written by a {restored_pc}"
                    f"-process fleet; this engine serves a {my_pc}"
                    "-process topology — merge the per-process "
                    "checkpoints first (parallel.mesh."
                    "merge_process_states; README multi-host playbook)")
            from real_time_fraud_detection_system_tpu.parallel.mesh \
                import adopt_process_slice

            self.state.feature_state = adopt_process_slice(
                self.state.feature_state, self.cfg, n_old, self.topology)
            self.state.layout_devices = self.n_dev
            self.state.process_count = self.topology.n_processes
            self.state.process_id = self.topology.process_id
            return
        if n_old == self.n_dev:
            return
        from real_time_fraud_detection_system_tpu.parallel.mesh import (
            reshard_engine_state,
        )

        self.state.feature_state = reshard_engine_state(
            self.kind, self.state.feature_state, self.cfg, n_old,
            self.n_dev, stacked=True)
        self.state.layout_devices = self.n_dev
        # placement over the mesh happens in _ensure_sharded

    def _commit_replicated(self) -> None:
        """Place params + scaler on the mesh with the replicated sharding
        the step RETURNS them in. Skipped when already committed (cheap
        host-side sharding check). Without this, the first step call
        after construction, a checkpoint restore, or a hot model reload
        sees differently-sharded inputs than the previous call produced
        and silently RETRACES inside the serving loop (measured: 969 ms
        vs 8 ms steady-state at width 1)."""
        rep = NamedSharding(self.mesh, P())

        def needs(t) -> bool:
            # Inspect ALL leaves, not just the first one carrying a
            # .sharding: a partially swapped params tree (e.g. a hot
            # reload that replaced some leaves with host arrays) would
            # otherwise be skipped on the strength of its one committed
            # leaf, silently reintroducing the per-call retrace this
            # guard exists to prevent. A leaf WITHOUT a .sharding at all
            # (numpy array, python scalar) is a host leaf and equally
            # needs the commit — after it, every leaf is a committed
            # device array, so this stays a one-shot.
            for leaf in jax.tree.leaves(t):
                sh = getattr(leaf, "sharding", None)
                if sh is None:
                    return True  # host leaf: commit
                if not (isinstance(sh, NamedSharding)
                        and sh.mesh.shape == self.mesh.shape):
                    return True
            return False  # every leaf already mesh-committed (or empty)

        for name in ("params", "scaler"):
            t = getattr(self.state, name)
            if needs(t):
                self._m_commits.inc()
                setattr(self.state, name, jax.tree.map(
                    lambda x: jax.device_put(jnp.asarray(x), rep), t))

    def _ensure_sharded(self) -> None:
        """Re-place the feature state after an external restore.

        ``Checkpointer.restore`` rebuilds leaves as plain device arrays;
        the sharded step wants them laid out over the mesh (jit would
        auto-reshard every call otherwise — correct but wasteful)."""
        self._commit_replicated()  # restore/reload leave them uncommitted
        if self.kind == "sequence":
            from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
                shard_history_state,
            )

            leaf = self.state.feature_state.count
            sh = getattr(leaf, "sharding", None)
            if not (isinstance(sh, NamedSharding) and sh.mesh.shape
                    == self.mesh.shape):
                self.state.feature_state = shard_history_state(
                    self.state.feature_state, self.mesh, axis=self.axis)
            return
        leaf = self.state.feature_state.customer.count
        sh = getattr(leaf, "sharding", None)
        if not (isinstance(sh, NamedSharding) and sh.mesh.shape
                == self.mesh.shape):
            self.state.feature_state = shard_feature_state(
                self.state.feature_state, self.mesh, axis=self.axis
            )

    # -- AOT precompilation over the mesh ----------------------------------

    def dispatch_inventory(self) -> list:
        """Enumerate every sharded dispatch signature — ONE shape family
        (chunks are always ``[7, n_dev * rows_per_shard]``) × TWO step
        variants: the owner-local step and the dense-spill ROUTED step
        (``partition_batch_spill`` overflow re-packing) — plus the
        per-shard ``("compact",)`` recency-compaction pass when the
        tiered exact store runs with a cadence. Same
        single-source-of-truth contract as the single-chip inventory:
        ``precompile`` compiles this list, ``_start_batch`` dispatches
        under these keys, and ``tools/rtfdsverify`` proves contracts
        over it. ``kind='sequence'`` has no AOT path (pytree batches) —
        empty inventory, skipped warmup, nothing to prove."""
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            DispatchSignature,
        )

        if self.kind == "sequence":
            return []
        zmode_kinds = ("tree", "forest", "gbt")
        total = self.n_dev * self.rows_per_shard
        sigs = [
            DispatchSignature(
                key=("sharded", routed),
                variant="sharded-routed" if routed else "sharded-local",
                kind=self.kind,
                z_mode=self.z_mode if self.kind in zmode_kinds else None,
                bucket=total,
                donate=(0,),  # make_sharded_step donates the state tree
                selective=bool(self._selective),
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=bool(self.cfg.runtime.use_pallas),
            )
            for routed in (False, True)
        ]
        if self._compact_every:
            # Per-shard recency compaction is part of the compiled step
            # family on the mesh too: ONE shape (the sharded state + an
            # int32 day scalar), fired from the same batch cadence —
            # enumerated so precompile/verify cover it and the cadence
            # can never pay a mid-stream compile.
            sigs.append(DispatchSignature(
                key=("compact",),
                variant="compact",
                kind=self.kind,
                z_mode=None,
                bucket=0,
                donate=(0,),
                selective=False,
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=False,
            ))
        if self._demote_slots:
            # Cold-tier promotion over the mesh: ONE fixed shape (the
            # sharded state + the owner-grouped [n_dev, K, ...] payload
            # blocks) — enumerated so warmup compiles it and a returning
            # key can never pay a mid-stream compile.
            sigs.append(DispatchSignature(
                key=("promote",),
                variant="promote",
                kind=self.kind,
                z_mode=None,
                bucket=0,
                donate=(0,),
                selective=False,
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=False,
            ))
        return sigs

    def _ensure_step(self, routed: bool):
        """THE lazy build+cache+meter point for both step variants —
        shared by the hot path (``_start_batch``), warmup
        (``precompile`` via ``signature_step``) and the verifier, so
        the serving program, the compiled program and the proven
        program are one object. Templates carry pytree structure only
        (``_sds``); the built jit serves live arrays identically."""
        cached = (self._sharded_step_routed if routed
                  else self._sharded_step)
        if cached is not None:
            return cached
        build = (self._sharded_build_routed if routed
                 else self._sharded_build)
        total = self.n_dev * self.rows_per_shard
        step = build(
            self._sds(self.state.feature_state),
            self._sds(self.state.params),
            self._sds(self.state.scaler),
            jax.ShapeDtypeStruct((7, total), jnp.int32),
        )
        self._m_step_builds.inc()
        if routed:
            self._sharded_step_routed = step
        else:
            self._sharded_step = step
        return step

    def signature_step(self, sig):
        """The shard_map step the signature dispatches to — the same
        lazily-built jit object ``_start_batch`` serves, so a
        lower/trace of this callable IS the serving program."""
        if sig.variant == "compact":
            return self._compact
        if sig.variant == "promote":
            return self._promote
        return self._ensure_step(sig.variant == "sharded-routed")

    def precompile(self) -> dict:
        """AOT-compile BOTH sharded step variants before the first poll.

        Iterates :meth:`dispatch_inventory` (the routed variant
        otherwise first compiles on a hot-key overflow deep into serving
        — a real mid-stream compile, 969 ms measured vs 8 ms
        steady-state, landing exactly when load spikes) via the same
        ``.lower(...).compile()`` path as the single-chip engine
        (shape-only templates; no step executes).
        """
        inventory = self.dispatch_inventory()
        if not inventory:  # kind='sequence' (no AOT path: pytree batches)
            return {"buckets": [], "variants": 0, "seconds": 0.0,
                    "skipped": "sequence"}
        t0 = time.perf_counter()
        self._ensure_layout()
        self._ensure_sharded()
        self.state.params = jax.tree.map(jnp.asarray, self.state.params)
        self._aot_params_sig = self._params_sig(self.state.params)
        variants = 0
        with self.tracer.span("precompile"):
            for sig in inventory:
                if sig.key in self._aot:
                    continue
                step = self.signature_step(sig)
                self._aot[sig.key] = step.lower(
                    *self.signature_templates(sig)).compile()
                self._m_precompiled.inc()
                variants += 1
        return {
            "buckets": sorted({s.bucket for s in inventory}),
            "variants": variants,
            "seconds": round(time.perf_counter() - t0, 3),
        }

    # -- the sharded hot path ----------------------------------------------

    def _validate_sharded(self, cols: dict) -> None:
        """Strict-ingest check with CHUNK-level attribution: beyond the
        single-chip engine's row facts, the PoisonRowError names the
        shard placements (``customer_id % n_dev``) the corrupt rows were
        headed for — so a crash-loop diagnosis on a mesh points at the
        chunks, not just the batch. The predicate itself lives in ONE
        place (validate_ingest_rows); only the attribution is added here
        (computed solely on failure)."""
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            validate_ingest_rows,
        )

        def detail(bad):
            shards = sorted(set(
                (np.asarray(cols["customer_id"])[bad]
                 % self.n_dev).astype(int).tolist()))
            return f"shard placement(s) {shards[:8]}"

        validate_ingest_rows(cols, detail_fn=detail)
        topo = self.topology
        if (topo is not None and topo.strict_affinity
                and len(cols["tx_id"])):
            # Partition-affinity contract: every polled row's customer
            # residue must be ours. A breach means two processes would
            # serve the same key's history — fail fast before any state
            # diverges, naming the mis-wired side.
            owner = topo.owner_process(cols["customer_id"])
            mine = owner == topo.process_id
            if not mine.all():
                others = sorted(set(owner[~mine].tolist()))
                raise ValueError(
                    f"partition-affinity breach: {int((~mine).sum())} "
                    f"polled row(s) belong to process(es) {others[:4]} "
                    f"but this is process {topo.process_id} — fix the "
                    "launcher's source slicing (PartitionAffineSource "
                    "residues / Kafka partition blocks), or pass "
                    "strict_affinity=False for a broker-partitioned "
                    "fleet whose keys are not residue-aligned")

    def _start_batch(self, cols: dict) -> dict:
        """Dedup → partition (spill) → launch sharded step(s), async.

        Device results stay futures in the handle; :meth:`_finish_batch`
        materializes and re-assembles them in input order — so
        :meth:`~.engine.ScoringEngine.run`'s double-buffering overlaps the
        next batch's partition + H2D with this batch's mesh compute.
        """
        t0 = time.perf_counter()
        with self.tracer.span("host_prep"):
            keep = latest_wins_mask_host(cols["tx_id"], cols["kafka_ts_ms"])
            cols = {k: v[keep] for k, v in cols.items()}
            self._validate_sharded(cols)
            n = len(cols["tx_id"])
            self._ensure_sharded()
            if n:
                # Same placement rule as partition_batch_spill
                # (customer_id % n_dev): one bincount per batch, so the
                # dashboard can see hot-key imbalance the moment it
                # starts spilling.
                loads = np.bincount(
                    (cols["customer_id"] % self.n_dev).astype(np.int64),
                    minlength=self.n_dev)
                for i, g in enumerate(self._m_shard_rows):
                    g.set(int(loads[i]))

            chunks = partition_batch_spill(
                cols, self.n_dev, self.rows_per_shard
            ) if n else []
        # host prep ends here: the chunk loop below is dispatch (make_
        # batch + H2D + jit launches), split out so the sharded loop's
        # phase decomposition matches the single-chip engine's.
        t_prep = time.perf_counter()
        parts = []
        tier_parts = []  # exact mode: per-chunk [n_dev, 2] tier rows
        t_fetch = None  # last chunk's async-fetch issue time
        for part_cols, rows, pos in chunks:
            batch = make_batch(
                customer_id=part_cols["customer_id"],
                terminal_id=part_cols["terminal_id"],
                tx_datetime_us=part_cols["tx_datetime_us"],
                amount_cents=part_cols["tx_amount_cents"],
                label=np.where(
                    part_cols["__valid__"],
                    part_cols.get(
                        "label",
                        np.full(len(part_cols["__valid__"]), -1, np.int64),
                    ),
                    -1,
                ),
            )
            batch = batch._replace(valid=part_cols["__valid__"])
            if self.kind != "sequence":
                # One packed H2D copy per chunk (pack_batch layout); the
                # packed step bitcasts it back inside the jit. Seven
                # separate leaf transfers pay seven per-call overheads —
                # most of the sharded loop's fixed cost on a remote chip.
                jbatch = jnp.asarray(pack_batch(batch))
            else:
                jbatch = jax.tree.map(jnp.asarray, batch)
            routed = bool(part_cols.get("__routed__", False))
            if self.kind == "sequence":
                step = self._seq_step_routed if routed else self._seq_step
                # original batch row index per chunk slot — the
                # same-second tiebreaker (chunk packing permutes rows)
                okey = np.zeros(len(part_cols["__valid__"]), np.int32)
                okey[pos] = rows.astype(np.int32)
                sig = step_signature(
                    *jax.tree.leaves(jbatch),
                    static=(self.kind, routed, self.n_dev))
                with self._recompile.step(sig):
                    hstate, probs = step(
                        self.state.feature_state, self.state.params,
                        jbatch, jnp.asarray(okey))
                self.state.feature_state = hstate
                t_fetch = self._issue_host_fetch(probs, None) or t_fetch
                # the sequence scorer has no engineered feature matrix;
                # None skips the feats copy (_finish_batch's buffer is 0)
                parts.append((rows, pos, probs, None))
                continue
            # The detector window covers the lazy step BUILD too: a
            # routed variant first compiled on a hot-key overflow deep
            # into serving is a real in-loop compile and must alarm.
            # z_mode rides the statics: the sharded step closes over the
            # base engine's z-mode-aware predict.
            sig = step_signature(
                jbatch,
                static=(self.kind, routed, self.n_dev, self.z_mode))
            with self._recompile.step(sig):
                step = self._ensure_step(routed)
                out = self._dispatch_step(
                    ("sharded", routed), step,
                    self.state.feature_state, self.state.params,
                    self.state.scaler, jbatch,
                )
            fstate, params, probs, feats = out[:4]
            if self._exact:
                # [n_dev, 2] per-shard [dense, cms] rows served this
                # chunk — accumulated across chunks, materialized at
                # finish (scalar-sized; no async fetch needed)
                tier_parts.append(out[4])
            self.state.feature_state = fstate
            self.state.params = params
            # async D2H per chunk: each chunk's transfer starts the
            # moment ITS compute finishes, overlapping later chunk
            # dispatches and the next batch's host prep
            t_fetch = self._issue_host_fetch(probs, feats) or t_fetch
            parts.append((rows, pos, probs, feats))
        t_disp = time.perf_counter()
        if chunks:
            # one dispatch span over all chunk launches (the per-chunk
            # jit calls are its children on the profiler timeline)
            self.tracer.add_span("dispatch", t_prep, t_disp,
                                 chunks=len(chunks))
        handle = {"cols": cols, "n": n, "parts": parts, "t0": t0,
                  "prep_s": t_prep - t0, "dispatch_s": t_disp - t_prep,
                  "fetch_issue_t": t_fetch}
        if tier_parts:
            handle["tier_shard"] = tier_parts
        # notify compaction's recency cutoff (the base engine does this
        # in its own _start_batch; the sharded path overrides it wholesale)
        self._note_batch_days(cols)
        self._note_cold_touches(cols)
        return handle

    def _finish_batch(self, handle: dict) -> BatchResult:
        n = handle["n"]
        self._meter_fetch_overlap(handle)
        # _emit_features_now, not the raw config flag: the overload
        # ladder's rung-2 degrade (inherited run() loop) switches the
        # mesh engine to alerts-only emission the same host-side way —
        # the shard_map step and both AOT variants are untouched.
        emit = self._emit_features_now()
        probs_np = np.zeros(n, dtype=np.float32)
        if self.kind == "sequence" or not emit:
            # nothing below writes the feature matrix on these paths
            # (sequence parts carry feats=None; alerts-only skips the
            # per-shard feats copy) — share the read-only staging buffer
            feats_np = self._zero_features(n)
        else:
            feats_np = np.zeros((n, N_FEATURES), dtype=np.float32)
        overflowed = False  # per BATCH, however many chunks overflow
        for rows, pos, probs, feats in handle["parts"]:
            if isinstance(feats, dict):
                # selective emission: one packed fetch per chunk carries
                # [probs(pad) | count | idx(cap) | feats(cap·15)] — the
                # same layout the single-chip engine unpacks; indices are
                # chunk SLOTS, mapped back to original batch rows via the
                # chunk's (pos → rows) placement.
                pad = feats["full"].shape[0]
                cap = ((feats["packed"].shape[0] - pad - 1)
                       // (1 + N_FEATURES))
                flat = np.asarray(feats["packed"])
                probs_np[rows] = flat[:pad][pos]
                count = int(flat[pad])
                if count > cap:
                    overflowed = True
                    feats_np[rows] = np.asarray(feats["full"])[pos]
                elif count:
                    idx = flat[pad + 1:pad + 1 + count].astype(np.int64)
                    sel = flat[pad + 1 + cap:
                               pad + 1 + cap + count * N_FEATURES]
                    slot_to_row = np.full(pad, -1, np.int64)
                    slot_to_row[pos] = rows
                    # flagged slots are valid by construction, so every
                    # target is a real batch row
                    feats_np[slot_to_row[idx]] = sel.reshape(
                        count, N_FEATURES)
                continue
            probs_np[rows] = np.asarray(probs)[pos]
            if feats is not None and emit:
                # alerts-only mode skips the per-shard feature D2H, same
                # contract as the single-chip engine
                feats_np[rows] = np.asarray(feats)[pos]
        if overflowed:
            # once per batch, matching the single-chip counter semantics
            # (engine.py: "batches whose flagged-row count overflowed")
            self.selective_overflows += 1
        tier_parts = handle.pop("tier_shard", None)
        if tier_parts is not None:
            # per-shard tier accounting ([n_dev, 2] summed over chunks):
            # shard-labeled counters get their own rows, the base
            # table-level counters get the shard sums — so the global
            # healthz/dashboard contract is identical on the mesh.
            tier = np.zeros((self.n_dev, 2), np.float64)
            for t in tier_parts:
                tier += np.asarray(t)
            if self._m_tier_shard is not None:
                for s in range(self.n_dev):
                    self._m_tier_shard[("dense", s)].inc(float(tier[s, 0]))
                    self._m_tier_shard[("cms", s)].inc(float(tier[s, 1]))
            handle["tier"] = tier.sum(axis=0)  # [dense, cms] global
        return self._emit_result(handle, probs_np, feats_np)

    # -- feedback into the owner-partitioned terminal table ----------------

    def apply_state_feedback(
        self,
        terminal_ids: np.ndarray,
        days: np.ndarray,
        labels: np.ndarray,
    ) -> None:
        """Land delayed fraud labels in the sharded terminal risk windows.

        The sharded layout places terminal key k at global row
        ``(k % n_dev) * cap_local + ((k // n_dev) & (cap_local - 1))``
        (owner shard × local slot, mirroring ``parallel/step.py``). The
        scatter runs as a plain jitted global-array op — GSPMD inserts the
        (off-hot-path) collectives."""
        # cross-width restored state must convert before any slot scatter
        self._ensure_layout()
        if self.kind == "sequence":
            raise ValueError(
                "the labeled-feedback loop is not wired for "
                "kind='sequence'")
        labels = np.asarray(labels)
        mask = labels >= 0
        if not mask.any():
            return
        self._ensure_sharded()
        n_dev = self.n_dev
        cap_local = self.cfg.features.terminal_capacity // n_dev
        key = fold_key(np.asarray(terminal_ids)[mask]).astype(np.uint32)
        if self._exact:
            # Directory-routed feedback: ownership is key % n_dev (the
            # step's routing modulo), the slot is a LOOKUP into the
            # owner's directory — hits land in the owner's dense window
            # rows, misses in the owner's sketch replica's fraud column
            # (features/online.apply_feedback_sharded_exact; never an
            # insert, so feedback cannot evict live traffic's slots).
            if self._sharded_sf_exact is None:
                from real_time_fraud_detection_system_tpu.features.online \
                    import apply_feedback_sharded_exact

                fcfg = self.cfg.features

                def sfx(fstate, tk, dd, yy, valid):
                    return apply_feedback_sharded_exact(
                        fstate, tk, dd, yy, valid, fcfg)

                self._sharded_sf_exact = jax.jit(sfx, donate_argnums=(0,))
        elif self._sharded_sf is None:
            self._sharded_sf = jax.jit(
                apply_feedback_at_slot, donate_argnums=(0,)
            )
        if not self._exact:
            gslot = (
                (key % np.uint32(n_dev)).astype(np.int64) * cap_local
                + ((key // np.uint32(n_dev)) & np.uint32(cap_local - 1))
            ).astype(np.int32)
        d = np.asarray(days)[mask].astype(np.int32)
        y = labels[mask].astype(np.int32)
        # Bucket-pad like the single-chip path (engine.py) so a stream of
        # ever-different label counts hits ONE jit cache entry, not one
        # compile per length.
        biggest = max(self.cfg.runtime.batch_buckets)
        for s in range(0, len(y), biggest):
            m = len(y[s : s + biggest])
            pad = bucket_size(m, self.cfg.runtime.batch_buckets)
            dd = np.zeros(pad, dtype=np.int32)
            dd[:m] = d[s : s + m]
            yy = np.zeros(pad, dtype=np.int32)
            yy[:m] = y[s : s + m]
            valid = np.zeros(pad, dtype=bool)
            valid[:m] = True
            if self._exact:
                tk = np.zeros(pad, dtype=np.uint32)
                tk[:m] = key[s : s + m]
                self.state.feature_state = self._sharded_sf_exact(
                    self.state.feature_state, jnp.asarray(tk),
                    jnp.asarray(dd), jnp.asarray(yy), jnp.asarray(valid),
                )
                continue
            gs = np.zeros(pad, dtype=np.int32)
            gs[:m] = gslot[s : s + m]
            self.state.feature_state = self._sharded_sf(
                self.state.feature_state, jnp.asarray(gs), jnp.asarray(dd),
                jnp.asarray(yy), jnp.asarray(valid),
            )
