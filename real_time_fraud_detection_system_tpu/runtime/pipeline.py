"""End-to-end demo pipeline — the reference's full docker-compose flow,
in-process.

The reference demo (README.md:31-43 + ``datagen/data_gen.py``) is:
datagen INSERTs → Postgres WAL → Debezium → Kafka topics
``debezium.payment.{customers,terminals,transactions}`` → three Spark sink
jobs MERGE into Iceberg → the ``fraud_detection.py`` scorer streams the
transaction table and appends ``analyzed_transactions``.

:func:`run_demo` plays the same movie without Docker:

1. generate profiles + transactions (``data/generator.py``);
2. train a model on the early window (offline notebook chain);
3. encode everything as Debezium envelopes into an :class:`InProcBroker`
   (the Kafka role), customers/terminals first (snapshot), then the
   post-train transaction stream;
4. "job1"/"job2": decode profile envelopes → MERGE into
   :class:`~..io.tables.UpsertTable` dimension tables;
5. "job3"+scorer fused: the :class:`ScoringEngine` consumes transaction
   envelopes (decode → latest-wins dedup → stateful features → classify)
   and appends to the analyzed sink — one jitted step instead of Spark's
   four process hops.

Returns a summary dict with table sizes, stream stats, and AUC of the
streamed scores against ground-truth labels.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.core.envelope import (
    decode_profile_envelopes,
    encode_profile_envelopes,
)
from real_time_fraud_detection_system_tpu.core.schema import (
    CUSTOMERS,
    TERMINALS,
)
from real_time_fraud_detection_system_tpu.io.tables import UpsertTable
from real_time_fraud_detection_system_tpu.models.train import (
    TrainedModel,
    train_model,
)
from real_time_fraud_detection_system_tpu.runtime.engine import ScoringEngine
from real_time_fraud_detection_system_tpu.runtime.sources import (
    InProcBroker,
    ReplaySource,
)
from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.timing import date_to_epoch_s

log = get_logger("pipeline")


def sink_dimension_topic(
    broker: InProcBroker,
    topic: str,
    schema,
    table: Optional[UpsertTable] = None,
    batch_rows: int = 4096,
) -> UpsertTable:
    """job1/job2: drain a profile topic into an UpsertTable via MERGE."""
    if table is None:
        table = UpsertTable(schema)
    offsets = [0] * broker.n_partitions
    while True:
        msgs, ts = [], []
        for p in range(broker.n_partitions):
            recs = broker.poll(topic, p, offsets[p], batch_rows)
            offsets[p] += len(recs)
            msgs += [r.value for r in recs]
            ts += [r.ts_ms for r in recs]
        if not msgs:
            break
        cols, invalid = decode_profile_envelopes(msgs, schema.fields, ts)
        table.merge(cols, valid=~invalid)
    return table


def run_demo(
    cfg: Config,
    model: Optional[TrainedModel] = None,
    model_kind: str = "forest",
    out_dir: Optional[str] = None,
    stream_days: Optional[int] = None,
    batch_rows: int = 4096,
    n_devices: int = 1,
) -> dict:
    """Full generate → CDC → sink → score flow; returns a summary dict.

    ``n_devices > 1`` serves the scoring leg on the sharded multi-chip
    engine (customer-partitioned rows, terminal all_to_all) — the same
    E2E movie at the reference's scaled-out deployment shape."""
    from real_time_fraud_detection_system_tpu.data.generator import (
        generate_dataset,
    )

    t0 = time.perf_counter()
    customers, terminals, txs = generate_dataset(cfg.data)
    log.info(
        "generated %d txs, %d customers, %d terminals",
        txs.n, customers.n, terminals.n,
    )

    if model is None:
        model, train_metrics = train_model(txs, cfg, kind=model_kind)
        log.info("trained %s: %s", model_kind, train_metrics)
    else:
        train_metrics = {}
        model_kind = model.kind

    # --- CDC ingress: snapshot the dimension tables, stream transactions.
    broker = InProcBroker(cfg.runtime.n_partitions)
    epoch0 = date_to_epoch_s(cfg.data.start_date)
    cust_cols = {
        "customer_id": customers.customer_id,
        "x_location": customers.x,
        "y_location": customers.y,
    }
    term_cols = {
        "terminal_id": terminals.terminal_id,
        "x_location": terminals.x,
        "y_location": terminals.y,
    }
    for topic, cols, keycol in (
        ("debezium.payment.customers", cust_cols, "customer_id"),
        ("debezium.payment.terminals", term_cols, "terminal_id"),
    ):
        msgs = encode_profile_envelopes(
            topic.rsplit(".", 1)[1], cols, ts_ms=epoch0 * 1000
        )
        keys = [str(int(k)).encode() for k in cols[keycol]]
        broker.produce_many(topic, keys, msgs,
                            ts_ms=[epoch0 * 1000] * len(msgs))

    # job1/job2: MERGE the dimension snapshots.
    customer_table = sink_dimension_topic(
        broker, "debezium.payment.customers", CUSTOMERS
    )
    terminal_table = sink_dimension_topic(
        broker, "debezium.payment.terminals", TERMINALS
    )
    log.info(
        "dimension tables: %d customers, %d terminals",
        len(customer_table), len(terminal_table),
    )

    # The live stream: everything after the training horizon (the engine's
    # feature state warm-starts by replaying the horizon itself).
    horizon = cfg.train.delta_train_days + cfg.train.delta_delay_days
    if stream_days is not None:
        horizon = max(cfg.data.n_days - stream_days, 0)
    stream_mask = txs.tx_time_days >= horizon
    stream = txs.slice(np.flatnonzero(stream_mask))
    warm = txs.slice(np.flatnonzero(~stream_mask))

    if n_devices > 1:
        from real_time_fraud_detection_system_tpu.runtime.sharded_engine import (
            ShardedScoringEngine,
        )

        engine = ShardedScoringEngine(
            cfg, kind=model_kind, params=model.params, scaler=model.scaler,
            n_devices=n_devices,
        )
    else:
        engine = ScoringEngine(
            cfg, kind=model_kind, params=model.params, scaler=model.scaler
        )
    if warm.n:
        warm_src = ReplaySource(warm, epoch0, batch_rows=65536)
        engine.run(warm_src)  # state warm-up, scores discarded

    sink = None
    raw_table = None
    if out_dir is not None:
        import os

        from real_time_fraud_detection_system_tpu.io.sink import ParquetSink
        from real_time_fraud_detection_system_tpu.io.tables import (
            RawTransactionsTable,
        )

        sink = ParquetSink(out_dir)
        # The persistent raw-transactions table (the reference's
        # day-partitioned nessie.payment.transactions) lands beside the
        # analyzed output.
        raw_table = RawTransactionsTable(os.path.join(out_dir, "transactions"))
    from real_time_fraud_detection_system_tpu.io.sink import (
        FanoutSink,
        MemorySink,
    )

    mem = MemorySink()
    tee = FanoutSink(mem, sink, raw_table)

    src = ReplaySource(
        stream, epoch0, batch_rows=batch_rows, mode="envelope",
        n_partitions=cfg.runtime.n_partitions,
    )
    stats = engine.run(src, sink=tee)
    streamed_rows = int(stats["rows"])  # run() reports per-run deltas
    rows_per_s = streamed_rows / stats["wall_s"] if stats["wall_s"] > 0 else 0.0

    # Ground-truth assessment of the streamed scores (possible only in the
    # synthetic demo: the generator knows the labels). Join on tx_id.
    out = mem.concat()
    if not out:  # empty stream: horizon covered the whole dataset
        log.warning(
            "no rows streamed (train+delay horizon %d >= %d days); "
            "nothing to assess", horizon, cfg.data.n_days,
        )
        out = {"tx_id": np.zeros(0, np.int64),
               "prediction": np.zeros(0, np.float64)}
    order = np.argsort(out["tx_id"], kind="mergesort")
    out_ids = out["tx_id"][order]
    probs = out["prediction"][order]
    sid = np.argsort(stream.tx_id, kind="mergesort")
    stream_ids = stream.tx_id[sid]
    stream_labels = stream.tx_fraud[sid]
    pos = np.searchsorted(stream_ids, out_ids)
    pos_c = np.clip(pos, 0, max(len(stream_ids) - 1, 0))
    ok = (pos < len(stream_ids)) & (stream_ids[pos_c] == out_ids)
    from real_time_fraud_detection_system_tpu.models.metrics import roc_auc

    auc = roc_auc(stream_labels[pos_c[ok]], probs[ok])

    tee.flush()

    summary = {
        "customers": len(customer_table),
        "terminals": len(terminal_table),
        "raw_tx_rows": len(raw_table) if raw_table is not None else 0,
        "warm_rows": int(warm.n),
        "streamed_rows": streamed_rows,
        "rows_per_s": float(rows_per_s),
        "latency_p50_ms": float(stats["latency_p50_ms"]),
        "latency_p99_ms": float(stats["latency_p99_ms"]),
        "stream_auc": float(auc),
        "flagged_at_0.5": int((probs >= 0.5).sum()),
        "train_metrics": train_metrics,
        "wall_s": time.perf_counter() - t0,
    }
    log.info("demo summary: %s", summary)
    return summary
