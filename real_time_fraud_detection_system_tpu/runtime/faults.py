"""Failure detection, retry policies, fault injection, supervised recovery.

The reference's resilience machinery is compose-level only (SURVEY §5.3):
healthchecks + ``restart:`` policies (``docker-compose.yml:83-87,133``), the
datagen 4×5 s connect retry (``datagen/data_gen.py:72-80``), tolerated model
-download 404s (``fraud_detection.py:73-79``), and Spark checkpoint replay.
It has **no fault injection at all**. This module provides the in-process
equivalents plus the missing injection tools:

- :class:`RetryPolicy` / :func:`with_retries` — exponential-backoff retry,
  the ``psycopg2`` connect-loop analogue;
- :class:`Heartbeat` — stall detection for the micro-batch loop (the
  healthcheck role: no progress for ``timeout_s`` → unhealthy);
- :class:`FlakySource` / :func:`corrupt_messages` — deterministic fault
  injectors: scripted transient poll failures (source wrapper) and
  scripted envelope corruption (message transform);
- :func:`run_with_recovery` — the ``restart: on-failure`` supervisor: on a
  crash, rebuild the engine state from the last checkpoint, seek the
  source, resume; exactly-once at micro-batch granularity because offsets
  and state are checkpointed atomically together (``io/checkpoint.py``).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple, Type

from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)

log = get_logger("faults")


def _record_fault(kind: str, count: int = 1, **fields) -> None:
    """Count an injected fault (by kind) and land it in the flight
    record, so a chaos run's telemetry shows exactly which failures were
    scripted vs organic."""
    get_registry().counter(
        "rtfds_faults_injected_total", "injected faults by kind",
        kind=kind).inc(count)
    rec = active_recorder()
    if rec is not None:
        rec.record_event("fault", fault_kind=kind, count=count, **fields)


class TransientError(RuntimeError):
    """An injected or genuinely transient failure — safe to retry."""


class StallError(TransientError):
    """The watchdog found no engine progress within the stall budget."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay = base * multiplier^attempt (capped)."""

    max_attempts: int = 4
    base_delay_s: float = 5.0
    multiplier: float = 1.0  # reference uses constant 5 s sleeps
    max_delay_s: float = 60.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def delay(self, attempt: int) -> float:
        return min(self.base_delay_s * self.multiplier**attempt,
                   self.max_delay_s)


def with_retries(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    retry_on: Tuple[Type[BaseException], ...] = (TransientError,),
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with up to ``max_attempts`` tries (the datagen connect
    loop, ``data_gen.py:72-80``). Non-listed exceptions propagate at once."""
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if attempt + 1 < policy.max_attempts:
                d = policy.delay(attempt)
                log.warning("attempt %d/%d failed (%s); retrying in %.1fs",
                            attempt + 1, policy.max_attempts, e, d)
                sleep(d)
    raise last  # type: ignore[misc]


class Heartbeat:
    """Progress-based failure detector (the compose healthcheck role).

    ``beat()`` on every engine loop pass (:func:`run_with_recovery` wires
    the heartbeat into ``engine.run``); ``healthy()`` is False once
    ``timeout_s`` passes with no beat. :func:`run_with_recovery` watches it
    from a supervisor thread and escalates a stall into the restart path
    (:class:`StallError`) — a silently hung source or device step is
    recovered from like a crash, not waited on forever.
    """

    def __init__(self, timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._last = clock()
        self.beats = 0

    def beat(self) -> None:
        self._last = self._clock()
        self.beats += 1

    def healthy(self) -> bool:
        return (self._clock() - self._last) <= self.timeout_s

    def seconds_since_beat(self) -> float:
        return self._clock() - self._last


class HangingSource:
    """Wraps a source; scripted poll indices HANG (block silently) instead
    of raising — the failure mode retries can't see and only a watchdog
    catches (a dead TPU tunnel, a wedged Kafka client, a stuck NFS read).

    Each scripted hang fires once: the incarnation that hit it stays
    blocked (until ``release`` or ``max_hang_s``), and the restarted
    incarnation's polls proceed — modeling a connection that is re-opened
    by the restart while the old one stays wedged.
    """

    def __init__(self, inner, hang_at: Sequence[int] = (),
                 max_hang_s: float = 60.0):
        import threading

        self.inner = inner
        self.hang_at = set(int(i) for i in hang_at)
        self.max_hang_s = max_hang_s
        self.release = threading.Event()
        self._polls = 0

    def poll_batch(self):
        i = self._polls
        self._polls += 1
        if i in self.hang_at:
            self.hang_at.discard(i)
            _record_fault("hang", poll=i)
            self.release.wait(timeout=self.max_hang_s)  # silent stall
        return self.inner.poll_batch()

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)


class FlakySource:
    """Wraps a source; raises TransientError on scripted poll indices.

    ``fail_at`` lists 0-based poll indices that raise *instead of* returning
    the batch; the underlying source is only advanced on success, so a
    retried poll returns the batch the failure swallowed — exactly like a
    Kafka consumer that died before committing.
    """

    def __init__(self, inner, fail_at: Sequence[int] = ()):
        self.inner = inner
        self.fail_at = set(int(i) for i in fail_at)
        self._polls = 0

    def poll_batch(self):
        i = self._polls
        self._polls += 1
        if i in self.fail_at:
            _record_fault("flaky_poll", poll=i)
            raise TransientError(f"injected poll failure #{i}")
        return self.inner.poll_batch()

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)


def corrupt_messages(msgs: Sequence[bytes],
                     corrupt_every: int = 17) -> list:
    """Envelope-level fault injection: truncate every k-th message.

    Corrupt envelopes must be masked by the decoder, never crash the batch
    (the golden-decode robustness property, SURVEY §4). Produce the result
    into a broker/topic to exercise the full envelope path."""
    k = max(int(corrupt_every), 1)
    out = [
        m[: max(len(m) // 2, 1)] if i % k == k - 1 else m
        for i, m in enumerate(msgs)
    ]
    n_corrupt = len(msgs) // k
    if n_corrupt:
        _record_fault("corrupt_envelope", count=n_corrupt)
    return out


class _FencedCheckpointer:
    """Restores only checkpoints saved through THIS wrapper.

    Used by :func:`run_with_recovery` when ``resume=False``: a stale
    checkpoint left by a previous run must never be restored by a crash
    incarnation of a run that explicitly asked for a fresh start. The
    pre-existing checkpoint files are recorded at construction and left
    untouched until this run's FIRST save — if the fresh run dies before
    ever saving, the previous run's checkpoints remain resumable. The
    first save supersedes the old lineage: the stale files are renamed
    aside (``stale-<token>-ckpt-…``, bytes preserved, unique token so
    repeated fresh runs never clobber each other's stash) so they are
    invisible to ``latest()`` AND to the retention GC — otherwise `keep`
    stale higher-numbered files would garbage-collect this run's first
    saves the moment they land.
    """

    def __init__(self, inner):
        self.inner = inner
        self._saved: list = []
        # Lineage API (Checkpointer AND StoreCheckpointer provide it):
        # record the pre-existing checkpoints to quarantine on first save.
        self._stale: list = list(inner.list_checkpoints())

    def save(self, engine_state):
        if self._stale:
            self.inner.quarantine(self._stale, uuid.uuid4().hex[:8])
            self._stale = []
        path = self.inner.save(engine_state)
        self._saved.append(path)
        return path

    def restore(self, engine_state, path=None):
        if path is None:
            # inner.exists filters saves the inner's own GC removed —
            # storage-agnostic (os.path.exists would wrongly drop every
            # object-store key).
            mine = [p for p in self._saved if self.inner.exists(p)]
            if not mine:
                return None
            path = max(mine)
        return self.inner.restore(engine_state, path=path)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _AbandonFence:
    """Shared flag: flipped when the watchdog abandons an incarnation."""

    def __init__(self):
        self.abandoned = False

    def check(self) -> None:
        if self.abandoned:
            raise StallError("incarnation abandoned by the watchdog")


class _FenceGuard:
    """Proxy that cuts a zombie incarnation off from shared objects.

    Every attribute access (method call, ``offsets`` property, heartbeat
    ``beat``) first checks the fence: once the watchdog abandons the
    incarnation, the zombie's next interaction with the source, sink,
    checkpointer, or heartbeat raises :class:`StallError` inside the
    zombie thread — it cannot steal batches from the restarted
    incarnation, overwrite the live checkpoint with stale state, append
    stale results, or mask real stalls by beating the shared heartbeat.
    (Whole checkpoints are atomic snapshots, so a save that *completes*
    just before abandonment is still consistent.)
    """

    def __init__(self, inner, fence: _AbandonFence):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_fence", fence)

    def __getattr__(self, name):
        fence = object.__getattribute__(self, "_fence")
        inner = object.__getattribute__(self, "_inner")
        attr = getattr(inner, name)
        if callable(attr):
            def _guarded(*a, **k):
                fence.check()
                return attr(*a, **k)

            return _guarded
        fence.check()
        return attr

    def __setattr__(self, name, value):
        fence = object.__getattribute__(self, "_fence")
        fence.check()
        setattr(object.__getattribute__(self, "_inner"), name, value)


class _GuardedSource(_FenceGuard):
    """Source fence with a post-poll check.

    Beyond the inherited pre-access checks, a poll that was already in
    flight when the watchdog abandoned this incarnation needs one more
    check AFTER it returns: when the hang finally releases, the zombie's
    poll may have consumed rows from a source SHARED with the restarted
    incarnation. The post-check drops that batch and kills the zombie —
    at-most-one-batch loss in that double-fault race, never a mis-seek.
    The clean fix is not sharing the cursor at all: pass ``make_source``
    to :func:`run_with_recovery` so each incarnation owns a fresh source
    session (what a real Kafka deployment gets from consumer-group
    generation fencing: a zombie consumer's partitions are revoked, its
    late poll cannot commit).
    """

    def poll_batch(self):
        fence = object.__getattribute__(self, "_fence")
        inner = object.__getattribute__(self, "_inner")
        fence.check()
        cols = inner.poll_batch()
        fence.check()  # in-flight poll that outlived abandonment: drop
        return cols


def _run_watched(engine, source, sink, checkpointer, max_batches,
                 heartbeat: Heartbeat, feedback=None, model_reload=None):
    """Run one engine incarnation under a stall watchdog.

    The engine loop runs in a worker thread beating the heartbeat each
    pass; this (supervisor) thread polls ``healthy()``. On a stall the
    worker is ABANDONED — a thread blocked in a hung syscall/device call
    cannot be killed — and :class:`StallError` escalates into the restart
    path. The abandoned worker is fenced (:class:`_FenceGuard`): when its
    hang eventually releases, its first touch of the shared source, sink,
    checkpointer, or heartbeat raises and the zombie dies, instead of
    corrupting the restarted incarnation's stream.
    """
    import threading

    box: dict = {}
    fence = _AbandonFence()
    g_source = _GuardedSource(source, fence)
    g_sink = _FenceGuard(sink, fence) if sink is not None else None
    g_ckpt = _FenceGuard(checkpointer, fence) if checkpointer is not None \
        else None
    g_heartbeat = _FenceGuard(heartbeat, fence)
    g_feedback = _FenceGuard(feedback, fence) if feedback is not None \
        else None
    if getattr(engine, "feature_cache", None) is not None:
        # The cache outlives incarnations (it's how the feedback join
        # finds rows scored before a restart) — fence THIS incarnation's
        # handle so a zombie can't overwrite rows the live incarnation
        # re-scored (or reset their labeled marks, double-applying
        # additive label scatters).
        engine.feature_cache = _FenceGuard(engine.feature_cache, fence)

    def _target():
        try:
            box["stats"] = engine.run(
                g_source, sink=g_sink, checkpointer=g_ckpt,
                max_batches=max_batches, heartbeat=g_heartbeat,
                feedback=g_feedback, model_reload=model_reload,
            )
        except BaseException as e:  # report into the supervisor thread
            box["err"] = e

    heartbeat.beat()  # incarnation start = progress
    worker = threading.Thread(target=_target, daemon=True,
                              name="engine-incarnation")
    worker.start()
    poll = min(max(heartbeat.timeout_s / 4.0, 0.01), 1.0)
    while worker.is_alive():
        worker.join(poll)
        if worker.is_alive() and not heartbeat.healthy():
            fence.abandoned = True
            raise StallError(
                f"no engine progress for "
                f"{heartbeat.seconds_since_beat():.1f}s (stall budget "
                f"{heartbeat.timeout_s:.1f}s); abandoning hung incarnation"
            )
    if "err" in box:
        raise box["err"]
    return box["stats"]


def run_with_recovery(
    make_engine: Callable[[], object],
    source=None,
    checkpointer=None,
    sink=None,
    max_restarts: int = 3,
    max_batches: int = 0,
    heartbeat: Optional[Heartbeat] = None,
    stall_timeout_s: float = 0.0,
    resume: bool = True,
    make_source: Optional[Callable[[], object]] = None,
    make_feedback: Optional[Callable[[object], object]] = None,
    make_model_reload: Optional[Callable[[], object]] = None,
    recover_on: Tuple[Type[BaseException], ...] = (
        TransientError, OSError, ConnectionError,
    ),
) -> dict:
    """Supervisor loop: run → on crash OR stall, restore checkpoint, resume.

    ``make_engine`` builds a fresh engine (state template) per incarnation;
    the checkpointer restores (offsets, feature state, params, scaler) into
    it and the source seeks to the checkpointed offsets, so every committed
    micro-batch is processed exactly once and uncommitted ones are replayed
    — Spark's checkpointLocation recovery contract (SURVEY §5.4).

    Stall watchdog: pass ``stall_timeout_s`` (or a pre-built ``heartbeat``)
    and each incarnation runs in a worker thread beating the heartbeat per
    loop pass while the supervisor watches ``healthy()`` — a silently hung
    source or device step (the failure retries can't see: no exception is
    ever raised) is detected within the stall budget and recovered like a
    crash. Without either, the loop is synchronous and reacts to
    exceptions only.

    ``make_feedback``: factory called with each incarnation's engine to
    build its labeled-feedback loop (a fresh consumer session per
    incarnation in production — see :class:`~.feedback.KafkaFeedbackSource`).

    ``make_source``: factory for a FRESH source per incarnation (the
    restart re-seeks it to the checkpointed offsets). Strongly preferred
    with the watchdog: an abandoned incarnation then owns a dead private
    session and can never touch the live stream — the analogue of Kafka's
    consumer-group generation fencing. With a single shared ``source``,
    the fence still blocks a zombie's future accesses, but a poll that
    was in flight at abandonment and later returns has already consumed
    its rows: that batch is dropped (at-most-one-batch loss in a rare
    double-fault race). At least one of ``source``/``make_source`` is
    required.

    The sink must tolerate replayed batches (idempotent append by tx_id or
    latest-wins MERGE downstream, as in the reference's MERGE INTO).

    ``resume=False`` ignores any pre-existing checkpoint for the whole run
    (a fresh pass over the stream): the checkpointer is fenced so crash
    incarnations restore only checkpoints written by THIS run — a stale
    checkpoint from a previous run is never silently resumed, even if the
    first incarnation crashes before its first save. ``recover_on`` lists
    the exception types treated as recoverable; anything else propagates
    immediately (engine bugs should crash loudly, not restart-loop).
    """
    if source is None and make_source is None:
        raise ValueError("run_with_recovery needs a source or make_source")
    restarts = 0
    if source is None:
        source = make_source()
    initial_offsets = list(source.offsets)
    if not resume:
        checkpointer = _FencedCheckpointer(checkpointer)
    if heartbeat is None and stall_timeout_s > 0:
        heartbeat = Heartbeat(timeout_s=stall_timeout_s)
    last_was_stall = False
    t_session = time.monotonic()
    while True:
        engine = make_engine()
        if restarts > 0 and make_source is not None:
            # Fresh source session per incarnation: the previous (possibly
            # zombie) session is cut loose. Closed best-effort only after a
            # CRASH — after a stall the zombie thread may still be blocked
            # inside it and close() could hang the supervisor too.
            close = getattr(source, "close", None)
            if close is not None and not last_was_stall:
                try:
                    close()
                except Exception:  # a dying session may not close cleanly
                    pass
            source = make_source()
        restored = None
        if resume or restarts > 0:
            # With resume=False the fence makes this a no-op until the
            # current run has saved at least once.
            restored = checkpointer.restore(engine.state)
        if restored is not None:
            source.seek(engine.state.offsets)
            log.info("restored checkpoint at batch %d",
                     engine.state.batches_done)
        else:
            # No checkpoint yet: a fresh engine must consume from the very
            # beginning, or batches polled before the crash would be lost
            # to the new (empty) feature state.
            source.seek(initial_offsets)
        # Sink-side restore fence: drop indexed output parts beyond the
        # restored batch counter (0 on a fresh start) — replay may
        # re-batch the backlog differently, leaving stale parts it never
        # overwrites (the sink analogue of the checkpoint fence above).
        truncate = getattr(sink, "truncate_after", None) if sink else None
        if truncate is not None:
            truncate(engine.state.batches_done)
        # Feedback loop binds THIS incarnation's engine (and, in
        # production, its own consumer session).
        feedback = make_feedback(engine) if make_feedback else None
        # A FRESH reloader per incarnation: the restored checkpoint holds
        # pre-swap weights, so the new incarnation must re-apply the
        # latest artifact on its first interval instead of trusting a
        # previous incarnation's signature — and an abandoned (zombie)
        # worker keeps only ITS closure, never mutating the live one's.
        model_reload = make_model_reload() if make_model_reload else None
        try:
            if heartbeat is not None:
                stats = _run_watched(
                    engine, source, sink, checkpointer, max_batches,
                    heartbeat, feedback=feedback, model_reload=model_reload,
                )
            else:
                stats = engine.run(
                    source, sink=sink, checkpointer=checkpointer,
                    max_batches=max_batches, feedback=feedback,
                    model_reload=model_reload,
                )
            # Final checkpoint so a clean exit never replays.
            checkpointer.save(engine.state)
            commit = getattr(source, "commit", None)
            if commit is not None:
                commit()
            if feedback is not None:
                feedback.commit()
                feedback.close()
            stats["restarts"] = restarts
            # Whole-session totals: engine.run reports per-run deltas, but
            # a recovered session's caller wants rows across restarts —
            # the engine's lifetime counters (checkpoint-restored + this
            # incarnation) are exactly that. wall_s/rows_per_s are made
            # consistent with them: session wall clock, not the last
            # incarnation's.
            stats["rows"] = engine.state.rows_done
            stats["batches"] = engine.state.batches_done
            stats["wall_s"] = time.monotonic() - t_session
            stats["rows_per_s"] = (
                stats["rows"] / stats["wall_s"] if stats["wall_s"] > 0
                else 0.0
            )
            return stats
        except recover_on as e:
            restarts += 1
            last_was_stall = isinstance(e, StallError)
            if feedback is not None and not last_was_stall:
                # Close the dead incarnation's feedback session so the
                # group rebalances promptly (a stalled zombie may still
                # be inside it — leak that one rather than hang here).
                try:
                    feedback.close()
                except Exception:
                    pass
            log.warning("engine crashed (%s); restart %d/%d",
                        e, restarts, max_restarts)
            cause = "stall" if last_was_stall else "crash"
            rec = active_recorder()
            if restarts > max_restarts:
                # budget exhausted: the final failure is NOT a restart —
                # counting it would skew the baseline chaos PRs assert on
                if rec is not None:
                    rec.record_event(
                        "gave_up", restarts=restarts - 1, cause=cause,
                        error=f"{type(e).__name__}: {e}"[:200])
                raise
            get_registry().counter(
                "rtfds_engine_restarts_total",
                "supervisor restarts by cause", cause=cause).inc()
            if rec is not None:
                rec.record_event(
                    "restart", restarts=restarts, cause=cause,
                    error=f"{type(e).__name__}: {e}"[:200])
