"""Failure detection, retry policies, fault injection, supervised recovery.

The reference's resilience machinery is compose-level only (SURVEY §5.3):
healthchecks + ``restart:`` policies (``docker-compose.yml:83-87,133``), the
datagen 4×5 s connect retry (``datagen/data_gen.py:72-80``), tolerated model
-download 404s (``fraud_detection.py:73-79``), and Spark checkpoint replay.
It has **no fault injection at all**. This module provides the in-process
equivalents plus the missing injection tools:

- :class:`RetryPolicy` / :func:`with_retries` — exponential-backoff retry,
  the ``psycopg2`` connect-loop analogue;
- :class:`Heartbeat` — stall detection for the micro-batch loop (the
  healthcheck role: no progress for ``timeout_s`` → unhealthy);
- :class:`FlakySource` / :func:`corrupt_messages` /
  :class:`PoisonSource` / :func:`poison_messages` — deterministic fault
  injectors: scripted transient poll failures (source wrapper), scripted
  envelope corruption (message transform), and scripted poison pills
  (rows that deterministically crash ingest on every replay);
- :func:`run_with_recovery` — the ``restart: on-failure`` supervisor: on a
  crash, rebuild the engine state from the last checkpoint, seek the
  source, resume; exactly-once at micro-batch granularity because offsets
  and state are checkpointed atomically together (``io/checkpoint.py``).
  Unlike Spark's replay contract (which only helps when failures are
  transient), the supervisor DIAGNOSES failures: K consecutive crashes at
  the same resume point reclassify the failure from transient to poison,
  the offending micro-batch is bisected down to the minimal failing row
  set against a pre-batch state snapshot, those rows land in a
  dead-letter queue, and the stream continues past them — at-most-K
  restarts per poison batch instead of stream death.
"""

from __future__ import annotations

import random
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple, Type

import numpy as np

from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)

log = get_logger("faults")


def _record_fault(kind: str, count: int = 1, **fields) -> None:
    """Count an injected fault (by kind) and land it in the flight
    record, so a chaos run's telemetry shows exactly which failures were
    scripted vs organic."""
    get_registry().counter(
        "rtfds_faults_injected_total", "injected faults by kind",
        kind=kind).inc(count)
    rec = active_recorder()
    if rec is not None:
        rec.record_event("fault", fault_kind=kind, count=count, **fields)


class TransientError(RuntimeError):
    """An injected or genuinely transient failure — safe to retry."""


class StallError(TransientError):
    """The watchdog found no engine progress within the stall budget."""


class PoisonRowError(TransientError):
    """A batch contained row(s) that fail ingest validation (corrupt
    envelope values that decoded structurally but carry impossible
    content, e.g. a negative amount).

    Subclasses :class:`TransientError` deliberately: at the moment it is
    raised, the supervisor cannot tell a corrupt record from a transient
    infrastructure hiccup — both look like "the batch crashed". The
    crash-loop breaker in :func:`run_with_recovery` resolves exactly that
    ambiguity: a failure that recurs at the same resume point is
    reclassified from transient to poison and quarantined via bisection,
    whatever its exception type.
    """


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay = base * multiplier^attempt (capped).

    ``multiplier`` defaults to 1.0 — the reference's constant-5 s connect
    loop (``datagen/data_gen.py:72-80`` sleeps the same 5 s every try);
    pass > 1.0 for genuine exponential growth. ``jitter`` is the fraction
    of each delay randomized away: the slept time is uniform in
    ``[(1 - jitter) * d, d]``, so ``jitter=1.0`` is classic full jitter —
    the thundering-herd guard for fleet-wide reconnects (a thousand
    workers that all lost the same broker must not all come back on the
    same tick). ``delay()`` stays deterministic; only the slept time
    (:meth:`sleep_s`) jitters.
    """

    max_attempts: int = 4
    base_delay_s: float = 5.0
    multiplier: float = 1.0  # reference uses constant 5 s sleeps
    max_delay_s: float = 60.0
    jitter: float = 0.0  # 0 = deterministic; 1.0 = full jitter

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be a fraction in [0, 1]")

    def delay(self, attempt: int) -> float:
        return min(self.base_delay_s * self.multiplier**attempt,
                   self.max_delay_s)

    def sleep_s(self, attempt: int,
                rand: Callable[[], float] = random.random) -> float:
        """The (possibly jittered) time to actually sleep for ``attempt``."""
        d = self.delay(attempt)
        if self.jitter <= 0.0:
            return d
        return d * (1.0 - self.jitter * rand())


def with_retries(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    retry_on: Tuple[Type[BaseException], ...] = (TransientError,),
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with up to ``max_attempts`` tries (the datagen connect
    loop, ``data_gen.py:72-80``). Non-listed exceptions propagate at once.
    Each retried attempt lands in ``rtfds_retry_attempts_total{outcome=
    retried}``; a run that exhausts the budget lands one
    ``outcome=exhausted`` sample before re-raising."""
    reg = get_registry()
    m_retried = reg.counter(
        "rtfds_retry_attempts_total", "with_retries attempts by outcome",
        outcome="retried")
    m_exhausted = reg.counter(
        "rtfds_retry_attempts_total", "with_retries attempts by outcome",
        outcome="exhausted")
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if attempt + 1 < policy.max_attempts:
                d = policy.sleep_s(attempt)
                m_retried.inc()
                log.warning("attempt %d/%d failed (%s); retrying in %.1fs",
                            attempt + 1, policy.max_attempts, e, d)
                sleep(d)
    m_exhausted.inc()
    raise last  # type: ignore[misc]


class Heartbeat:
    """Progress-based failure detector (the compose healthcheck role).

    ``beat()`` on every engine loop pass (:func:`run_with_recovery` wires
    the heartbeat into ``engine.run``); ``healthy()`` is False once
    ``timeout_s`` passes with no beat. :func:`run_with_recovery` watches it
    from a supervisor thread and escalates a stall into the restart path
    (:class:`StallError`) — a silently hung source or device step is
    recovered from like a crash, not waited on forever.
    """

    def __init__(self, timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._last = clock()
        self.beats = 0

    def beat(self) -> None:
        self._last = self._clock()
        self.beats += 1

    def healthy(self) -> bool:
        return (self._clock() - self._last) <= self.timeout_s

    def seconds_since_beat(self) -> float:
        return self._clock() - self._last


class HangingSource:
    """Wraps a source; scripted poll indices HANG (block silently) instead
    of raising — the failure mode retries can't see and only a watchdog
    catches (a dead TPU tunnel, a wedged Kafka client, a stuck NFS read).

    Each scripted hang fires once: the incarnation that hit it stays
    blocked (until ``release`` or ``max_hang_s``), and the restarted
    incarnation's polls proceed — modeling a connection that is re-opened
    by the restart while the old one stays wedged.
    """

    def __init__(self, inner, hang_at: Sequence[int] = (),
                 max_hang_s: float = 60.0):
        import threading

        self.inner = inner
        self.hang_at = set(int(i) for i in hang_at)
        self.max_hang_s = max_hang_s
        self.release = threading.Event()
        self._polls = 0

    def poll_batch(self):
        i = self._polls
        self._polls += 1
        if i in self.hang_at:
            self.hang_at.discard(i)
            _record_fault("hang", poll=i)
            self.release.wait(timeout=self.max_hang_s)  # silent stall
        return self.inner.poll_batch()

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)


class FlakySource:
    """Wraps a source; raises TransientError on scripted poll indices.

    ``fail_at`` lists 0-based poll indices that raise *instead of* returning
    the batch; the underlying source is only advanced on success, so a
    retried poll returns the batch the failure swallowed — exactly like a
    Kafka consumer that died before committing.
    """

    def __init__(self, inner, fail_at: Sequence[int] = ()):
        self.inner = inner
        self.fail_at = set(int(i) for i in fail_at)
        self._polls = 0

    def poll_batch(self):
        i = self._polls
        self._polls += 1
        if i in self.fail_at:
            _record_fault("flaky_poll", poll=i)
            raise TransientError(f"injected poll failure #{i}")
        return self.inner.poll_batch()

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)


class PoisonSource:
    """Wraps a source; scripted ``tx_id`` rows are served CORRUPTED
    (negated amount) on EVERY poll that contains them.

    The deterministic poison-pill injector: unlike
    :func:`corrupt_messages` (whose truncated envelopes the decoder
    masks), a poisoned row decodes structurally fine and then fails the
    engine's ingest validation (:class:`PoisonRowError`) — so a
    checkpoint replay re-polls the same rows, re-corrupts them, and
    crashes again, exactly the crash loop the supervisor's breaker +
    bisection + dead-letter path exists to survive. Works on any
    columnar ``poll_batch`` source.
    """

    def __init__(self, inner, poison_tx_ids: Sequence[int] = ()):
        self.inner = inner
        self.poison_tx_ids = frozenset(int(i) for i in poison_tx_ids)
        self._ids = np.fromiter(sorted(self.poison_tx_ids), dtype=np.int64,
                                count=len(self.poison_tx_ids))

    def poll_batch(self):
        cols = self.inner.poll_batch()
        if cols is None or not len(self.poison_tx_ids):
            return cols
        tx = cols.get("tx_id")
        if tx is None or len(tx) == 0:
            return cols
        mask = np.isin(np.asarray(tx), self._ids)
        if mask.any():
            cols = dict(cols)
            amt = np.array(cols["tx_amount_cents"], copy=True)
            amt[mask] = -np.abs(amt[mask]) - 1
            cols["tx_amount_cents"] = amt
            _record_fault("poison", count=int(mask.sum()))
        return cols

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)

    def commit(self) -> None:
        commit = getattr(self.inner, "commit", None)
        if commit is not None:
            commit()

    def close(self) -> None:
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()


class FlakyStore:
    """Wraps an :mod:`..io.store` object; scripted PUT/GET op indices
    raise ``ConnectionError`` instead of touching the store.

    The durable-state twin of :class:`FlakySource`: a checkpoint save or
    restore that hits a scripted failure looks exactly like a flaky
    S3/MinIO endpoint (same exception family the hardened
    ``StoreCheckpointer`` retries on), and the underlying store is only
    touched on success — so a retried op performs the work the failure
    swallowed, never half of it. ``fail_puts``/``fail_gets`` are 0-based
    per-verb op indices.
    """

    def __init__(self, inner, fail_puts: Sequence[int] = (),
                 fail_gets: Sequence[int] = ()):
        self.inner = inner
        self.fail_puts = set(int(i) for i in fail_puts)
        self.fail_gets = set(int(i) for i in fail_gets)
        self._puts = 0
        self._gets = 0

    def put(self, key: str, data: bytes) -> None:
        i = self._puts
        self._puts += 1
        if i in self.fail_puts:
            _record_fault("flaky_store_put", op=i, key=key)
            raise ConnectionError(f"injected store PUT failure #{i}")
        self.inner.put(key, data)

    def get(self, key: str) -> bytes:
        i = self._gets
        self._gets += 1
        if i in self.fail_gets:
            _record_fault("flaky_store_get", op=i, key=key)
            raise ConnectionError(f"injected store GET failure #{i}")
        return self.inner.get(key)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class TornStore:
    """Wraps a store; the scripted PUT lands TRUNCATED — and succeeds.

    The torn-write injector: unlike :class:`FlakyStore` (whose failures
    the caller can see and retry), a torn PUT reports success while
    storing only the first ``keep_bytes`` of the payload — the
    silent-partial-write failure mode only restore-time verification
    (checkpoint format v2 manifests) can catch. ``tear_at`` is the
    0-based PUT op index to tear; every other op passes through.
    """

    def __init__(self, inner, tear_at: int = 0, keep_bytes: int = 64):
        self.inner = inner
        self.tear_at = int(tear_at)
        self.keep_bytes = int(keep_bytes)
        self._puts = 0

    def put(self, key: str, data: bytes) -> None:
        i = self._puts
        self._puts += 1
        if i == self.tear_at:
            _record_fault("torn_store_put", op=i, key=key,
                          kept=min(self.keep_bytes, len(data)),
                          dropped=max(0, len(data) - self.keep_bytes))
            self.inner.put(key, data[: self.keep_bytes])
            return  # reports success: the tear is silent by design
        self.inner.put(key, data)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def poison_messages(msgs: Sequence[bytes],
                    poison_at: Sequence[int] = ()) -> list:
    """Envelope-level poison injection: re-encode scripted messages with
    a negated amount.

    The corrupted-producer analogue of :func:`corrupt_messages`, one
    notch nastier: the envelope still parses (the decoder can NOT mask
    it), so the impossible value reaches the engine's ingest validation
    and crashes the batch deterministically on every replay. Produce the
    result into a broker/topic to exercise the full poison path."""
    from real_time_fraud_detection_system_tpu.core.envelope import (
        decode_transaction_envelopes_fast,
        encode_transaction_envelopes,
    )

    idxs = sorted(set(int(i) for i in poison_at) if poison_at else ())
    idxs = [i for i in idxs if 0 <= i < len(msgs)]
    out = list(msgs)
    if not idxs:
        return out
    cols, invalid = decode_transaction_envelopes_fast(
        [msgs[i] for i in idxs])
    poisoned = encode_transaction_envelopes(
        cols["tx_id"], cols["tx_datetime_us"], cols["customer_id"],
        cols["terminal_id"], -np.abs(cols["tx_amount_cents"]) - 1,
    )
    n = 0
    for j, i in enumerate(idxs):
        if invalid[j]:
            continue  # already-corrupt envelope: leave it to the decoder
        out[i] = poisoned[j]
        n += 1
    if n:
        _record_fault("poison_envelope", count=n)
    return out


def corrupt_messages(msgs: Sequence[bytes],
                     corrupt_every: int = 17) -> list:
    """Envelope-level fault injection: truncate every k-th message.

    Corrupt envelopes must be masked by the decoder, never crash the batch
    (the golden-decode robustness property, SURVEY §4). Produce the result
    into a broker/topic to exercise the full envelope path."""
    k = max(int(corrupt_every), 1)
    out = [
        m[: max(len(m) // 2, 1)] if i % k == k - 1 else m
        for i, m in enumerate(msgs)
    ]
    n_corrupt = len(msgs) // k
    if n_corrupt:
        _record_fault("corrupt_envelope", count=n_corrupt)
    return out


class _FencedCheckpointer:
    """Restores only checkpoints saved through THIS wrapper.

    Used by :func:`run_with_recovery` when ``resume=False``: a stale
    checkpoint left by a previous run must never be restored by a crash
    incarnation of a run that explicitly asked for a fresh start. The
    pre-existing checkpoint files are recorded at construction and left
    untouched until this run's FIRST save — if the fresh run dies before
    ever saving, the previous run's checkpoints remain resumable. The
    first save supersedes the old lineage: the stale files are renamed
    aside (``stale-<token>-ckpt-…``, bytes preserved, unique token so
    repeated fresh runs never clobber each other's stash) so they are
    invisible to ``latest()`` AND to the retention GC — otherwise `keep`
    stale higher-numbered files would garbage-collect this run's first
    saves the moment they land.
    """

    def __init__(self, inner):
        self.inner = inner
        self._saved: list = []
        # Lineage API (Checkpointer AND StoreCheckpointer provide it):
        # record the pre-existing checkpoints to quarantine on first save.
        self._stale: list = list(inner.list_checkpoints())

    def save(self, engine_state):
        if self._stale:
            self.inner.quarantine(self._stale, uuid.uuid4().hex[:8])
            self._stale = []
        path = self.inner.save(engine_state)
        self._saved.append(path)
        return path

    def restore(self, engine_state, path=None):
        if path is None:
            # inner.exists filters saves the inner's own GC removed —
            # storage-agnostic (os.path.exists would wrongly drop every
            # object-store key).
            mine = [p for p in self._saved if self.inner.exists(p)]
            if not mine:
                return None
            path = max(mine)
        return self.inner.restore(engine_state, path=path)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _AbandonFence:
    """Shared flag: flipped when the watchdog abandons an incarnation."""

    def __init__(self):
        self.abandoned = False

    def check(self) -> None:
        if self.abandoned:
            raise StallError("incarnation abandoned by the watchdog")


class _FenceGuard:
    """Proxy that cuts a zombie incarnation off from shared objects.

    Every attribute access (method call, ``offsets`` property, heartbeat
    ``beat``) first checks the fence: once the watchdog abandons the
    incarnation, the zombie's next interaction with the source, sink,
    checkpointer, or heartbeat raises :class:`StallError` inside the
    zombie thread — it cannot steal batches from the restarted
    incarnation, overwrite the live checkpoint with stale state, append
    stale results, or mask real stalls by beating the shared heartbeat.
    (Whole checkpoints are atomic snapshots, so a save that *completes*
    just before abandonment is still consistent.)
    """

    def __init__(self, inner, fence: _AbandonFence):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_fence", fence)

    def __getattr__(self, name):
        fence = object.__getattribute__(self, "_fence")
        inner = object.__getattribute__(self, "_inner")
        attr = getattr(inner, name)
        if callable(attr):
            def _guarded(*a, **k):
                fence.check()
                return attr(*a, **k)

            return _guarded
        fence.check()
        return attr

    def __setattr__(self, name, value):
        fence = object.__getattribute__(self, "_fence")
        fence.check()
        setattr(object.__getattribute__(self, "_inner"), name, value)


class _GuardedSource(_FenceGuard):
    """Source fence with a post-poll check.

    Beyond the inherited pre-access checks, a poll that was already in
    flight when the watchdog abandoned this incarnation needs one more
    check AFTER it returns: when the hang finally releases, the zombie's
    poll may have consumed rows from a source SHARED with the restarted
    incarnation. The post-check drops that batch and kills the zombie —
    at-most-one-batch loss in that double-fault race, never a mis-seek.
    The clean fix is not sharing the cursor at all: pass ``make_source``
    to :func:`run_with_recovery` so each incarnation owns a fresh source
    session (what a real Kafka deployment gets from consumer-group
    generation fencing: a zombie consumer's partitions are revoked, its
    late poll cannot commit).
    """

    def poll_batch(self):
        fence = object.__getattribute__(self, "_fence")
        inner = object.__getattribute__(self, "_inner")
        fence.check()
        cols = inner.poll_batch()
        fence.check()  # in-flight poll that outlived abandonment: drop
        return cols


def _fence_model_reload(model_reload, fence: "_AbandonFence"):
    """Fence a reload poll AND keep the shared signature baseline
    honest: a poll whose incarnation was abandoned DURING the call (a
    store GET stalled long enough for the watchdog to give up) may have
    committed the file's new signature to the cross-incarnation
    baseline (``poll.sig_state``, ``--learn-registry`` mode) while its
    swap can never land — every fenced apply path is closed to a
    zombie. Restore the pre-call signature so the LIVE incarnation's
    next poll still sees the change. If the live one updated the
    baseline meanwhile this rolls it back one step and it redundantly
    re-applies the same artifact next poll — the safe direction;
    silently losing the update is not."""
    sig = getattr(model_reload, "sig_state", None)

    def _fenced_reload():
        fence.check()
        before = sig.get("sig") if sig is not None else None
        out = model_reload()
        try:
            fence.check()
        except StallError:
            if sig is not None:
                sig["sig"] = before
            raise
        return out

    return _fenced_reload


def _run_watched(engine, source, sink, checkpointer, max_batches,
                 heartbeat: Heartbeat, feedback=None, model_reload=None,
                 learning=None, target=None):
    """Run one engine incarnation under a stall watchdog.

    The engine loop runs in a worker thread beating the heartbeat each
    pass; this (supervisor) thread polls ``healthy()``. On a stall the
    worker is ABANDONED — a thread blocked in a hung syscall/device call
    cannot be killed — and :class:`StallError` escalates into the restart
    path. The abandoned worker is fenced (:class:`_FenceGuard`): when its
    hang eventually releases, its first touch of the shared source, sink,
    checkpointer, or heartbeat raises and the zombie dies, instead of
    corrupting the restarted incarnation's stream.

    ``target`` replaces the default ``engine.run`` body with another
    supervised workload run over the SAME guarded objects — it is called
    as ``target(g_source, g_sink, g_checkpointer, g_heartbeat)``. Poison
    isolation runs through this, so a batch that HANGS (instead of
    crashing) mid-diagnosis is still bounded by the stall budget.
    """
    import threading

    box: dict = {}
    fence = _AbandonFence()
    g_source = _GuardedSource(source, fence)
    g_sink = _FenceGuard(sink, fence) if sink is not None else None
    g_ckpt = _FenceGuard(checkpointer, fence) if checkpointer is not None \
        else None
    g_heartbeat = _FenceGuard(heartbeat, fence)
    g_feedback = _FenceGuard(feedback, fence) if feedback is not None \
        else None
    # The learning loop outlives incarnations (its learner thread keeps
    # the replay window warm across restarts) — fence THIS incarnation's
    # handle so a zombie's promotion decision can never swap params on
    # the live incarnation's engine.
    g_learning = _FenceGuard(learning, fence) if learning is not None \
        else None
    g_model_reload = (_fence_model_reload(model_reload, fence)
                      if model_reload is not None else None)
    if getattr(engine, "feature_cache", None) is not None:
        # The cache outlives incarnations (it's how the feedback join
        # finds rows scored before a restart) — fence THIS incarnation's
        # handle so a zombie can't overwrite rows the live incarnation
        # re-scored (or reset their labeled marks, double-applying
        # additive label scatters).
        engine.feature_cache = _FenceGuard(engine.feature_cache, fence)
    if learning is not None:
        # The shadow score cache and learner queue outlive incarnations
        # just like the feature cache — attach now (idempotent: the
        # engine.run attach becomes a no-op for this engine) and fence
        # the handles the attach installed, so a zombie that wakes
        # mid-_finish can't write stale champion/candidate scores into
        # the shared shadow cache or stale rows into the learner queue.
        learning.attach(engine)
        if engine.shadow is not None:
            engine.shadow = _FenceGuard(engine.shadow, fence)
        if engine.feedback_tap is not None:
            _tap = engine.feedback_tap

            def _fenced_tap(*a, **k):
                fence.check()
                return _tap(*a, **k)

            engine.feedback_tap = _fenced_tap

    def _target():
        try:
            if target is not None:
                box["stats"] = target(g_source, g_sink, g_ckpt,
                                      g_heartbeat)
            else:
                box["stats"] = engine.run(
                    g_source, sink=g_sink, checkpointer=g_ckpt,
                    max_batches=max_batches, heartbeat=g_heartbeat,
                    feedback=g_feedback, model_reload=g_model_reload,
                    learning=g_learning,
                )
        # rtfdslint: disable=broad-exception-catch (thread-boundary transport: the ORIGINAL exception object crosses to the supervisor thread, which applies the typed recover_on policy — narrowing here would strip the taxonomy, not preserve it)
        except BaseException as e:  # report into the supervisor thread
            box["err"] = e

    heartbeat.beat()  # incarnation start = progress
    worker = threading.Thread(target=_target, daemon=True,
                              name="engine-incarnation")
    worker.start()
    poll = min(max(heartbeat.timeout_s / 4.0, 0.01), 1.0)
    while worker.is_alive():
        worker.join(poll)
        if worker.is_alive() and not heartbeat.healthy():
            fence.abandoned = True
            raise StallError(
                f"no engine progress for "
                f"{heartbeat.seconds_since_beat():.1f}s (stall budget "
                f"{heartbeat.timeout_s:.1f}s); abandoning hung incarnation"
            )
    if "err" in box:
        raise box["err"]
    return box["stats"]


def _subset_cols(cols: dict, idx) -> dict:
    return {k: np.asarray(v)[idx] for k, v in cols.items()}


def _bisect_poison_rows(engine, snapshot: bytes, cols: dict,
                        recover_on,
                        heartbeat=None) -> Tuple[np.ndarray, dict]:
    """Minimal failing row set of a poison batch, by recursive halving.

    Every probe first restores the engine's full state from the
    pre-batch ``snapshot`` (``io/checkpoint.state_to_bytes`` payload), so
    probing never corrupts feature state, counters, or offsets — the
    probes are pure questions. A subset that fails only in combination
    (both halves pass alone, the union crashes) is quarantined whole
    rather than looping forever. Returns ``(bad_row_indices,
    {row_index: exception})``; the engine is left restored to the
    pre-batch snapshot. Probe count is O(k log n) for k poison rows.
    """
    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        bytes_to_state,
    )

    n = len(next(iter(cols.values())))
    bad: list = []
    errors: dict = {}

    def probe(idx) -> Optional[BaseException]:
        if heartbeat is not None:
            # each probe is real progress — keep the watchdog satisfied
            # through a long bisection
            heartbeat.beat()
        bytes_to_state(snapshot, engine.state)
        try:
            engine.process_batch(_subset_cols(cols, idx))
            return None
        except recover_on as e:
            return e

    def rec(idx) -> None:
        e = probe(idx)
        if e is None:
            return
        if len(idx) == 1:
            i = int(idx[0])
            bad.append(i)
            errors[i] = e
            return
        before = len(bad)
        mid = len(idx) // 2
        rec(idx[:mid])
        rec(idx[mid:])
        if len(bad) == before:
            # interaction-dependent failure: halves pass alone, the
            # union crashes — quarantine the whole subset (conservative,
            # terminates)
            for i in idx:
                bad.append(int(i))
                errors[int(i)] = e

    rec(np.arange(n))
    bytes_to_state(snapshot, engine.state)  # leave pre-batch state
    return np.asarray(sorted(set(bad)), dtype=np.int64), errors


def _run_poison_isolation(engine, source, sink, checkpointer, dead_letter,
                          max_batches: int, recover_on,
                          heartbeat: Optional[Heartbeat] = None) -> int:
    """One careful incarnation: step batch-by-batch until the crash-
    looping micro-batch is found, bisect it, quarantine the minimal
    failing row set to the dead-letter queue, score + sink the
    survivors, and checkpoint PAST the poison batch.

    Runs unpipelined with a pre-batch state snapshot per step (the cost
    that makes this a diagnosis mode, not the serving loop); control
    returns to the normal supervisor loop after the first quarantine, a
    clean-batch budget (the crash can only live within one checkpoint
    cadence of the resume point — beyond that the classification was a
    same-point transient after all), stream end, or ``max_batches``.
    Failures that are NOT row-shaped (the poll itself raising) propagate
    to the supervisor and count as ordinary crashes. Returns the number
    of rows quarantined (0 when the suspect batch replayed clean).
    """
    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        bytes_to_state,
        state_to_bytes,
    )
    from real_time_fraud_detection_system_tpu.runtime.engine import (
        empty_batch_result,
    )

    every = int(getattr(engine.cfg.runtime, "checkpoint_every_batches", 50)
                or 50)
    clean_budget = 2 * every + 8
    clean = 0
    quarantined = 0
    rec = active_recorder()
    log.warning("poison isolation: stepping batch-by-batch from batch %d",
                engine.state.batches_done)
    while True:
        if heartbeat is not None:
            heartbeat.beat()
        if max_batches and engine.state.batches_done >= max_batches:
            break
        if clean >= clean_budget:
            # a whole checkpoint cadence replayed clean: the crash loop
            # was a same-point transient, not poison — resume fast mode
            log.info("poison isolation: %d clean batches, no crash — "
                     "reclassifying as transient and resuming", clean)
            break
        snapshot = state_to_bytes(engine.state)
        cols = source.poll_batch()  # a poll crash is not row-poison
        if cols is None:
            break
        if len(next(iter(cols.values()), ())) == 0:
            break  # idle live source: hand back to the paced normal loop
        offsets = list(source.offsets)
        try:
            res = engine.process_batch(cols)
        except recover_on as e:
            bad_idx, errors = _bisect_poison_rows(
                engine, snapshot, cols, recover_on, heartbeat=heartbeat)
            batch_index = int(engine.state.batches_done) + 1
            if len(bad_idx) == 0:
                # the batch crashed once but every probe passed (a
                # transient riding the poison window): retry it whole
                raise
            dead_letter.put_rows(
                _subset_cols(cols, bad_idx), reason="crash",
                errors=[f"{type(errors[int(i)]).__name__}: "
                        f"{errors[int(i)]}"[:300] for i in bad_idx],
                batch_index=batch_index, offsets=offsets)
            quarantined += len(bad_idx)
            log.warning(
                "poison isolation: batch %d crashed (%s: %s); "
                "quarantined %d/%d rows to the dead-letter queue",
                batch_index, type(e).__name__, str(e)[:120],
                len(bad_idx), len(next(iter(cols.values()))))
            good = np.ones(len(next(iter(cols.values()))), dtype=bool)
            good[bad_idx] = False
            if good.any():
                # survivors score from the pre-batch snapshot — feature
                # state never sees the quarantined rows
                res = engine.process_batch(_subset_cols(
                    cols, np.flatnonzero(good)))
            else:
                engine.state.batches_done += 1
                res = empty_batch_result(engine.state.batches_done)
            engine.state.offsets = offsets
            if sink is not None:
                sink.append(res)
            break  # checkpoint below advances PAST the poison batch
        engine.state.offsets = offsets
        if sink is not None:
            sink.append(res)
        clean += 1
    drain = getattr(sink, "drain", None) if sink is not None else None
    if drain is not None:
        drain()
    checkpointer.save(engine.checkpoint_state())
    commit = getattr(source, "commit", None)
    if commit is not None:
        commit()
    if rec is not None:
        rec.record_event("poison", phase="isolated", rows=quarantined,
                         batches_done=int(engine.state.batches_done))
    return quarantined


def run_with_recovery(
    make_engine: Callable[[], object],
    source=None,
    checkpointer=None,
    sink=None,
    max_restarts: int = 3,
    max_batches: int = 0,
    heartbeat: Optional[Heartbeat] = None,
    stall_timeout_s: float = 0.0,
    resume: bool = True,
    make_source: Optional[Callable[[], object]] = None,
    make_feedback: Optional[Callable[[object], object]] = None,
    make_model_reload: Optional[Callable[[], object]] = None,
    learning=None,
    recover_on: Tuple[Type[BaseException], ...] = (
        TransientError, OSError, ConnectionError,
    ),
    crash_loop_k: int = 2,
    dead_letter=None,
    restart_backoff: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """Supervisor loop: run → on crash OR stall, restore checkpoint, resume.

    ``make_engine`` builds a fresh engine (state template) per incarnation;
    the checkpointer restores (offsets, feature state, params, scaler) into
    it and the source seeks to the checkpointed offsets, so every committed
    micro-batch is processed exactly once and uncommitted ones are replayed
    — Spark's checkpointLocation recovery contract (SURVEY §5.4).

    Stall watchdog: pass ``stall_timeout_s`` (or a pre-built ``heartbeat``)
    and each incarnation runs in a worker thread beating the heartbeat per
    loop pass while the supervisor watches ``healthy()`` — a silently hung
    source or device step (the failure retries can't see: no exception is
    ever raised) is detected within the stall budget and recovered like a
    crash. Without either, the loop is synchronous and reacts to
    exceptions only.

    ``make_feedback``: factory called with each incarnation's engine to
    build its labeled-feedback loop (a fresh consumer session per
    incarnation in production — see :class:`~.feedback.KafkaFeedbackSource`).

    ``make_source``: factory for a FRESH source per incarnation (the
    restart re-seeks it to the checkpointed offsets). Strongly preferred
    with the watchdog: an abandoned incarnation then owns a dead private
    session and can never touch the live stream — the analogue of Kafka's
    consumer-group generation fencing. With a single shared ``source``,
    the fence still blocks a zombie's future accesses, but a poll that
    was in flight at abandonment and later returns has already consumed
    its rows: that batch is dropped (at-most-one-batch loss in a rare
    double-fault race). At least one of ``source``/``make_source`` is
    required.

    The sink must tolerate replayed batches (idempotent append by tx_id or
    latest-wins MERGE downstream, as in the reference's MERGE INTO).

    ``resume=False`` ignores any pre-existing checkpoint for the whole run
    (a fresh pass over the stream): the checkpointer is fenced so crash
    incarnations restore only checkpoints written by THIS run — a stale
    checkpoint from a previous run is never silently resumed, even if the
    first incarnation crashes before its first save. ``recover_on`` lists
    the exception types treated as recoverable; anything else propagates
    immediately (engine bugs should crash loudly, not restart-loop).

    **Crash-loop breaker**: ``crash_loop_k`` consecutive same-typed
    crash failures at the SAME progress point (the engine's batch
    counter + offsets at failure time, so progress a dying incarnation
    made before crashing resets the streak) reclassify the failure from
    transient to poison
    (``rtfds_crash_loops_total``, flight-record ``poison`` event) instead
    of burning the restart budget on a deterministic replay. With a
    ``dead_letter`` sink (:class:`~..io.sink.DeadLetterSink`) the next
    incarnation runs :func:`_run_poison_isolation`: the offending
    micro-batch is replayed through the engine in halves against a
    pre-batch state snapshot down to the minimal failing row set, those
    rows are quarantined (idempotent by tx_id, so a crash mid-bisection
    neither loses nor duplicates them), survivors are scored and sunk
    normally, and the stream continues with offsets advanced — at-most-K
    restarts per poison batch, never stream death (the restart budget is
    refunded on successful isolation). Without a dead-letter sink the
    breaker logs the diagnosis + fires ``rtfds_crash_loops_total`` once
    per loop but keeps the budgeted, backed-off retry — a same-point
    transient (e.g. a broker outage) must not die earlier than it would
    have before the breaker existed, and a true poison loop is still
    bounded by ``max_restarts`` exactly as before.
    Stall-caused restarts never count toward the crash streak.

    **Restart backoff**: ``restart_backoff`` (a :class:`RetryPolicy`)
    sleeps between crash-caused restarts — exponential with optional
    full jitter, metered as ``rtfds_restart_backoff_seconds_total``.
    Stall-caused restarts skip it (they already waited out the stall
    budget). ``None`` (default) keeps the legacy hot restart loop.
    """
    if source is None and make_source is None:
        raise ValueError("run_with_recovery needs a source or make_source")
    restarts = 0
    budget_used = 0  # like restarts, but refunded on poison isolation
    fail_key: Optional[tuple] = None  # resume point of the last crash
    fail_count = 0  # consecutive crashes at fail_key
    poison_pending = False
    if source is None:
        source = make_source()
    initial_offsets = list(source.offsets)
    if not resume:
        checkpointer = _FencedCheckpointer(checkpointer)
    if heartbeat is None and stall_timeout_s > 0:
        heartbeat = Heartbeat(timeout_s=stall_timeout_s)
    last_was_stall = False
    t_session = time.monotonic()
    while True:
        engine = make_engine()
        if restarts > 0 and make_source is not None:
            # Fresh source session per incarnation: the previous (possibly
            # zombie) session is cut loose. Closed best-effort only after a
            # CRASH — after a stall the zombie thread may still be blocked
            # inside it and close() could hang the supervisor too.
            close = getattr(source, "close", None)
            if close is not None and not last_was_stall:
                try:
                    close()
                # rtfdslint: disable=exception-swallow (best-effort close of a DEAD incarnation's source; the real crash is already being handled by the supervisor — a close error here must not mask it)
                except Exception:  # a dying session may not close cleanly
                    pass
            source = make_source()
        restored = None
        if resume or restarts > 0:
            # With resume=False the fence makes this a no-op until the
            # current run has saved at least once.
            restored = checkpointer.restore(engine.state)
        if restored is not None:
            source.seek(engine.state.offsets)
            log.info("restored checkpoint at batch %d",
                     engine.state.batches_done)
        else:
            # No checkpoint yet: a fresh engine must consume from the very
            # beginning, or batches polled before the crash would be lost
            # to the new (empty) feature state.
            source.seek(initial_offsets)
        # Sink-side restore fence: drop indexed output parts beyond the
        # restored batch counter (0 on a fresh start) — replay may
        # re-batch the backlog differently, leaving stale parts it never
        # overwrites (the sink analogue of the checkpoint fence above).
        truncate = getattr(sink, "truncate_after", None) if sink else None
        if truncate is not None:
            truncate(engine.state.batches_done)
        # Feedback loop binds THIS incarnation's engine (and, in
        # production, its own consumer session). Isolation incarnations
        # run without feedback/reload — they exist to diagnose one batch.
        feedback = (make_feedback(engine)
                    if make_feedback and not poison_pending else None)
        # A FRESH reloader per incarnation: the restored checkpoint holds
        # pre-swap weights, so the new incarnation must re-apply the
        # latest artifact on its first interval instead of trusting a
        # previous incarnation's signature — and an abandoned (zombie)
        # worker keeps only ITS closure, never mutating the live one's.
        model_reload = (make_model_reload()
                        if make_model_reload and not poison_pending
                        else None)
        # Isolation must run UNPREFETCHED: a PrefetchSource's producer
        # thread polling ahead during bisection would decouple the
        # polled position from the batch under diagnosis. set_sync(True)
        # stops the producer and rewinds the inner source to the
        # consumed position, so isolation sees the same batch boundaries
        # a checkpoint replay would; flipped back after isolation.
        set_sync = getattr(source, "set_sync", None)
        if poison_pending and set_sync is not None:
            set_sync(True)
        try:
            if poison_pending:
                # No training overlaps a bisection in progress: the
                # learner's device work would race the unpipelined
                # probe steps' timing diagnosis.
                if learning is not None:
                    learning.pause()
                if heartbeat is not None:
                    # Isolation under the same stall watchdog + zombie
                    # fencing as a normal incarnation: a batch that HANGS
                    # mid-diagnosis is bounded by the stall budget too.
                    _run_watched(
                        engine, source, sink, checkpointer, max_batches,
                        heartbeat,
                        target=lambda src, snk, ckpt, hb:
                        _run_poison_isolation(
                            engine, src, snk, ckpt, dead_letter,
                            max_batches, recover_on, heartbeat=hb),
                    )
                else:
                    _run_poison_isolation(
                        engine, source, sink, checkpointer, dead_letter,
                        max_batches, recover_on,
                    )
                # Progress was made past the suspect point: clear the
                # diagnosis and REFUND the restart budget the crash loop
                # consumed — a poison batch must never kill the stream.
                poison_pending = False
                fail_key, fail_count = None, 0
                budget_used = 0
                if set_sync is not None:
                    set_sync(False)  # fast (prefetched) mode resumes
                if learning is not None:
                    learning.resume()
                continue
            if heartbeat is not None:
                stats = _run_watched(
                    engine, source, sink, checkpointer, max_batches,
                    heartbeat, feedback=feedback, model_reload=model_reload,
                    learning=learning,
                )
            else:
                stats = engine.run(
                    source, sink=sink, checkpointer=checkpointer,
                    max_batches=max_batches, feedback=feedback,
                    model_reload=model_reload, learning=learning,
                )
            # Final checkpoint so a clean exit never replays. The
            # checkpoint VIEW (not raw state): with a terminal-sketch
            # exchange armed it strips adopted peer content so resize
            # merges sum disjoint per-process partials exactly.
            checkpointer.save(engine.checkpoint_state())
            commit = getattr(source, "commit", None)
            if commit is not None:
                commit()
            if feedback is not None:
                feedback.commit()
                feedback.close()
            stats["restarts"] = restarts
            # Whole-session totals: engine.run reports per-run deltas, but
            # a recovered session's caller wants rows across restarts —
            # the engine's lifetime counters (checkpoint-restored + this
            # incarnation) are exactly that. wall_s/rows_per_s are made
            # consistent with them: session wall clock, not the last
            # incarnation's.
            stats["rows"] = engine.state.rows_done
            stats["batches"] = engine.state.batches_done
            stats["wall_s"] = time.monotonic() - t_session
            stats["rows_per_s"] = (
                stats["rows"] / stats["wall_s"] if stats["wall_s"] > 0
                else 0.0
            )
            return stats
        except recover_on as e:
            restarts += 1
            budget_used += 1
            last_was_stall = isinstance(e, StallError)
            if feedback is not None and not last_was_stall:
                # Close the dead incarnation's feedback session so the
                # group rebalances promptly (a stalled zombie may still
                # be inside it — leak that one rather than hang here).
                try:
                    feedback.close()
                # rtfdslint: disable=exception-swallow (best-effort close of the dead incarnation's feedback session so the group rebalances; the crash being recovered is the signal, not this close)
                except Exception:
                    pass
            log.warning("engine crashed (%s); restart %d/%d",
                        e, restarts, max_restarts)
            cause = "stall" if last_was_stall else "crash"
            err_s = f"{type(e).__name__}: {e}"[:200]
            rec = active_recorder()
            classified = False
            if not last_was_stall and not poison_pending:
                # Crash-loop breaker: consecutive same-typed crashes at
                # the SAME progress point (the engine's batch counter +
                # offsets AT failure — progress made by the dying
                # incarnation counts, checkpointed or not) are a
                # deterministic replay, not bad luck.
                fail_sig = (
                    int(getattr(engine.state, "batches_done", -1)),
                    tuple(int(x) for x in
                          getattr(engine.state, "offsets", ()) or ()),
                    type(e).__name__,
                )
                if fail_sig == fail_key:
                    fail_count += 1
                else:
                    fail_key, fail_count = fail_sig, 1
                if fail_count == max(1, int(crash_loop_k)):
                    # first crossing of K: the failure is now diagnosed
                    # as poison (the metric/event fire ONCE per loop)
                    get_registry().counter(
                        "rtfds_crash_loops_total",
                        "crash loops reclassified from transient to "
                        "poison (K consecutive failures at one progress "
                        "point)").inc()
                    if rec is not None:
                        rec.record_event(
                            "poison", phase="detected",
                            resume_batch=fail_key[0],
                            failures=fail_count, error=err_s)
                    if dead_letter is None:
                        # No quarantine path configured: log the
                        # diagnosis but keep the budgeted (backed-off)
                        # retry — a same-point transient (broker outage)
                        # must not die earlier than it would have before
                        # the breaker existed; the budget bounds a true
                        # poison loop exactly as before.
                        log.error(
                            "crash loop: %d consecutive failures at "
                            "progress point %s — likely poison input; "
                            "configure a dead-letter sink "
                            "(--dead-letter) to quarantine it instead "
                            "of retrying into the restart budget",
                            fail_count, fail_key)
                    else:
                        classified = True
            if classified:
                # The classification restart rides the normal restart
                # telemetry but skips the budget check: poison handling
                # is bounded by construction (isolation either advances
                # past the batch or its own failures land back here with
                # poison_pending set, where the budget DOES apply).
                poison_pending = True
                fail_key, fail_count = None, 0
            elif budget_used > max_restarts:
                # budget exhausted: the final failure is NOT a restart —
                # counting it would skew the baseline chaos PRs assert on
                if rec is not None:
                    rec.record_event(
                        "gave_up", restarts=restarts - 1, cause=cause,
                        error=err_s)
                raise
            get_registry().counter(
                "rtfds_engine_restarts_total",
                "supervisor restarts by cause", cause=cause).inc()
            if rec is not None:
                rec.record_event(
                    "restart", restarts=restarts, cause=cause, error=err_s)
            if restart_backoff is not None and not last_was_stall \
                    and not classified:
                # Exponential backoff + jitter between restarts — crash
                # restarts AND failed-isolation retries (a down broker
                # mid-diagnosis must not hot-loop); skipped for stalls
                # (they already waited out the stall budget) and for the
                # classification transition itself (diagnosis should
                # start immediately).
                d = restart_backoff.sleep_s(budget_used - 1)
                if d > 0:
                    get_registry().counter(
                        "rtfds_restart_backoff_seconds_total",
                        "seconds slept backing off between restarts",
                    ).inc(d)
                    log.info("backing off %.2fs before restart %d",
                             d, restarts)
                    sleep(d)
