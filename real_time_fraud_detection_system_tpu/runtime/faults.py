"""Failure detection, retry policies, fault injection, supervised recovery.

The reference's resilience machinery is compose-level only (SURVEY §5.3):
healthchecks + ``restart:`` policies (``docker-compose.yml:83-87,133``), the
datagen 4×5 s connect retry (``datagen/data_gen.py:72-80``), tolerated model
-download 404s (``fraud_detection.py:73-79``), and Spark checkpoint replay.
It has **no fault injection at all**. This module provides the in-process
equivalents plus the missing injection tools:

- :class:`RetryPolicy` / :func:`with_retries` — exponential-backoff retry,
  the ``psycopg2`` connect-loop analogue;
- :class:`Heartbeat` — stall detection for the micro-batch loop (the
  healthcheck role: no progress for ``timeout_s`` → unhealthy);
- :class:`FlakySource` / :func:`corrupt_messages` — deterministic fault
  injectors: scripted transient poll failures (source wrapper) and
  scripted envelope corruption (message transform);
- :func:`run_with_recovery` — the ``restart: on-failure`` supervisor: on a
  crash, rebuild the engine state from the last checkpoint, seek the
  source, resume; exactly-once at micro-batch granularity because offsets
  and state are checkpointed atomically together (``io/checkpoint.py``).
"""

from __future__ import annotations

import os
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple, Type

from real_time_fraud_detection_system_tpu.utils.logging import get_logger

log = get_logger("faults")


class TransientError(RuntimeError):
    """An injected or genuinely transient failure — safe to retry."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay = base * multiplier^attempt (capped)."""

    max_attempts: int = 4
    base_delay_s: float = 5.0
    multiplier: float = 1.0  # reference uses constant 5 s sleeps
    max_delay_s: float = 60.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def delay(self, attempt: int) -> float:
        return min(self.base_delay_s * self.multiplier**attempt,
                   self.max_delay_s)


def with_retries(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    retry_on: Tuple[Type[BaseException], ...] = (TransientError,),
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with up to ``max_attempts`` tries (the datagen connect
    loop, ``data_gen.py:72-80``). Non-listed exceptions propagate at once."""
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last = e
            if attempt + 1 < policy.max_attempts:
                d = policy.delay(attempt)
                log.warning("attempt %d/%d failed (%s); retrying in %.1fs",
                            attempt + 1, policy.max_attempts, e, d)
                sleep(d)
    raise last  # type: ignore[misc]


class Heartbeat:
    """Progress-based failure detector (the compose healthcheck role).

    ``beat()`` on every processed batch (:func:`run_with_recovery` wires
    this automatically when given a heartbeat); ``healthy()`` is False once
    ``timeout_s`` passes with no beat. Checking is the job of an external
    monitor thread — the supervisor loop itself is synchronous and can only
    react to crashes, not silent stalls.
    """

    def __init__(self, timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._last = clock()
        self.beats = 0

    def beat(self) -> None:
        self._last = self._clock()
        self.beats += 1

    def healthy(self) -> bool:
        return (self._clock() - self._last) <= self.timeout_s

    def seconds_since_beat(self) -> float:
        return self._clock() - self._last


class FlakySource:
    """Wraps a source; raises TransientError on scripted poll indices.

    ``fail_at`` lists 0-based poll indices that raise *instead of* returning
    the batch; the underlying source is only advanced on success, so a
    retried poll returns the batch the failure swallowed — exactly like a
    Kafka consumer that died before committing.
    """

    def __init__(self, inner, fail_at: Sequence[int] = ()):
        self.inner = inner
        self.fail_at = set(int(i) for i in fail_at)
        self._polls = 0

    def poll_batch(self):
        i = self._polls
        self._polls += 1
        if i in self.fail_at:
            raise TransientError(f"injected poll failure #{i}")
        return self.inner.poll_batch()

    @property
    def offsets(self):
        return self.inner.offsets

    def seek(self, offsets):
        self.inner.seek(offsets)


def corrupt_messages(msgs: Sequence[bytes],
                     corrupt_every: int = 17) -> list:
    """Envelope-level fault injection: truncate every k-th message.

    Corrupt envelopes must be masked by the decoder, never crash the batch
    (the golden-decode robustness property, SURVEY §4). Produce the result
    into a broker/topic to exercise the full envelope path."""
    k = max(int(corrupt_every), 1)
    return [
        m[: max(len(m) // 2, 1)] if i % k == k - 1 else m
        for i, m in enumerate(msgs)
    ]


class _FencedCheckpointer:
    """Restores only checkpoints saved through THIS wrapper.

    Used by :func:`run_with_recovery` when ``resume=False``: a stale
    checkpoint left by a previous run must never be restored by a crash
    incarnation of a run that explicitly asked for a fresh start. The
    pre-existing checkpoint files are recorded at construction and left
    untouched until this run's FIRST save — if the fresh run dies before
    ever saving, the previous run's checkpoints remain resumable. The
    first save supersedes the old lineage: the stale files are renamed
    aside (``stale-<token>-ckpt-…``, bytes preserved, unique token so
    repeated fresh runs never clobber each other's stash) so they are
    invisible to ``latest()`` AND to the retention GC — otherwise `keep`
    stale higher-numbered files would garbage-collect this run's first
    saves the moment they land.
    """

    def __init__(self, inner):
        self.inner = inner
        self._saved: list = []
        self._stale: list = []
        directory = getattr(inner, "directory", None)
        if directory and os.path.isdir(directory):
            self._stale = [
                os.path.join(directory, f)
                for f in sorted(os.listdir(directory))
                if f.startswith("ckpt-") and f.endswith(".npz")
            ]

    def _quarantine_stale(self) -> None:
        # Retention: one stash only — clear any previous run's stale-*
        # files first, so repeated resume=False runs on a persistent dir
        # keep at most `keep` quarantined snapshots, not an unbounded pile.
        dirs = {os.path.dirname(p) for p in self._stale}
        for d in dirs:
            for old in os.listdir(d):
                if old.startswith("stale-") and old.endswith(".npz"):
                    os.remove(os.path.join(d, old))
        token = uuid.uuid4().hex[:8]
        for p in self._stale:
            if os.path.exists(p):
                d, f = os.path.split(p)
                os.replace(p, os.path.join(d, f"stale-{token}-{f}"))
        self._stale = []

    def save(self, engine_state):
        if self._stale:
            self._quarantine_stale()
        path = self.inner.save(engine_state)
        self._saved.append(path)
        return path

    def restore(self, engine_state, path=None):
        import os as _os

        if path is None:
            mine = [p for p in self._saved if _os.path.exists(p)]
            if not mine:
                return None
            path = max(mine)
        return self.inner.restore(engine_state, path=path)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def run_with_recovery(
    make_engine: Callable[[], object],
    source,
    checkpointer,
    sink=None,
    max_restarts: int = 3,
    max_batches: int = 0,
    heartbeat: Optional[Heartbeat] = None,
    resume: bool = True,
    recover_on: Tuple[Type[BaseException], ...] = (
        TransientError, OSError, ConnectionError,
    ),
) -> dict:
    """Supervisor loop: run → on crash, restore last checkpoint and resume.

    ``make_engine`` builds a fresh engine (state template) per incarnation;
    the checkpointer restores (offsets, feature state, params, scaler) into
    it and the source seeks to the checkpointed offsets, so every committed
    micro-batch is processed exactly once and uncommitted ones are replayed
    — Spark's checkpointLocation recovery contract (SURVEY §5.4).

    The sink must tolerate replayed batches (idempotent append by tx_id or
    latest-wins MERGE downstream, as in the reference's MERGE INTO).

    ``resume=False`` ignores any pre-existing checkpoint for the whole run
    (a fresh pass over the stream): the checkpointer is fenced so crash
    incarnations restore only checkpoints written by THIS run — a stale
    checkpoint from a previous run is never silently resumed, even if the
    first incarnation crashes before its first save. ``recover_on`` lists
    the exception types treated as recoverable; anything else propagates
    immediately (engine bugs should crash loudly, not restart-loop).
    """
    restarts = 0
    initial_offsets = list(source.offsets)
    if not resume:
        checkpointer = _FencedCheckpointer(checkpointer)
    if heartbeat is not None:
        inner_sink = sink

        class _BeatSink:
            def append(self, res):
                heartbeat.beat()
                if inner_sink is not None:
                    inner_sink.append(res)

        sink = _BeatSink()
    while True:
        engine = make_engine()
        restored = None
        if resume or restarts > 0:
            # With resume=False the fence makes this a no-op until the
            # current run has saved at least once.
            restored = checkpointer.restore(engine.state)
        if restored is not None:
            source.seek(engine.state.offsets)
            log.info("restored checkpoint at batch %d",
                     engine.state.batches_done)
        else:
            # No checkpoint yet: a fresh engine must consume from the very
            # beginning, or batches polled before the crash would be lost
            # to the new (empty) feature state.
            source.seek(initial_offsets)
        try:
            stats = engine.run(
                source, sink=sink, checkpointer=checkpointer,
                max_batches=max_batches,
            )
            # Final checkpoint so a clean exit never replays.
            checkpointer.save(engine.state)
            stats["restarts"] = restarts
            return stats
        except recover_on as e:
            restarts += 1
            log.warning("engine crashed (%s); restart %d/%d",
                        e, restarts, max_restarts)
            if restarts > max_restarts:
                raise
