"""Continuous learning in the loop: streaming retrain, shadow scoring,
and gated canary promotion.

The reference's only path to a better model is retrain offline, overwrite
the pickle, restart the Spark job. PR 1–6 already got further (hot param
swap mid-stream, label feedback between device steps) but nothing CLOSED
the loop — there was no candidate model, no way to compare it to the
champion on live traffic, and no safe path to promote it. This module is
that loop, in the overlap-training-with-serving shape of
*Parallel-and-stream accelerator for computationally fast supervised
learning* (arXiv:2111.00032):

- :class:`StreamingLearner` — warm-starts a **candidate** from the
  champion and incrementally fits it on the labeled-feedback window OFF
  the loop thread (the ``AsyncSink``/``PrefetchSource`` pattern: bounded
  queue, original-typed error propagation back to the supervisor,
  pausable around poison isolation), publishing versions to the
  :class:`~..io.registry.ModelRegistry` on a label cadence;
- :class:`ShadowScorer` — the candidate scores the SAME host feature
  rows beside the champion (the cheap dual output the selective-emission
  work made possible: features are already host-side wherever the
  feedback loop runs), with divergence counters
  (``rtfds_shadow_divergence_total``, ``rtfds_shadow_score_delta``) and
  **live precision/recall per model** computed from the feedback stream
  (``rtfds_live_precision/recall{model=champion|candidate}``);
- :class:`LearningLoop` — the promotion controller: installs freshly
  published candidates into shadow, **promotes** when the candidate's
  live metrics beat the champion's over a configurable label window
  (re-verifying the artifact at the gate — a corrupt candidate is
  refused, counted, and the champion keeps serving), and **rolls back**
  when the new champion regresses against its pre-promotion baseline.
  Promotion swaps params through the engine's ``_note_params_swap``
  hook, so a warm-started candidate (same shape family) never drops the
  AOT cache — promotion pays zero mid-stream recompiles.

Single-threaded contract: everything except the learner's worker thread
runs on the serving loop thread between device steps (the same contract
as :class:`~.feedback.FeedbackLoop`). The worker thread shares only the
bounded queue and the registry (whose backends are their own sync
point: an artifact is visible only after its bytes landed).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.core.batch import bucket_size
from real_time_fraud_detection_system_tpu.io.artifacts import (
    CorruptModelError,
)
from real_time_fraud_detection_system_tpu.models.scaler import transform
from real_time_fraud_detection_system_tpu.models.train import TrainedModel
from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)

log = get_logger("learner")

# |p_candidate - p_champion| ladder for the score-delta histogram
# (probabilities, not latencies — the shared latency ladder would put
# every observation in one bucket).
SCORE_DELTA_BUCKETS = (1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
                      0.1, 0.25, 0.5, 1.0)


class LiveModelMetrics:
    """Windowed confusion counts → live precision/recall for one model.

    The window is explicit (``reset()`` starts a fresh comparison
    window) so champion and candidate are always judged on the SAME
    stretch of labeled traffic; gauges export the current window."""

    def __init__(self, role: str, threshold: float = 0.5, registry=None):
        self.role = role
        self.threshold = float(threshold)
        reg = registry if registry is not None else get_registry()
        self._g_prec = reg.gauge(
            "rtfds_live_precision",
            "live precision over the current label window", model=role)
        self._g_rec = reg.gauge(
            "rtfds_live_recall",
            "live recall over the current label window", model=role)
        self._m_labels = reg.counter(
            "rtfds_live_labels_total",
            "feedback labels scored into the live metric windows",
            model=role)
        self.tp = self.fp = self.fn = self.tn = 0

    def reset(self) -> None:
        self.tp = self.fp = self.fn = self.tn = 0
        self._g_prec.set(0.0)
        self._g_rec.set(0.0)

    def observe(self, probs: np.ndarray, labels: np.ndarray) -> None:
        if len(labels) == 0:
            return
        pred = np.asarray(probs) >= self.threshold
        y = np.asarray(labels) > 0
        self.tp += int((pred & y).sum())
        self.fp += int((pred & ~y).sum())
        self.fn += int((~pred & y).sum())
        self.tn += int((~pred & ~y).sum())
        self._m_labels.inc(len(labels))
        self._g_prec.set(self.precision)
        self._g_rec.set(self.recall)

    @property
    def n(self) -> int:
        return self.tp + self.fp + self.fn + self.tn

    @property
    def positives(self) -> int:
        """Fraud labels in the window — recall is undefined without
        any, and the controller must not read the 0.0 placeholder as
        evidence (a spurious rollback at low fraud prevalence)."""
        return self.tp + self.fn

    @property
    def precision(self) -> float:
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    @property
    def recall(self) -> float:
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class ShadowScorer:
    """Score the candidate beside the champion on the same batches.

    The engine calls :meth:`score_batch` once per emitted batch (loop
    thread) with the host feature rows it already fetched and the
    champion's probabilities; the candidate's probabilities come from
    one extra jitted predict on a bucket-padded copy of the SAME
    features — the main serving step's compiled program is untouched, so
    shadow mode can never cause a serving-path recompile. Scores are
    cached by tx_id (direct-mapped, bounded) so delayed feedback labels
    can be joined back to BOTH models' decisions: that join is what
    makes ``rtfds_live_precision/recall{model=…}`` live rather than
    offline. Each transaction's label is consumed at most once (the
    cache entry clears on observation), so at-least-once feedback
    replays never double-count the confusion windows.
    """

    def __init__(self, kind: str, cfg, capacity: int = 1 << 16,
                 decision_threshold: float = 0.5,
                 divergence_threshold: float = 0.25, registry=None):
        from real_time_fraud_detection_system_tpu.models.forest import (
            resolve_z_mode,
        )
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            predict_fn_for,
        )

        self.kind = kind
        self.cfg = cfg
        self.capacity = int(capacity)
        self.decision_threshold = float(decision_threshold)
        self.divergence_threshold = float(divergence_threshold)
        self.candidate_version: Optional[int] = None
        self._cand_params = None
        self._cand_scaler = None
        # The candidate dual-scores with the SAME device-plane arithmetic
        # the champion serves with (runtime.z_mode): a mode split would
        # let the shadow diverge for arithmetic reasons, not model ones.
        predict = predict_fn_for(
            kind, z_mode=resolve_z_mode(cfg.runtime.z_mode))

        def step(params, scaler, x_raw):
            return predict(params, transform(scaler, x_raw))

        self._step = jax.jit(step)
        self._aot: dict = {}
        # per-bucket staging scratch for the padded candidate input —
        # reused across batches (the engine's PR 5 staging pattern); a
        # fresh np.zeros per batch would put an allocation + full
        # zero-fill of up to the biggest bucket on the serving loop
        # thread
        self._x_scratch: dict = {}
        # direct-mapped tx_id → (champion prob, candidate prob)
        self._ids = np.full(self.capacity, -1, np.int64)
        self._champ_p = np.zeros(self.capacity, np.float32)
        self._cand_p = np.zeros(self.capacity, np.float32)
        self._has_cand = np.zeros(self.capacity, bool)
        reg = registry if registry is not None else get_registry()
        self.champion = LiveModelMetrics(
            "champion", threshold=decision_threshold, registry=reg)
        self.candidate = LiveModelMetrics(
            "candidate", threshold=decision_threshold, registry=reg)
        self._m_rows = reg.counter(
            "rtfds_shadow_rows_total",
            "rows dual-scored by the shadow candidate")
        self._m_div = reg.counter(
            "rtfds_shadow_divergence_total",
            "rows where candidate and champion disagree (decision flip "
            "at the decision threshold, or |Δp| over the divergence "
            "threshold)")
        self._h_delta = reg.histogram(
            "rtfds_shadow_score_delta",
            "per-batch max |candidate - champion| score delta",
            buckets=SCORE_DELTA_BUCKETS)

    # -- candidate management (loop thread) -------------------------------

    def _clear_cache(self) -> None:
        self._ids.fill(-1)
        self._has_cand.fill(False)

    def set_candidate(self, version: int, params, scaler,
                      fresh_window: bool = True) -> None:
        """Install a (verified, device-form) candidate for dual scoring.

        ``fresh_window=True`` (the FIRST candidate of a comparison, e.g.
        after a promotion or rollback) restarts both metric windows so
        champion and candidate are judged on the same labeled stretch
        and drops the score cache. ``fresh_window=False`` (a
        *continuation* install: the streaming learner published a newer
        version of the same candidate stream) keeps windows and cache —
        the comparison measures the candidate STREAM's live quality, and
        resetting on every publish would starve the windows below the
        promotion gate whenever the publish cadence outpaces label
        arrival."""
        self._cand_params = jax.tree.map(jnp.asarray, params)
        self._cand_scaler = scaler
        self.candidate_version = int(version)
        if fresh_window:
            self._clear_cache()
            self.champion.reset()
            self.candidate.reset()

    def clear_candidate(self) -> None:
        self._cand_params = None
        self._cand_scaler = None
        self.candidate_version = None
        self._clear_cache()
        self.candidate.reset()

    def precompile(self, buckets) -> int:
        """AOT-compile the shadow predict per bucket size (the shadow
        twin of the engine's step precompilation): with a candidate
        installed under ``runtime.precompile``, no bucket's first shadow
        touch pays a mid-stream XLA compile."""
        if self._cand_params is None:
            return 0
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            ScoringEngine,
        )

        n_feat = None
        for b in sorted(set(int(b) for b in buckets)):
            if b in self._aot:
                continue
            if n_feat is None:
                from real_time_fraud_detection_system_tpu.features.spec \
                    import N_FEATURES

                n_feat = N_FEATURES
            x_t = jax.ShapeDtypeStruct((b, n_feat), jnp.float32)
            self._aot[b] = self._step.lower(
                ScoringEngine._sds(self._cand_params),
                ScoringEngine._sds(self._cand_scaler), x_t).compile()
        return len(self._aot)

    def _dispatch(self, pad: int, x):
        fn = self._aot.get(pad)
        if fn is not None:
            try:
                return fn(self._cand_params, self._cand_scaler, x)
            except (TypeError, ValueError):
                # shape-family drift: correctness first — fall back to
                # jit for the whole cache (it retraces, slower, right)
                self._aot = {}
        return self._step(self._cand_params, self._cand_scaler, x)

    # -- hot path (loop thread, once per emitted batch) -------------------

    def score_batch(self, tx_ids: np.ndarray, feats_np: np.ndarray,
                    champ_probs: np.ndarray) -> None:
        n = len(tx_ids)
        if n == 0:
            return
        tx_ids = np.asarray(tx_ids, dtype=np.int64)
        champ = np.asarray(champ_probs[:n], dtype=np.float32)
        cand = None
        if self._cand_params is not None:
            pad = bucket_size(n, self.cfg.runtime.batch_buckets)
            n_feat = feats_np.shape[1]
            x = self._x_scratch.get(pad)
            if x is None or x.shape[1] != n_feat:
                x = np.zeros((pad, n_feat), np.float32)
                self._x_scratch[pad] = x
            elif n < pad:
                # rows [:n] are overwritten below; only the pad tail can
                # carry a previous batch's rows
                x[n:] = 0.0
            x[:n] = feats_np[:n]
            cand = np.asarray(self._dispatch(pad, jnp.asarray(x)))[:n]
            thr = self.decision_threshold
            delta = np.abs(cand - champ)
            flips = ((cand >= thr) != (champ >= thr)) \
                | (delta > self.divergence_threshold)
            self._m_rows.inc(n)
            if flips.any():
                self._m_div.inc(int(flips.sum()))
            self._h_delta.observe(float(delta.max()))
        slots = tx_ids % self.capacity
        self._ids[slots] = tx_ids
        self._champ_p[slots] = champ
        if cand is not None:
            self._cand_p[slots] = cand
            self._has_cand[slots] = True
        else:
            self._has_cand[slots] = False

    def observe_labels(self, tx_ids: np.ndarray,
                       labels: np.ndarray) -> None:
        """Join arrived labels to the cached per-model scores and update
        the live confusion windows. Consumes each cached entry once
        (idempotent under at-least-once label redelivery)."""
        tx_ids = np.asarray(tx_ids, dtype=np.int64)
        labels = np.asarray(labels)
        good = labels >= 0
        if not good.any():
            return
        tx_ids, labels = tx_ids[good], labels[good]
        slots = tx_ids % self.capacity
        hit = (self._ids[slots] == tx_ids) & (tx_ids >= 0)
        if not hit.any():
            return
        sel = slots[hit]
        y = labels[hit]
        self.champion.observe(self._champ_p[sel], y)
        with_cand = self._has_cand[sel]
        if with_cand.any():
            self.candidate.observe(self._cand_p[sel][with_cand],
                                   y[with_cand])
        self._ids[sel] = -1  # one observation per transaction
        self._has_cand[sel] = False


class StreamingLearner:
    """Incrementally fit a candidate on the feedback window, OFF the
    loop thread, publishing to the registry on a label cadence.

    The input-side mirror of :class:`~..io.sink.AsyncSink`: the serving
    loop's only cost is one bounded-queue enqueue per labeled-feedback
    application (``submit``); a full queue DROPS the oldest-style way —
    ``rtfds_learner_dropped_labels_total`` counts it — because serving
    latency must never wait on training. A worker-thread failure is
    re-raised on the loop thread with its ORIGINAL type at the next
    ``submit``/``raise_pending`` (the supervisor's ``recover_on`` policy
    applies unchanged); while a failure is pending the worker discards
    queued work, and the re-raise clears it so a recovered incarnation
    resumes training. ``pause()``/``resume()`` gate the worker around
    poison isolation (an isolation incarnation must not overlap device
    work with a bisection in progress).

    Training is the engine's own backtracking SGD (Armijo-style halving
    until the step contracts) over a bounded replay window of the most
    recent labeled rows — each new submission re-fits ``epochs`` passes
    over the window, so the candidate converges fast on fresh evidence
    without unbounded host memory.
    """

    _STOP = object()

    def __init__(self, kind: str, params, scaler, cfg, registry,
                 parent_version: Optional[int] = None,
                 publish_every_labels: int = 512, max_queue: int = 8,
                 learning_rate: Optional[float] = None, epochs: int = 2,
                 window_rows: int = 4096, metrics=None):
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            loss_fn_for,
        )

        loss = loss_fn_for(kind)
        if loss is None:
            raise ValueError(
                f"model kind {kind!r} has no gradient path — the "
                "streaming learner fits differentiable kinds "
                "(logreg/mlp/autoencoder); tree ensembles retrain "
                "offline and publish to the registry directly")
        self.kind = kind
        self.cfg = cfg
        self.registry = registry
        self.parent_version = parent_version
        self.publish_every_labels = int(publish_every_labels)
        self.learning_rate = float(
            learning_rate if learning_rate is not None
            else cfg.train.online_learning_rate)
        self.epochs = max(1, int(epochs))
        self.window_rows = max(1, int(window_rows))
        # candidate state (worker thread owns it; reset() from the loop
        # thread takes the same lock)
        self._plock = threading.Lock()
        self._params = jax.tree.map(jnp.asarray, params)
        self._scaler = scaler
        # Bumped by reset(): a training pass that started against an
        # older generation discards its result instead of writing back —
        # a promotion/rollback reset must never be clobbered by in-flight
        # training descended from the superseded lineage.
        self._gen = 0
        # rtfdslint: disable=unbounded-queue (replay window: trimmed back under window_rows immediately after every append in _train_chunk — bounded by construction, and the trim must pop WHOLE chunks, which maxlen cannot express)
        self._buf_x: List[np.ndarray] = []
        # rtfdslint: disable=unbounded-queue (same bounded replay window as _buf_x above — the two lists trim in lockstep)
        self._buf_y: List[np.ndarray] = []
        self._buf_rows = 0
        self._labels_since_publish = 0
        self.labels_total = 0

        def fb(params, scaler, x_raw, y, valid, lr):
            x = transform(scaler, x_raw)
            l0 = loss(params, x, y, valid)
            g = jax.grad(loss)(params, x, y, valid)
            new = jax.tree.map(lambda p, gi: p - lr * gi, params, g)
            l1 = loss(new, x, y, valid)
            return new, l0, l1

        self._fb_step = jax.jit(fb)
        reg = metrics if metrics is not None else get_registry()
        self._m_trained = reg.counter(
            "rtfds_learner_labels_trained_total",
            "labeled rows the streaming learner fitted on")
        self._m_dropped = reg.counter(
            "rtfds_learner_dropped_labels_total",
            "labeled rows dropped because the learner queue was full "
            "(serving never blocks on training)")
        self._m_published = reg.counter(
            "rtfds_learner_published_total",
            "candidate versions the learner published to the registry")
        self._g_queue = reg.gauge(
            "rtfds_learner_queue_depth",
            "labeled-feedback chunks waiting for the learner thread")
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(max_queue)))
        self._published: List[int] = []
        self._pub_lock = threading.Lock()
        self._err: Optional[BaseException] = None
        self._paused = threading.Event()
        # pause/train handshake: the worker enters training only under
        # this condition while not paused, and pause() waits out an
        # in-flight chunk — the no-training-overlaps-a-bisection
        # invariant covers work already on the device, not just the
        # next queue item.
        self._train_cond = threading.Condition()
        self._in_train = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rtfds-learner")
        self._thread.start()

    # -- loop-thread API --------------------------------------------------

    def raise_pending(self) -> None:
        """Re-raise a worker failure with its original type; clears the
        box so a recovered incarnation resumes training."""
        err, self._err = self._err, None
        if err is not None:
            raise err

    def submit(self, feats: np.ndarray, labels: np.ndarray) -> None:
        """Hand the learner a chunk of labeled rows (raw serving
        features, exactly what the champion's SGD consumed)."""
        self.raise_pending()
        if len(labels) == 0:
            return
        try:
            self._q.put_nowait((np.array(feats, np.float32, copy=True),
                                np.array(labels, np.int32, copy=True)))
        except queue.Full:
            self._m_dropped.inc(len(labels))
        self._g_queue.set(self._q.qsize())

    def take_published(self) -> Optional[int]:
        """Newest candidate version published since the last call (older
        unconsumed versions are superseded), or None."""
        with self._pub_lock:
            if not self._published:
                return None
            v = self._published[-1]
            self._published.clear()
        return v

    def pause(self, timeout_s: float = 60.0) -> None:
        """Stop consuming AND wait out any in-flight training chunk
        (poison isolation runs unaccompanied — a chunk already issuing
        device work would perturb the bisection's unpipelined probe
        timing just as much as a freshly dequeued one). Submissions
        still enqueue up to the bound. A chunk is bounded (window_rows ×
        epochs), so the wait is too; the timeout is a backstop for a
        wedged device, logged rather than raised — isolation proceeding
        is better than the supervisor hanging."""
        self._paused.set()
        with self._train_cond:
            deadline = time.monotonic() + timeout_s
            while self._in_train:
                left = deadline - time.monotonic()
                if left <= 0:
                    log.warning(
                        "learner pause: in-flight training chunk did "
                        "not finish within %.0fs; poison isolation "
                        "proceeds alongside it", timeout_s)
                    return
                self._train_cond.wait(left)

    def resume(self) -> None:
        self._paused.clear()
        with self._train_cond:
            self._train_cond.notify_all()

    def reset(self, params, scaler, parent_version: Optional[int]) -> None:
        """Warm-restart the candidate (post-promotion: from the new
        champion; post-rollback: from the restored one) and drop the
        replay window — it was evidence for a decided comparison."""
        with self._plock:
            self._gen += 1
            self._params = jax.tree.map(jnp.asarray, params)
            self._scaler = scaler
            self.parent_version = parent_version
            self._buf_x.clear()
            self._buf_y.clear()
            self._buf_rows = 0
            self._labels_since_publish = 0

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every queued chunk is processed (tests + clean
        shutdown); re-raises a pending worker failure."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.raise_pending()
            # unfinished_tasks (not empty()+busy-flag) closes the TOCTOU
            # window between the worker's q.get() returning and it
            # marking itself busy: the count drops only at task_done(),
            # AFTER the chunk trained.
            if self._q.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        self._paused.clear()
        self._q.put(self._STOP)
        self._thread.join(timeout=10.0)

    # -- worker thread ----------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is self._STOP:
                    return
                # the pause check and the in-train transition share one
                # lock: once pause() has set the flag, the worker can no
                # longer slip INTO training (no TOCTOU window for the
                # bisection invariant)
                with self._train_cond:
                    while self._paused.is_set():
                        self._train_cond.wait(0.05)
                    self._in_train = True
                try:
                    if self._err is None:
                        self._train(*item)
                    # while a failure is pending, queued chunks are
                    # discarded — their labels replay from the feedback
                    # stream after the supervisor recovers
                finally:
                    with self._train_cond:
                        self._in_train = False
                        self._train_cond.notify_all()
            # rtfdslint: disable=broad-exception-catch (thread-boundary transport: the training thread parks the ORIGINAL exception for the loop thread to re-raise typed)
            except BaseException as e:  # reported to the loop thread
                self._err = e
            finally:
                self._q.task_done()
                self._g_queue.set(self._q.qsize())

    def _train(self, feats: np.ndarray, labels: np.ndarray) -> None:
        with self._plock:
            gen = self._gen
            self._buf_x.append(feats)
            self._buf_y.append(labels)
            self._buf_rows += len(labels)
            while self._buf_rows > self.window_rows and len(self._buf_x) > 1:
                self._buf_rows -= len(self._buf_y.pop(0))
                self._buf_x.pop(0)
            x_all = np.concatenate(self._buf_x)
            y_all = np.concatenate(self._buf_y)
            params, scaler = self._params, self._scaler
        biggest = max(self.cfg.runtime.batch_buckets)
        for _ in range(self.epochs):
            for s in range(0, len(y_all), biggest):
                yc = y_all[s:s + biggest]
                n = len(yc)
                pad = bucket_size(n, self.cfg.runtime.batch_buckets)
                x = np.zeros((pad, x_all.shape[1]), np.float32)
                x[:n] = x_all[s:s + n]
                y = np.zeros(pad, np.int32)
                y[:n] = np.maximum(yc, 0)
                valid = np.zeros(pad, bool)
                valid[:n] = yc >= 0
                if not valid.any():
                    continue
                jx, jy, jv = (jnp.asarray(x), jnp.asarray(y),
                              jnp.asarray(valid))
                lr = self.learning_rate
                for _h in range(8):  # Armijo halvings; lr is traced
                    new, l0, l1 = self._fb_step(params, scaler, jx, jy,
                                                jv, jnp.float32(lr))
                    if bool(l1 <= l0):
                        params = new
                        break
                    lr *= 0.5
        n_new = int((labels >= 0).sum())
        with self._plock:
            if self._gen != gen:
                # a promotion/rollback reset landed mid-train: this
                # result descends from the superseded lineage (possibly
                # a ROLLED-BACK champion) — discard, never write back
                return
            self._params = params
            # cadence counters live under the same lock reset() zeroes
            # them under, so a reset can never resurrect pre-reset labels
            self.labels_total += n_new
            self._labels_since_publish += n_new
            publish = self._labels_since_publish >= self.publish_every_labels
        self._m_trained.inc(n_new)
        if publish:
            self._publish(gen)

    def _publish(self, gen: int) -> None:
        with self._plock:
            if self._gen != gen:
                # reset() landed between the training write-back and
                # here: _params is now the freshly-reset champion —
                # publishing it would register a spurious candidate
                # identical to the champion with a stale label count
                return
            model = TrainedModel(kind=self.kind, scaler=self._scaler,
                                 params=self._params)
            parent = self.parent_version
            labels = self.labels_total
            self._labels_since_publish = 0
        # the (possibly slow, retried) registry PUT runs unlocked — a
        # loop-thread reset() must never wait out a store retry budget
        version = self.registry.publish(
            model, parent=parent, source="learner",
            labels_trained=labels)
        self._m_published.inc()
        with self._plock:
            stale = self._gen != gen
        if not stale:
            # a reset that landed during the PUT supersedes this
            # version: leave it in the registry as lineage, but never
            # hand it to the controller for install
            with self._pub_lock:
                self._published.append(version)
        log.info("published candidate v%d (parent v%s, %d labels)",
                 version, parent, labels)


class LearningLoop:
    """The promotion controller: shadow install → gated canary
    promotion → regression rollback, polled once per finished batch
    (between device steps, the feedback contract).

    Every decision is made from the LIVE metric windows the feedback
    stream feeds and re-verifies the artifact at the gate: a candidate
    whose registry bytes are corrupt is refused
    (``rtfds_model_promotions_total{outcome=refused_corrupt}``) and the
    champion keeps serving. Promotion and rollback swap engine params
    through ``_note_params_swap`` — a same-shape-family candidate keeps
    the AOT cache, so neither ever pays a mid-stream recompile.
    """

    def __init__(self, registry, cfg, kind: str, model=None, learner=None,
                 metrics=None, model_is_champion: bool = True):
        lc = cfg.learn
        self.registry = registry
        self.cfg = cfg
        self.kind = kind
        self.learner = learner
        self.promote_min_labels = int(lc.promote_min_labels)
        self.promote_margin = float(lc.promote_margin)
        self.precision_tolerance = float(lc.precision_tolerance)
        self.rollback_min_labels = int(lc.rollback_min_labels)
        self.rollback_margin = float(lc.rollback_margin)
        reg = metrics if metrics is not None else get_registry()
        self._m_promotions = {
            o: reg.counter(
                "rtfds_model_promotions_total",
                "candidate promotion attempts by outcome", outcome=o)
            for o in ("promoted", "refused_corrupt")
        }
        self._m_rollbacks = reg.counter(
            "rtfds_model_rollbacks_total",
            "champions rolled back after a live-metric regression")
        self._m_resyncs = reg.counter(
            "rtfds_model_resyncs_total",
            "incarnations whose starting params predated the registry "
            "champion and were re-synced to it at attach")
        self.shadow = ShadowScorer(
            kind, cfg, capacity=int(lc.shadow_cache_rows),
            decision_threshold=float(lc.decision_threshold),
            divergence_threshold=float(lc.divergence_threshold),
            registry=reg)
        # Bootstrap: an empty registry adopts the serving model as v1 —
        # from here on, every params swap is a versioned event.
        if registry.champion_version() is None and model is not None:
            v = registry.publish(model, source="bootstrap")
            registry.promote(v, by="bootstrap")
        self.champion_version = registry.champion_version()
        # The version whose params the serving engines are CONSTRUCTED
        # with (cmd_score adopts the champion before building engines):
        # attach() stamps it on fresh engines so a later incarnation can
        # tell bootstrap-era params from the current champion.
        # model_is_champion=False (the caller FAILED to adopt the
        # champion — e.g. a flaky-store read at startup — and serves
        # fallback params instead): the stamp must not claim otherwise,
        # so it stays None and every attach() retries re-applying the
        # champion until the registry heals.
        self._boot_version = (self.champion_version
                              if model_is_champion else None)
        if (model_is_champion and learner is not None
                and learner.parent_version is None):
            learner.parent_version = self.champion_version
        # post-promotion watch: baseline the new champion must hold
        self._watch: Optional[dict] = None
        # newest published version waiting out an active canary watch
        # (installing mid-watch would reset the champion's metric window
        # and discard the watch's accumulated evidence)
        self._pending_install: Optional[int] = None
        self._attached = None  # the engine currently wired (identity)
        # Without an in-stream learner (tree kinds), candidates arrive
        # by EXTERNAL publish (`rtfds registry` after an offline
        # retrain): poll the registry on a batch cadence for a version
        # newer than anything this loop has handled. _ext_seen marks
        # handled versions so a rolled-back ex-champion (still the
        # newest artifact) is never re-installed.
        self._ext_every = (int(lc.external_poll_batches)
                           if learner is None else 0)
        self._ext_tick = 0
        self._ext_seen: Optional[int] = None

    # -- engine wiring ----------------------------------------------------

    def attach(self, engine) -> None:
        """Install the shadow scorer + learner tap on the engine
        (idempotent per engine; ``engine.run`` calls it at start — a
        supervisor's NEXT incarnation brings a fresh engine, and the
        loop re-attaches to it). Then re-syncs the engine to the
        registry champion: the registry pointer, not whatever params the
        incarnation starts with, is the record of what should serve."""
        if self._attached is engine:
            return
        if engine.state.model_version is None:
            # fresh engine (no checkpoint stamp): its params are the
            # model cmd_score built engines from — the adoption-time
            # champion
            engine.state.model_version = self._boot_version
        engine.set_shadow(self.shadow)
        if self.learner is not None:
            engine.feedback_tap = self.learner.submit
        if self.cfg.runtime.precompile:
            self.shadow.precompile(self.cfg.runtime.batch_buckets)
        self._attached = engine
        self._resync(engine)

    def _resync(self, engine) -> None:
        """Re-apply the current champion when the engine's params stamp
        disagrees with the registry pointer. A fresh incarnation's
        params come from the bootstrap model or a checkpoint restore,
        either of which can predate a promotion/reload (a crash between
        a swap and the next checkpoint save restores pre-swap weights
        while the registry already records the new champion — without
        this the stale weights would serve indefinitely, silently).
        Counted in ``rtfds_model_resyncs_total``; a champion that fails
        verification keeps the restored params serving (loudly)."""
        v = self.champion_version
        stamp = engine.state.model_version
        if v is None or stamp == v:
            return
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            device_params_for,
        )

        try:
            m = self.registry.get(v)
        except (CorruptModelError, KeyError) as e:
            log.error(
                "cannot re-apply champion v%s over restored params "
                "(stamp v%s): %s: %s — serving the restored params; "
                "repair with `rtfds registry --verify` / --rollback",
                v, stamp, type(e).__name__, e)
            return
        engine.state.params = engine._note_params_swap(
            device_params_for(self.kind, m.params))
        engine.state.scaler = m.scaler
        engine._online_dirty = False
        engine.state.model_version = v
        self._m_resyncs.inc()
        self._event("model_resync", version=v, restored_stamp=stamp)
        log.info("re-applied registry champion v%s (incarnation started "
                 "on v%s params)", v, stamp)

    def pause(self) -> None:
        """Gate the learner's worker around poison isolation (no
        training overlaps a bisection in progress)."""
        if self.learner is not None:
            self.learner.pause()

    def resume(self) -> None:
        if self.learner is not None:
            self.learner.resume()

    def close(self) -> None:
        if self.learner is not None:
            self.learner.pause()
            self.learner.close()

    def note_external_swap(self, params, scaler, outcome: str,
                           engine=None) -> None:
        """A hot reload swapped params from OUTSIDE the registry: record
        it as a versioned event (publish + promote, source=reload) so
        the lineage stays complete. Best-effort — a params form the
        serializer can't round-trip (device-form tree tables) is logged,
        not fatal. The publish runs synchronously on the loop thread:
        reloads are poll-cadence rare and already pay a same-magnitude
        artifact load inline, and the lineage stamp must land before the
        next checkpoint save can record the new version."""
        try:
            model = TrainedModel(kind=self.kind, scaler=scaler,
                                 params=params)
            v = self.registry.publish(model, parent=self.champion_version,
                                      source="reload", note=outcome)
            self.registry.promote(v, by="reload")
            self.champion_version = v
            if engine is not None:
                # the stamp travels with the checkpoint: a restore that
                # predates this reload will mismatch the pointer and
                # attach() re-applies v
                engine.state.model_version = v
            # The reload supersedes any in-flight canary comparison: the
            # watch's baseline/previous describe a champion that is no
            # longer serving, and a later rollback would desync the
            # pointer (whose history top is now THIS reload) from the
            # params _rollback restores. Start a fresh comparison epoch.
            self._watch = None
            self._pending_install = None
            self.shadow.clear_candidate()
            self.shadow.champion.reset()
            if self.learner is not None:
                self.learner.reset(params, scaler, v)
        # rtfdslint: disable=broad-exception-catch (lineage registration of a hot-reload is best-effort: ANY registry failure must leave serving on the already-swapped params, warn-logged)
        except Exception as e:
            log.warning("could not register hot-reloaded params as a "
                        "version (%s: %s); serving is unaffected",
                        type(e).__name__, e)

    # -- per-batch control (loop thread) ----------------------------------

    def on_batch(self, engine) -> None:
        if self.learner is not None:
            self.learner.raise_pending()
            v = self.learner.take_published()
            if v is not None:
                self._pending_install = v
        elif self._ext_every > 0:
            self._ext_tick += 1
            if self._ext_tick >= self._ext_every:
                self._ext_tick = 0
                self._poll_external()
        if self._watch is not None:
            self._maybe_rollback(engine)
        if self._watch is None:
            # installs wait out an active watch: a fresh install resets
            # the champion's metric window, which IS the canary evidence
            # (a rollback discards the pending version with the rest of
            # the regressed lineage)
            v = self._pending_install
            self._pending_install = None
            if v is not None and v != self.shadow.candidate_version:
                self._install_candidate(engine, v)
            if self.shadow.candidate_version is not None:
                self._maybe_promote(engine)

    def _poll_external(self) -> None:
        """One registry listing: is there an externally published
        candidate this loop has not handled yet? (Only reached with
        ``learner=None`` — with an in-stream learner, candidates arrive
        through ``take_published``.)"""
        try:
            vs = self.registry.versions()
        # rtfdslint: disable=broad-exception-catch (a flaky registry listing skips ONE external-candidate poll and retries next cadence; any store/parse error type lands here via the backend)
        except Exception as e:
            log.warning("registry poll for external candidates failed "
                        "(%s: %s); retrying next cadence",
                        type(e).__name__, e)
            return
        if not vs:
            return
        v = vs[-1]
        if v in (self.champion_version, self.shadow.candidate_version,
                 self._ext_seen):
            return
        self._ext_seen = v
        self._pending_install = v
        log.info("externally published candidate v%d detected", v)

    def _install_candidate(self, engine, version: int) -> None:
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            device_params_for,
        )

        try:
            m = self.registry.get(version)
        except (CorruptModelError, KeyError) as e:
            # CorruptModelError: the artifact was quarantined + counted
            # by the registry. KeyError: the manifest vanished between
            # listing and read (a concurrent CLI get quarantined it).
            # Either way: refuse the install, keep the current shadow —
            # never let a registry read kill the serving loop.
            self._m_promotions["refused_corrupt"].inc()
            self._event("model_promote_refused", version=version,
                        stage="shadow_install",
                        reason=getattr(e, "reason", "missing"))
            return
        if m.kind != self.kind:
            # an external publish of the wrong model family: the jitted
            # shadow predict (and any later promotion swap) would change
            # the engine's shape family — not installable
            log.warning("candidate v%d is kind=%r but the serving kind "
                        "is %r; not installing (republish the right "
                        "kind)", version, m.kind, self.kind)
            self._event("model_promote_refused", version=version,
                        stage="shadow_install", reason="kind_mismatch")
            return
        self.shadow.set_candidate(
            version, device_params_for(self.kind, m.params), m.scaler,
            fresh_window=self.shadow.candidate_version is None)
        if self.cfg.runtime.precompile:
            self.shadow.precompile(self.cfg.runtime.batch_buckets)
        self._event("model_candidate", version=version,
                    champion=self.champion_version)

    def _maybe_promote(self, engine) -> None:
        ch, cand = self.shadow.champion, self.shadow.candidate
        if (cand.n < self.promote_min_labels
                or ch.n < self.promote_min_labels):
            return
        if (cand.recall > ch.recall + self.promote_margin
                and cand.precision >= ch.precision
                - self.precision_tolerance):
            self._promote(engine)

    def _promote(self, engine) -> None:
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            device_params_for,
        )

        version = self.shadow.candidate_version
        baseline = {"recall": self.shadow.candidate.recall,
                    "precision": self.shadow.candidate.precision}
        try:
            m = self.registry.get(version)  # re-verify AT the gate
        except (CorruptModelError, KeyError) as e:
            # KeyError = the version vanished since install (quarantined
            # by a concurrent reader): same refusal, same counter
            self._m_promotions["refused_corrupt"].inc()
            self._event("model_promote_refused", version=version,
                        stage="promote",
                        reason=getattr(e, "reason", "missing"))
            self.shadow.clear_candidate()
            return
        prev = self.champion_version
        engine.state.params = engine._note_params_swap(
            device_params_for(self.kind, m.params))
        engine.state.scaler = m.scaler
        engine.state.model_version = version
        # a promotion IS the versioned swap path: the registry artifact
        # replaces the on-device params by design, not by accident
        engine._online_dirty = False
        self.registry.promote(version)
        self.champion_version = version
        self._watch = {**baseline, "previous": prev}
        self.shadow.clear_candidate()
        self.shadow.champion.reset()
        if self.learner is not None:
            self.learner.reset(m.params, m.scaler, version)
        self._m_promotions["promoted"].inc()
        self._event("model_promoted", version=version, previous=prev,
                    recall=round(baseline["recall"], 4),
                    precision=round(baseline["precision"], 4))
        log.info("promoted candidate v%s over champion v%s "
                 "(live recall %.3f, precision %.3f)", version, prev,
                 baseline["recall"], baseline["precision"])

    def _maybe_rollback(self, engine) -> None:
        ch = self.shadow.champion
        if ch.n < self.rollback_min_labels or ch.positives == 0:
            # No fraud labels in the window yet: recall is UNDEFINED,
            # not 0.0 — at ~1% prevalence a min-size window has no
            # positives with non-trivial probability, and reading the
            # placeholder as collapse would demote a healthy champion.
            # Keep watching until positive labels arrive.
            return
        watch, self._watch = self._watch, None
        if ch.recall >= watch["recall"] - self.rollback_margin:
            # the new champion held its pre-promotion baseline over a
            # full window: the canary is proven, watch ends
            self._event("model_canary_passed",
                        version=self.champion_version,
                        recall=round(ch.recall, 4))
            return
        self._rollback(engine, watch)

    def _rollback(self, engine, watch: dict) -> None:
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            device_params_for,
        )

        prev = watch["previous"]
        regressed = self.champion_version
        regressed_recall = self.shadow.champion.recall
        try:
            m = self.registry.get(prev)
        except (CorruptModelError, KeyError) as e:
            log.error("rollback target v%s failed verification (%s); "
                      "keeping the regressed champion — operator "
                      "intervention needed", prev, e)
            return
        engine.state.params = engine._note_params_swap(
            device_params_for(self.kind, m.params))
        engine.state.scaler = m.scaler
        engine.state.model_version = prev
        engine._online_dirty = False
        self.registry.rollback()
        self.champion_version = prev
        self.shadow.champion.reset()
        self.shadow.clear_candidate()
        # anything published during the watch descends from the
        # regressed champion: never install it
        self._pending_install = None
        if self.learner is not None:
            self.learner.reset(m.params, m.scaler, prev)
        self._m_rollbacks.inc()
        self._event("model_rollback", version=prev, regressed=regressed,
                    recall=round(regressed_recall, 4),
                    baseline=round(watch["recall"], 4))
        log.warning("rolled back champion v%s → v%s (live recall fell "
                    "below the promotion baseline %.3f)", regressed, prev,
                    watch["recall"])

    def _event(self, name: str, **fields) -> None:
        rec = active_recorder()
        if rec is not None:
            rec.record_event(name, **fields)
