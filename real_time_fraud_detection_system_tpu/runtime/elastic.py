"""Elastic-fleet policy plane: the autoscaler's decision logic, resize
state machine, and committed-topology manifest — everything the
launcher's resize loop needs, importable and unit-testable WITHOUT
spawning a single process.

The reference pipeline leans on Spark/Kafka cluster elasticity to
survive traffic swings; this repo's fleet (``tools/multihost_launcher``)
is fixed-size without this module — a sustained spike rides the PR 12
overload ladder to rung 3 and sheds forever. The split of
responsibilities mirrors the ladder itself:

- :class:`ElasticPolicy` is the hysteresis + dwell brain: it watches the
  aggregated ``/cluster`` signals (worst-process overload rung, lag
  trend, shed backlog) and decides *whether* to resize — flap-proof by
  the same sustained-condition discipline as the ladder's rung
  transitions (dwell before acting, cooldown after, dead band between
  grow and shrink conditions).
- :class:`ResizeFsm` is the chaos-survivable spine: every resize walks
  ``steady → draining → retopologizing → committing → relaunching →
  steady``, and ANY fault inside the window rolls back through
  ``rolling_back`` to the pre-resize topology. Transitions are
  validated — a half-resized fleet is a programming error here, never a
  runtime state.
- :func:`store_topology` / :func:`load_topology` make the committed
  topology a single atomically-replaced manifest: readers either see the
  old fleet or the new one, and a torn write quarantines itself and
  falls back (the checkpoint plane's corrupt-entry discipline, applied
  to the control plane).

The fleet metrics registered here (:func:`fleet_metrics`) live in this
module — inside the package — so the metric-drift lint can hold the
README catalog and the dashboard to the same registry the launcher
actually exports.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from real_time_fraud_detection_system_tpu.utils.metrics import (
    get_registry,
)

# -- resize state machine ---------------------------------------------------

STEADY = "steady"
DRAINING = "draining"
RETOPOLOGIZING = "retopologizing"
COMMITTING = "committing"
RELAUNCHING = "relaunching"
ROLLING_BACK = "rolling_back"

# Every legal phase edge. Any mid-resize phase may divert to
# ROLLING_BACK (the chaos path); completion closes back to STEADY.
_TRANSITIONS = {
    STEADY: {DRAINING},
    DRAINING: {RETOPOLOGIZING, ROLLING_BACK},
    RETOPOLOGIZING: {COMMITTING, ROLLING_BACK},
    COMMITTING: {RELAUNCHING, ROLLING_BACK},
    RELAUNCHING: {STEADY, ROLLING_BACK},
    ROLLING_BACK: {STEADY},
}


class ResizeFsmError(RuntimeError):
    """An illegal resize-phase transition was attempted — the launcher
    logic, not the fleet, is broken; fail loudly instead of serving a
    half-resized topology."""


class ResizeFsm:
    """The resize window's explicit state machine. One instance per
    launcher; phases advance via :meth:`to` (validated), faults divert
    via :meth:`rollback`, and every transition lands in the journal
    callback so a crashed launcher's recovery can read how far the
    resize got."""

    def __init__(self, journal=None):
        self.phase = STEADY
        self._journal = journal  # callable(phase_record: dict) | None

    def to(self, phase: str, **info) -> None:
        if phase not in _TRANSITIONS.get(self.phase, ()):
            raise ResizeFsmError(
                f"illegal resize transition {self.phase} -> {phase}")
        self.phase = phase
        if self._journal is not None:
            self._journal({"phase": phase, **info})

    def rollback(self, **info) -> None:
        """Divert to ROLLING_BACK from any mid-resize phase."""
        if self.phase in (STEADY, ROLLING_BACK):
            raise ResizeFsmError(
                f"rollback from {self.phase} is not a resize fault")
        self.to(ROLLING_BACK, **info)

    @property
    def mid_resize(self) -> bool:
        return self.phase != STEADY


# -- policy -----------------------------------------------------------------


@dataclass
class ElasticConfig:
    """Autoscaler policy knobs (the launcher's ``--autoscale-*`` flags).

    Grow fires after the worst process has held rung >= ``grow_rung``
    for ``grow_dwell_s`` seconds; shrink after the fleet has been fully
    idle (rung 0, non-positive lag trend, zero shed backlog) for
    ``shrink_dwell_s``. ``cooldown_s`` blocks BOTH directions after any
    resize (completed or rolled back) so a rollback cannot flap straight
    into a retry storm. Targets double/halve, clamped to
    [min_processes, max_processes] — the resize itself is expensive
    (drain + merge + relaunch), so each one should buy a capacity
    octave."""

    min_processes: int = 1
    max_processes: int = 4
    grow_rung: int = 2
    grow_dwell_s: float = 2.0
    shrink_dwell_s: float = 10.0
    cooldown_s: float = 5.0

    def __post_init__(self):
        if self.min_processes < 1:
            raise ValueError(
                f"min_processes must be >= 1, got {self.min_processes}")
        if self.max_processes < self.min_processes:
            raise ValueError(
                f"max_processes {self.max_processes} < min_processes "
                f"{self.min_processes}")
        if not 1 <= self.grow_rung <= 3:
            raise ValueError(
                f"grow_rung must be in [1, 3], got {self.grow_rung}")
        if min(self.grow_dwell_s, self.shrink_dwell_s,
               self.cooldown_s) < 0:
            raise ValueError("dwell/cooldown seconds must be >= 0")


@dataclass
class ClusterSignals:
    """One poll of the aggregated fleet view (``/cluster`` + merged
    worker registries) — the policy's entire input."""

    worst_rung: int = 0
    lag_trend_rows_per_s: float = 0.0
    shed_pending_rows: float = 0.0
    worst_pressure: float = 0.0
    alive: int = 0


@dataclass
class ResizeDecision:
    direction: str  # "grow" | "shrink"
    target: int
    reason: str


class ElasticPolicy:
    """Sustained-pressure grow / sustained-idle shrink, with the PR 12
    ladder's flap-proofing: a condition must HOLD for its dwell (any
    contrary observation resets the streak), a dead band separates the
    two conditions (rung 1, or draining backlogs, arms neither), and a
    cooldown after every resize absorbs the relaunch transient."""

    def __init__(self, cfg: ElasticConfig):
        self.cfg = cfg
        self._grow_since: Optional[float] = None
        self._shrink_since: Optional[float] = None
        self._cooldown_until = 0.0

    def note_resized(self, now: float) -> None:
        """A resize just finished (completed OR rolled back): reset the
        streaks and start the cooldown."""
        self._grow_since = None
        self._shrink_since = None
        self._cooldown_until = now + self.cfg.cooldown_s

    def observe(self, sig: ClusterSignals, n_processes: int,
                now: float) -> Optional[ResizeDecision]:
        """Feed one signals poll; returns a decision when a dwell
        completes, else None. ``now`` is caller-supplied monotonic time
        so tests drive the clock."""
        cfg = self.cfg
        grow_cond = sig.worst_rung >= cfg.grow_rung
        # Idle means nothing is owed AND every process says so: rung 0
        # everywhere, the backlog is not growing, no shed rows await
        # replay, and every worker's registry was actually scraped — a
        # worker that is still warming up (or unreachable) is not
        # provably idle, and shrinking on blindness would drain a fleet
        # that never got to serve. Shrinking while rows are deferred
        # would merge them into a smaller fleet that just proved it
        # cannot keep up.
        shrink_cond = (sig.alive >= n_processes
                       and sig.worst_rung == 0
                       and sig.lag_trend_rows_per_s <= 0.0
                       and sig.shed_pending_rows <= 0.0)
        if not grow_cond:
            self._grow_since = None
        if not shrink_cond:
            self._shrink_since = None
        if now < self._cooldown_until:
            return None
        if grow_cond and n_processes < cfg.max_processes:
            if self._grow_since is None:
                self._grow_since = now
            if now - self._grow_since >= cfg.grow_dwell_s:
                target = min(cfg.max_processes, n_processes * 2)
                return ResizeDecision(
                    "grow", target,
                    f"rung {sig.worst_rung} sustained "
                    f"{cfg.grow_dwell_s:g}s (pressure "
                    f"{sig.worst_pressure:.2f}, lag trend "
                    f"{sig.lag_trend_rows_per_s:+.0f} rows/s)")
        if shrink_cond and n_processes > cfg.min_processes:
            if self._shrink_since is None:
                self._shrink_since = now
            if now - self._shrink_since >= cfg.shrink_dwell_s:
                target = max(cfg.min_processes, n_processes // 2)
                return ResizeDecision(
                    "shrink", target,
                    f"idle {cfg.shrink_dwell_s:g}s (rung 0, lag trend "
                    f"{sig.lag_trend_rows_per_s:+.0f} rows/s, no shed "
                    "backlog)")
        return None


# -- signal extraction ------------------------------------------------------


def _series_values(snap: dict, name: str):
    fam = (snap or {}).get(name)
    if not fam:
        return
    for row in fam.get("series", []):
        v = row.get("value")
        if v is not None:
            yield float(v)


def signals_from_snapshots(snaps: Dict[str, dict]) -> ClusterSignals:
    """Distill per-worker registry snapshots (``/metrics.json`` payloads
    keyed by process id) into the policy's :class:`ClusterSignals`.
    Worst-process semantics for rung/pressure (the slowest process gates
    the fleet), max for the lag trend (the worst backlog slope), sum for
    the shed backlog (rows owed are owed by the FLEET)."""

    sig = ClusterSignals(alive=len(snaps))
    for snap in snaps.values():
        sig.worst_rung = max(sig.worst_rung, int(max(
            _series_values(snap, "rtfds_overload_rung"), default=0)))
        sig.worst_pressure = max(sig.worst_pressure, max(
            _series_values(snap, "rtfds_overload_pressure"), default=0.0))
        sig.lag_trend_rows_per_s = max(
            sig.lag_trend_rows_per_s,
            max(_series_values(snap,
                               "rtfds_source_lag_trend_rows_per_s"),
                default=0.0))
        sig.shed_pending_rows += sum(
            _series_values(snap, "rtfds_shed_pending_rows"))
    return sig


# -- committed topology manifest --------------------------------------------


def store_topology(path: str, manifest: dict) -> None:
    """Atomically commit the fleet's topology manifest: tmp + fsync +
    rename, then a read-back verify. Until the rename lands, readers see
    the previous committed topology — the commit point of every resize.
    Raises ``OSError``/``ValueError`` when the write cannot be proven
    durable (the caller rolls back)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    data = json.dumps(manifest, sort_keys=True).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    back = load_topology(path)
    if back != manifest:
        raise ValueError(
            f"topology manifest at {path} failed read-back verification")


def load_topology(path: str) -> Optional[dict]:
    """Read the committed topology. A torn/unparsable manifest is
    QUARANTINED (renamed aside as evidence, like a corrupt checkpoint)
    and reads as None — the caller falls back to its previous known
    topology instead of trusting garbage."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    try:
        man = json.loads(raw.decode("utf-8"))
        if not isinstance(man, dict):
            raise ValueError("topology manifest is not an object")
        return man
    except (ValueError, UnicodeDecodeError):
        try:
            os.replace(path, path + f".torn-{int(time.time() * 1e3)}")
        except OSError:
            pass
        return None


# -- fleet metrics ----------------------------------------------------------


@dataclass
class FleetMetrics:
    """Handles for the elastic-fleet registry family — registered in the
    LAUNCHER's registry (merged into the ``/cluster`` aggregation view
    as the ``launcher`` process), and in tests' registries directly."""

    fleet_size: object = field(default=None)
    resize_pending: object = field(default=None)
    resize_seconds: object = field(default=None)
    spike_absorb: object = field(default=None)
    _registry: object = field(default=None)

    def resizes_total(self, direction: str, outcome: str):
        return self._registry.counter(
            "rtfds_fleet_resizes_total",
            "fleet resizes by direction and outcome (completed = new "
            "topology committed and serving; rolled_back = a resize-"
            "window fault restored the pre-resize fleet)",
            direction=direction, outcome=outcome)


def fleet_metrics(registry=None) -> FleetMetrics:
    reg = registry if registry is not None else get_registry()
    m = FleetMetrics(_registry=reg)
    m.fleet_size = reg.gauge(
        "rtfds_fleet_size",
        "serving processes in the current committed topology")
    m.resize_pending = reg.gauge(
        "rtfds_resize_pending",
        "1 while a resize is in flight (drain -> retopologize -> "
        "commit -> relaunch window); 0 in steady state")
    m.resize_seconds = reg.histogram(
        "rtfds_resize_seconds",
        "wall time of one fleet resize, drain start to new fleet "
        "serving (or rollback landed)")
    m.spike_absorb = reg.gauge(
        "rtfds_spike_absorb_seconds",
        "time from spike detection (worst rung first >= grow rung) to "
        "the worst rung back <= 1 on the resized fleet")
    return m
