"""Micro-batch scoring engine — the Spark Structured Streaming replacement.

The reference's hot loop (``fraud_detection.py:204-211`` + SURVEY §3.1) is:
Iceberg snapshot scan → SQL join → Arrow → Python UDF → sklearn → Iceberg
append, crossing four process boundaries per batch. Here the loop is: source
poll → host dedup/pad → ``device_put`` → ONE jitted ``step`` (feature state
scatter/gather + scale + classify [+ online SGD]) → sink append. The
feature state and weights never leave HBM; the jit cache is keyed by bucket
size only.

``--scorer {cpu,tpu}`` (reference north star): ``tpu`` runs the jitted
classifier; ``cpu`` runs the sklearn oracle on the same features, for parity
and baseline measurement.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.core.batch import (
    TxBatch,
    bucket_size,
    make_batch,
    pack_batch,
    unpack_batch,
)
from real_time_fraud_detection_system_tpu.features.online import (
    FeatureState,
    init_feature_state,
    state_bytes,
    update_and_featurize,
    update_and_featurize_exact,
    update_and_score_pallas,
    update_and_score_pallas_forest,
)
from real_time_fraud_detection_system_tpu.features.spec import N_FEATURES
from real_time_fraud_detection_system_tpu.models.forest import (
    TreeEnsemble,
    for_device,
    resolve_z_mode,
)
from real_time_fraud_detection_system_tpu.models.forest import (
    predict_proba as forest_predict_proba,
)
from real_time_fraud_detection_system_tpu.models.logreg import (
    logreg_loss,
    logreg_predict_proba,
)
from real_time_fraud_detection_system_tpu.models.mlp import (
    mlp_loss,
    mlp_predict_proba,
)
from real_time_fraud_detection_system_tpu.models.scaler import Scaler, transform
from real_time_fraud_detection_system_tpu.core import native
from real_time_fraud_detection_system_tpu.ops.dedup import (
    latest_wins_mask_host,
)
from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)
from real_time_fraud_detection_system_tpu.utils.timing import LatencyTracker
from real_time_fraud_detection_system_tpu.utils.trace import get_tracer
from real_time_fraud_detection_system_tpu.utils.xla_telemetry import (
    DeviceMemoryTelemetry,
    RecompileDetector,
    install_compile_telemetry,
    step_signature,
)

# The per-batch loop-time decomposition every layer reports under
# (rtfds_phase_seconds{phase=...} and the flight record's "phases" dict):
# source poll → host prep (dedup+pack) → dispatch (H2D + jit call) →
# result wait (device compute minus overlap + unpack) → sink write.
PHASES = ("source_poll", "host_prep", "dispatch", "result_wait",
          "sink_write")

# One double-buffered Pallas tree block must sit well inside ~16MB VMEM
# next to the row tile and [Bt, 128·k] intermediates (ops/pallas_forest).
# Decided at TRACE time from the live params' static shapes, so a
# checkpoint restore that swaps in a deeper ensemble retraces into the
# XLA composition instead of a VMEM-overflowing kernel.
_PALLAS_BLOCK_BUDGET = 4 * 2 ** 20


def device_params_for(kind: str, params):
    """Engine-ready params: tree-ensemble kinds convert to the fast GEMM
    form once (the step then serves them unchanged). Used at engine build
    AND by hot model reloads, which swap ``state.params`` in place."""
    if kind in ("tree", "forest") and isinstance(params, TreeEnsemble):
        return for_device(params, N_FEATURES)
    if kind == "gbt":
        from real_time_fraud_detection_system_tpu.models.gbt import (
            gbt_for_device,
        )

        return gbt_for_device(params, N_FEATURES)
    return params


def predict_fn_for(kind: str, z_mode: Optional[str] = None) -> Callable:
    """Device predict for ``kind``. ``z_mode`` (a RESOLVED mode —
    f32/bf16/int8, see ``models/forest.resolve_z_mode``) selects the
    tree-ensemble z-contraction arithmetic; non-ensemble kinds have no
    contraction and ignore it."""
    if kind == "logreg":
        return logreg_predict_proba
    if kind == "mlp":
        return mlp_predict_proba
    if kind == "gbt":
        from real_time_fraud_detection_system_tpu.models.gbt import (
            gbt_predict_proba,
        )

        if z_mode is None:
            return gbt_predict_proba
        return lambda p, x: gbt_predict_proba(p, x, z_mode)
    if kind in ("tree", "forest"):
        if z_mode is None:
            return forest_predict_proba
        return lambda p, x: forest_predict_proba(p, x, z_mode)
    if kind == "autoencoder":
        from real_time_fraud_detection_system_tpu.models.autoencoder import (
            autoencoder_predict_proba,
        )

        return autoencoder_predict_proba
    raise ValueError(f"unknown model kind {kind}")


def loss_fn_for(kind: str) -> Optional[Callable]:
    if kind == "logreg":
        return logreg_loss
    if kind == "mlp":
        return mlp_loss
    if kind == "autoencoder":
        from real_time_fraud_detection_system_tpu.models.autoencoder import (
            autoencoder_loss,
        )

        return autoencoder_loss
    return None  # tree ensembles have no gradient path


@dataclass
class EngineState:
    """Host-visible engine state (device pytrees + offsets + counters)."""

    feature_state: FeatureState
    params: object
    scaler: Scaler
    offsets: List[int] = field(default_factory=list)
    batches_done: int = 0
    rows_done: int = 0
    # Device count whose owner layout feature_state carries (window/
    # history layouts are shape-identical permutations, so the width must
    # travel WITH the state). Checkpoints record it; restore compares it
    # to the serving engine's own width and auto-reshards on mismatch.
    layout_devices: int = 1
    # Registry version the params descend from (continuous learning).
    # Travels WITH the state so a checkpoint restore tells the learning
    # loop exactly which champion the restored params are: a crash
    # between a promotion/reload swap and the next save restores
    # pre-swap weights, and the stamp mismatch is how attach() knows to
    # re-apply the registry champion instead of serving them stale.
    model_version: Optional[int] = None
    # Multi-host topology the writer served under: the fleet's process
    # count and THIS state's process id (its residue block). Like
    # layout_devices, it must travel with the state — a per-process
    # checkpoint holds only its block's keys, so restoring it under a
    # different topology would silently drop every other block.
    # Checkpoints record both; restore refuses a mismatch (except the
    # sanctioned 1→P adoption, which re-slices a global checkpoint).
    process_count: int = 1
    process_id: int = 0


@dataclass(frozen=True)
class DispatchSignature:
    """One (shape × static-facts) combination the engine can dispatch.

    The **dispatch signature inventory** (:meth:`ScoringEngine.
    dispatch_inventory`) enumerates every signature the runtime can ever
    hand to the device: ``key`` is simultaneously the AOT-cache key
    ``precompile()`` compiles under AND the key ``_dispatch_step``
    looks up at serve time, so the coverage proof and the warmup path
    cannot drift — there is one enumeration, and both consume it.
    ``tools/rtfdsverify`` abstract-interprets each signature's traced
    program (CPU-only, no weights) to prove the device-plane contracts
    (AOT coverage, z-mode exactness, donation safety, Pallas admission)
    before a stream ever starts."""

    key: tuple           # AOT cache key == runtime dispatch key
    variant: str         # "step" | "sharded-local" | "sharded-routed"
    kind: str            # model kind the step closes over
    z_mode: Optional[str]  # resolved z mode (tree-ensemble kinds; else None)
    bucket: int          # padded batch rows of this signature
    donate: tuple        # donated argnums of the jitted step
    selective: bool      # selective-emission packing compiled in
    emit_dtype: str      # emitted feature matrix dtype ("float32"/"bfloat16")
    use_pallas: bool     # a fused Pallas kernel is reachable at trace time

    def describe(self) -> str:
        """Stable human/fingerprint label (rtfdsverify finding context)."""
        return (f"{self.variant}[kind={self.kind} z={self.z_mode} "
                f"bucket={self.bucket} selective={self.selective} "
                f"emit={self.emit_dtype} pallas={self.use_pallas} "
                f"donate={','.join(map(str, self.donate)) or '-'}]")


@dataclass
class BatchResult:
    tx_id: np.ndarray
    tx_datetime_us: np.ndarray
    customer_id: np.ndarray
    terminal_id: np.ndarray
    amount_cents: np.ndarray
    features: np.ndarray  # [n, 15]
    probs: np.ndarray  # [n]
    latency_s: float
    # Monotone engine batch counter (survives checkpoint restore): a
    # replayed batch carries the SAME index, so idempotent sinks can
    # overwrite instead of duplicating (exactly-once sink output — the
    # role of Spark's sink commit protocol).
    batch_index: int = -1


def empty_batch_result(batch_index: int) -> BatchResult:
    """A zero-row result claiming ``batch_index`` — what a batch whose
    every row was quarantined to the dead-letter queue leaves behind, so
    the sink's ``batch_index`` lineage stays gap-free."""
    return BatchResult(
        tx_id=np.empty(0, np.int64),
        tx_datetime_us=np.empty(0, np.int64),
        customer_id=np.empty(0, np.int64),
        terminal_id=np.empty(0, np.int64),
        amount_cents=np.empty(0, np.int64),
        features=np.zeros((0, N_FEATURES), np.float32),
        probs=np.empty(0, np.float32),
        latency_s=0.0,
        batch_index=int(batch_index),
    )


def validate_ingest_rows(cols: dict, detail_fn=None) -> None:
    """Strict-ingest boundary check: values that decoded structurally
    but are IMPOSSIBLE (today: negative amounts — the generator, the
    OLTP schema, and the decimal codec all make them unrepresentable on
    the legitimate path) mean a corrupt or malicious envelope. Garbage
    must never scatter into the feature state, so the batch crashes
    loudly with :class:`~.faults.PoisonRowError`; under
    :func:`~.faults.run_with_recovery` + a dead-letter sink the crash
    loop is diagnosed and exactly these rows are quarantined while the
    stream continues. One vectorized compare per batch (~free).
    ``detail_fn(bad_mask) -> str`` lets callers append attribution (the
    sharded engine names shard placements) without re-running the
    predicate — it is invoked only on failure."""
    amounts = np.asarray(cols["tx_amount_cents"])
    if len(amounts) == 0:
        return
    bad = amounts < 0
    if bad.any():
        from real_time_fraud_detection_system_tpu.runtime.faults import (
            PoisonRowError,
        )

        ids = np.asarray(cols["tx_id"])[bad]
        detail = detail_fn(bad) if detail_fn is not None else ""
        raise PoisonRowError(
            f"corrupt row(s): negative amount_cents for "
            f"{int(bad.sum())} row(s), tx_id(s) {ids[:5].tolist()}"
            + (f" ({detail})" if detail else ""))


class ScoringEngine:
    """Drives source → jitted step → sink.

    ``online_lr > 0`` enables in-step online SGD from labeled rows
    (BASELINE.json config 4) for differentiable model kinds.
    """

    def __init__(
        self,
        cfg: Config,
        kind: str,
        params,
        scaler: Scaler,
        feature_state: Optional[FeatureState] = None,
        scorer: Optional[str] = None,
        cpu_model=None,
        online_lr: float = 0.0,
        feature_cache=None,
        metrics=None,
        dead_letter=None,
    ):
        self.cfg = cfg
        self.kind = kind
        self.scorer = scorer or cfg.runtime.scorer
        self.cpu_model = cpu_model
        self.online_lr = online_lr
        # Serving z_mode, resolved ONCE at build (auto → int8 on TPU /
        # f32 elsewhere): the tree-ensemble z-contraction arithmetic the
        # jitted step closes over — so precompile() compiles, and every
        # dispatch serves, the active mode. Decision-identical to f32 by
        # the gemm_leaf_sum exactness contract (int8 additionally
        # BIT-identical; engine-level gate in make perf-smoke).
        self.z_mode = resolve_z_mode(cfg.runtime.z_mode)
        # Data-plane guard (opt-in, runtime.nan_guard): rows whose step
        # outputs cross the host boundary non-finite are quarantined to
        # the dead-letter sink and the batch is re-scored from the
        # pre-batch state WITHOUT them — a NaN never contaminates the
        # running feature state (see _quarantine_nonfinite).
        self.dead_letter = dead_letter
        self._nan_guard = bool(cfg.runtime.nan_guard)
        if self._nan_guard and dead_letter is None:
            raise ValueError(
                "runtime.nan_guard needs a dead-letter sink to quarantine "
                "into — pass dead_letter=DeadLetterSink(...) "
                "(CLI: --nan-guard requires --dead-letter)")
        # The guard needs the PRE-batch state to stay alive across the
        # step (it re-runs the batch from it on detection), so donation
        # of the feature-state buffers is disabled while it is on.
        self._donate = () if self._nan_guard else (0,)
        self._init_telemetry(metrics)
        # Tiered-store attrs exist on EVERY engine (the shared batch path
        # reads them); only the non-sequence constructor below can arm
        # them.
        self._exact = False
        self._compact_every = 0
        self._compact = None
        self._max_day = 0
        self._m_tier = None
        self._m_slots_occ = None
        self._m_slots_rec = None
        # Host cold tier (features.cold_store, key_mode="exact"): armed
        # by _init_cold below; the defaults keep every shared-path
        # getattr/None-check cheap for sequence/direct/hash engines.
        self._cold = None  # io.coldstore.ColdStore
        self._promoter = None  # io.coldstore.ColdPromoter
        self._promote = None  # jitted features.online.promote_rows
        self._demote_slots = 0
        self._cold_pending = set()  # (table, key) enqueued, not landed
        self._degraded_keys = set()  # served from CMS while cold/in-flight
        self._cold_index = {}  # table -> sorted uint32 key snapshot
        self._cold_index_version = -1
        self._cold_synced = False
        # Elastic-fleet seams (armed by the CLI, None everywhere else):
        # a threading.Event the launcher's coordinated drain sets via
        # SIGTERM — run() breaks at the next batch boundary with offsets
        # resumable — and the cross-process terminal-sketch exchange
        # (runtime.cms_exchange.SketchExchange) run at checkpoint
        # cadence.
        self.stop_event = None
        self.cms_exchange = None
        if cfg.runtime.emit_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"emit_dtype must be float32|bfloat16, "
                f"got {cfg.runtime.emit_dtype!r}")
        if kind == "sequence":
            # Long-context serving: per-customer event histories in HBM
            # scored by the causal transformer — a different state and
            # step shape, built in its own branch.
            if cfg.features.key_mode == "exact":
                raise ValueError(
                    "key_mode='exact' is the windows-plane tiered "
                    "feature store; kind='sequence' serves from its own "
                    "history state (keep key_mode direct/hash)")
            if self.scorer == "cpu":
                raise ValueError(
                    "kind='sequence' has no sklearn oracle — "
                    "--scorer cpu does not apply")
            if online_lr > 0.0:
                raise ValueError(
                    "online SGD is not wired for kind='sequence'")
            if cfg.runtime.emit_dtype != "float32":
                # the sequence scorer never transfers a feature matrix
                # (zeros, built host-side) — a bf16 request would change
                # nothing; reject rather than let the operator believe
                # D2H bytes were halved
                raise ValueError(
                    "emit_dtype='bfloat16' has no effect for "
                    "kind='sequence' (no feature matrix leaves the "
                    "device); keep float32")
            if cfg.runtime.emit_threshold > 0.0:
                # the sequence scorer's feature matrix is definitionally
                # zeros — a threshold would change nothing; reject rather
                # than let the operator believe D2H bytes were cut
                raise ValueError(
                    "emit_threshold has no effect for kind='sequence' "
                    "(no feature matrix leaves the device); keep 0")
            self._init_sequence(cfg, params, scaler, feature_state,
                                feature_cache)
            return
        # Optional runtime.feedback.FeatureCache: every scored row's raw
        # feature vector is cached for the labeled-feedback join.
        self.feature_cache = feature_cache
        if not cfg.runtime.emit_features and (
            self.scorer == "cpu" or feature_cache is not None
        ):
            raise ValueError(
                "emit_features=False (alerts-only serving) cannot be "
                "combined with --scorer cpu or a feature cache: both "
                "consume host-side feature rows")
        if cfg.runtime.emit_dtype != "float32" and (
            self.scorer == "cpu" or feature_cache is not None
        ):
            raise ValueError(
                "emit_dtype='bfloat16' is lossy on the emitted feature "
                "columns; --scorer cpu and the feedback feature cache "
                "re-consume those rows and would drift — keep float32")
        thresh = float(cfg.runtime.emit_threshold)
        if not 0.0 <= thresh <= 1.0:
            raise ValueError(
                f"emit_threshold must be in [0, 1], got {thresh}")
        if thresh > 0.0 and not cfg.runtime.emit_features:
            # same principle as the sequence-kind rejection above: never
            # let the operator believe flagged rows' features will land
            # when alerts-only mode keeps the matrix in HBM entirely
            raise ValueError(
                "emit_threshold > 0 (selective emission) contradicts "
                "emit_features=False (alerts-only): pick one")
        self._selective = thresh > 0.0
        if self._selective:
            if self.scorer == "cpu" or feature_cache is not None:
                raise ValueError(
                    "selective emission (emit_threshold > 0) cannot be "
                    "combined with --scorer cpu or a feature cache: both "
                    "consume every row's features host-side")
            if cfg.runtime.emit_dtype != "float32":
                raise ValueError(
                    "selective emission already cuts feature D2H by "
                    "~1/emit_cap_fraction; emit_dtype='bfloat16' is not "
                    "supported on the packed selective transfer — keep "
                    "float32")
            if not 0.0 < cfg.runtime.emit_cap_fraction <= 1.0:
                raise ValueError(
                    "emit_cap_fraction must be in (0, 1], got "
                    f"{cfg.runtime.emit_cap_fraction}")
        # Batches whose flagged-row count overflowed the compaction cap
        # (each fell back to a full feature fetch — correct, just slower).
        self.selective_overflows = 0
        self._feedback_step = None
        self._state_feedback_step = None
        # Tiered feature store (key_mode="exact"): the step routes slots
        # through the exact key directory, serves admission misses from
        # the sketch tier, and returns per-batch tier counts; a periodic
        # compaction step (its own DispatchSignature variant, see
        # dispatch_inventory) reclaims dead hot-tier slots.
        self._exact = cfg.features.key_mode == "exact"
        self._compact_every = (cfg.features.compact_every
                               if self._exact else 0)
        self._check_state_budget()
        self._init_state_telemetry()
        # Depth-bounded tree ensembles score ~100× faster on TPU in the GEMM
        # form (see models/forest.py::predict_proba); convert once at build.
        params = device_params_for(kind, params)
        self.state = EngineState(
            feature_state=feature_state or init_feature_state(cfg.features),
            params=params,
            scaler=scaler,
        )
        self._predict = predict_fn_for(kind, z_mode=self.z_mode)
        self._loss = loss_fn_for(kind)
        fcfg = cfg.features
        z_mode = self.z_mode

        # Both FUSED featurize→score kernels read gathered hot-tier rows
        # directly and know nothing of the sketch fallback, so the
        # tiered exact mode keeps the XLA composition (the pure predict
        # swap in _maybe_use_pallas_forest still applies — it consumes
        # the already-assembled feature matrix).
        use_pallas = (
            cfg.runtime.use_pallas
            and kind == "logreg"
            and cfg.features.customer_source == "table"
            and not self._exact
        )
        # Fused featurize→score forest step (ops/pallas_forest.py): the
        # round-9 kernel that keeps the feature block VMEM-resident past
        # the scatter boundary. Gated like the logreg fused kernel (table
        # source — the CMS query has its own sketch layout) plus, at
        # TRACE time inside the step, on GEMM-form params whose tables
        # fit the VMEM block budget — so a hot reload to an oversized or
        # descent-form ensemble retraces into the XLA composition.
        use_pallas_forest = (
            cfg.runtime.use_pallas
            and kind in ("tree", "forest")
            and cfg.features.customer_source == "table"
            and self.scorer != "cpu"
            and not self._exact
        )
        if use_pallas_forest:
            from real_time_fraud_detection_system_tpu.models.forest import (
                GemmEnsemble,
            )
            from real_time_fraud_detection_system_tpu.ops.pallas_forest \
                import admit_block, to_pallas
        self._maybe_use_pallas_forest(kind, params)

        def _fused_forest_fits(p) -> bool:
            # trace-time gate (static shapes only — see use_pallas_forest);
            # admit_block is the SAME predicate rtfdsverify proves, so the
            # served gate and the verified budget cannot drift
            return (use_pallas_forest and isinstance(p, GemmEnsemble)
                    and admit_block(p, z_mode, _PALLAS_BLOCK_BUDGET).fits)

        exact = self._exact

        def _featurize(fstate, batch):
            # one shared featurize for the non-fused branches: the tiered
            # exact path additionally returns [dense, cms] row counts
            if exact:
                return update_and_featurize_exact(fstate, batch, fcfg)
            fstate, feats = update_and_featurize(fstate, batch, fcfg)
            return fstate, feats, None

        def step(fstate: FeatureState, params, scaler: Scaler, packed):
            # One packed H2D array per batch (see core.batch.pack_batch):
            # the unpack is free bitcasts inside the fused program.
            batch = unpack_batch(packed)
            tier = None
            if use_pallas:
                fstate, probs, feats = update_and_score_pallas(
                    fstate, batch, fcfg, scaler.mean, scaler.scale,
                    params.w, params.b,
                )
                x = transform(scaler, feats)
            # rtfdslint: disable=jit-recompile-hazard (trace-time gate on STATIC facts only: isinstance on the params pytree structure + pallas_block_bytes over params' static .shape tuple — no traced VALUE is branched on, and a retrace when a hot reload changes the params FORM is the intended XLA-fallback behavior, same contract as _maybe_use_pallas_forest)
            elif _fused_forest_fits(params):
                pf = to_pallas(params, z_mode)
                fstate, leaf, feats = update_and_score_pallas_forest(
                    fstate, batch, fcfg, scaler.mean, scaler.scale, pf,
                )
                x = transform(scaler, feats)
                probs = jnp.where(batch.valid, leaf / pf.n_trees, 0.0)
            elif self.scorer == "cpu":
                # Oracle serving: the classifier runs host-side on the
                # returned features (process_batch), so don't burn device
                # time on a predict whose output is discarded.
                fstate, feats, tier = _featurize(fstate, batch)
                x = transform(scaler, feats)
                probs = jnp.zeros(batch.valid.shape, jnp.float32)
            else:
                fstate, feats, tier = _featurize(fstate, batch)
                x = transform(scaler, feats)
                probs = self._predict(params, x)
                probs = jnp.where(batch.valid, probs, 0.0)
            if self.online_lr > 0.0 and self._loss is not None:
                labeled = batch.valid & (batch.label >= 0)
                y = jnp.maximum(batch.label, 0)
                g = jax.grad(self._loss)(params, x, y, labeled)
                has = jnp.any(labeled).astype(jnp.float32)
                params = jax.tree.map(
                    lambda p, gi: p - self.online_lr * has * gi, params, g
                )
            if cfg.runtime.emit_dtype == "bfloat16":
                # halve the emitted matrix's D2H bytes; the classifier
                # above consumed the f32 features (predictions unaffected)
                feats = feats.astype(jnp.bfloat16)
            if self._selective:
                # On-device compaction: gather the flagged rows' feature
                # vectors into a fixed-capacity buffer, then pack
                # probs + count + indices + features into ONE flat f32
                # array — a batch costs a single D2H transfer (the same
                # round-trip count as alerts-only serving) instead of a
                # full [B, 15] matrix. Indices ride as f32, exact for any
                # batch ≤ 2^24 rows (max_batch_rows is 2^20). The full
                # matrix is ALSO returned (it already exists; untouched
                # HBM until fetched) as the overflow fallback.
                pad = batch.valid.shape[0]
                cap = max(8, int(pad * cfg.runtime.emit_cap_fraction))
                flagged = batch.valid & (probs >= thresh)
                idx = jnp.nonzero(flagged, size=cap, fill_value=0)[0]
                count = jnp.sum(flagged).astype(jnp.float32)
                packed_out = jnp.concatenate([
                    probs,
                    count[None],
                    idx.astype(jnp.float32),
                    feats[idx].reshape(-1),
                ])
                emit = {"packed": packed_out, "full": feats}
            else:
                emit = feats
            if exact:
                # 5th output only in the tiered mode: every engine config
                # has ONE static step arity, so the dispatch signatures
                # stay enumerable (dispatch_inventory) and AOT-coverable.
                return fstate, params, probs, emit, tier
            return fstate, params, probs, emit

        self._step = jax.jit(step, donate_argnums=self._donate)
        if self._exact:
            from real_time_fraud_detection_system_tpu.features.online \
                import compact_feature_state

            # Cold tier armed: compaction DEMOTES pressure-evicted keys'
            # rows into a fixed-shape payload (K = cold_demote_slots per
            # table) instead of discarding — one static return arity per
            # engine config, same principle as the exact 5-tuple step.
            demote = (int(fcfg.cold_demote_slots)
                      if getattr(fcfg, "cold_store", "") else 0)
            self._demote_slots = demote

            def compact(fstate: FeatureState, now_day):
                return compact_feature_state(fstate, now_day, fcfg,
                                             demote_slots=demote)

            self._compact = jax.jit(compact, donate_argnums=self._donate)
            if demote:
                self._init_cold(fcfg)

    def _init_telemetry(self, metrics) -> None:
        """Resolve the registry series ONCE at build time: the hot loop
        then pays one method call per event, never a name lookup. A
        ``FlightRecorder`` can be attached via ``self.recorder`` (the CLI
        installs a process-wide one; ``run`` falls back to it)."""
        self.recorder = None
        reg = metrics if metrics is not None else get_registry()
        self.metrics = reg
        self._m_batches = reg.counter(
            "rtfds_batches_total", "micro-batches scored")
        self._m_rows = reg.counter("rtfds_rows_total", "rows scored")
        self._m_lat = reg.histogram(
            "rtfds_batch_latency_seconds",
            "end-to-end micro-batch latency (poll wait excluded)")
        self._m_phase = {
            ph: reg.histogram(
                "rtfds_phase_seconds",
                "per-batch loop-time decomposition by phase", phase=ph)
            for ph in PHASES
        }
        self._m_last = reg.gauge(
            "rtfds_last_batch_unix_seconds",
            "wall-clock time the last batch finished (healthz input)")
        self._m_qdepth = reg.gauge(
            "rtfds_queue_depth", "micro-batches currently in flight")
        # Tracing + XLA/device telemetry: the tracer is the process-wide
        # one (disabled by default — span() is then one attribute check);
        # compile counters are process-global (the jit cache is), while
        # the recompile alarm and memory gauges honor THIS registry.
        self.tracer = get_tracer()
        install_compile_telemetry()
        self._recompile = RecompileDetector(registry=reg)
        self._devmem = DeviceMemoryTelemetry(reg)
        # AOT-precompiled step executables (see precompile()): dispatch
        # key -> jax Compiled. Empty = plain jit dispatch.
        self._aot = {}
        self._aot_params_sig = None
        self._m_precompiled = reg.counter(
            "rtfds_precompiled_steps_total",
            "step executables AOT-compiled at warmup (bucket sizes x "
            "variants)")
        self._m_aot_fallbacks = reg.counter(
            "rtfds_aot_fallbacks_total",
            "dispatches that fell back from an AOT executable to jit "
            "(input signature drifted from the precompiled one)")
        # Overlapped result fetch (runtime.fetch_overlap): D2H copies are
        # issued async the moment a step's handle resolves, so the
        # transfer runs while the loop preps/dispatches later batches.
        # The counter accumulates the head start each batch's transfer
        # got before the blocking materialization — result_wait then
        # reflects device time + residual transfer, not full transfer
        # serialization.
        self._fetch_overlap = bool(self.cfg.runtime.fetch_overlap)
        self._m_fetch_overlap = reg.counter(
            "rtfds_fetch_overlap_seconds_total",
            "seconds of D2H head start granted by async result fetch "
            "(copy_to_host_async issue to blocking materialization)")
        # Per-bucket zero feature matrices, shared read-only across
        # batches (see _zero_features).
        self._zeros_cache: dict = {}
        # Continuous-learning hooks (runtime/learner.py): a ShadowScorer
        # dual-scores emitted batches beside the champion; feedback_tap
        # hands labeled rows to the streaming learner. Both None unless
        # a LearningLoop attaches.
        self.shadow = None
        self.feedback_tap = None
        # Overload-ladder host-side degrade flags (runtime/overload.py).
        # shadow_paused gates shadow scoring without detaching it (rung
        # 1 sheds it, descent restores it); _shed_features switches to
        # alerts-only emission WITHOUT touching the compiled step — the
        # feature matrix simply stays in HBM unfetched, so every
        # dispatch remains a signature from dispatch_inventory().
        self.shadow_paused = False
        self._shed_features = False
        # Param-swap accounting (hot reload × online SGD): True once any
        # online update (in-step SGD on labeled rows, or a feedback SGD
        # step) landed since the last wholesale params swap — a reload
        # then CLOBBERS those updates, and the operator must be able to
        # count it, not read a one-time warning.
        self._online_dirty = False
        # Device-plane config gauges (healthz's device_plane block reads
        # them): which z_mode the jitted step closes over, and whether
        # the opt-in fused Pallas path is enabled.
        self._m_zmode = {
            m: reg.gauge(
                "rtfds_z_mode",
                "active tree-ensemble z-contraction mode (1 = the mode "
                "the serving step compiled with; exactness contract in "
                "README § Device plane)", mode=m)
            for m in ("f32", "bf16", "int8")
        }
        for m, g in self._m_zmode.items():
            g.set(1.0 if m == self.z_mode else 0.0)
        self._m_use_pallas = reg.gauge(
            "rtfds_use_pallas",
            "1 when the opt-in fused Pallas scoring path is enabled")
        self._m_use_pallas.set(1.0 if self.cfg.runtime.use_pallas else 0.0)
        self._m_reloads = {
            o: reg.counter(
                "rtfds_model_reloads_total",
                "hot model reloads by outcome (clobbered_online_updates "
                "= the swap discarded on-device online-SGD updates "
                "accumulated since the previous artifact)", outcome=o)
            for o in ("clean", "clobbered_online_updates")
        }

    # -- tiered feature store (key_mode="exact") ---------------------------

    def _state_shards(self) -> int:
        """Shard count the static ``state_bytes`` accounting uses: 1 for
        the single-chip engine; the sharded engine reports its mesh
        width (per-device sketch replicas multiply the cms tier)."""
        return 1

    def _check_state_budget(self) -> None:
        """``features.state_hbm_budget_mb``: fail the BUILD, not the
        stream, when the configured feature state cannot fit the budget
        (static ``state_bytes`` accounting; the same numbers bench's
        ``detail.state_scale`` reports)."""
        fcfg = self.cfg.features
        if fcfg.state_hbm_budget_mb <= 0:
            return
        sb = state_bytes(fcfg, n_shards=self._state_shards())
        budget = int(fcfg.state_hbm_budget_mb * 2 ** 20)
        if sb["total"] > budget:
            raise ValueError(
                f"feature state needs {sb['total']} bytes "
                f"(dense {sb['dense']}, directory {sb['directory']}, "
                f"cms {sb['cms']}) against a state_hbm_budget_mb="
                f"{fcfg.state_hbm_budget_mb:g} budget ({budget} bytes) — "
                "shrink the hot tier (customer_capacity/"
                "terminal_capacity), the sketch (cms_width), or raise "
                "the budget")

    def _init_state_telemetry(self) -> None:
        """Tiered-store observability (registered only when the tier
        machinery is live, so plain direct/hash runs keep /healthz
        clean; bytes gauges also register whenever a budget is set)."""
        reg = self.metrics
        fcfg = self.cfg.features
        self._m_tier = None
        self._m_slots_occ = None
        self._m_slots_rec = None
        if self._exact:
            self._m_tier = {
                t: reg.counter(
                    "rtfds_feature_tier_rows_total",
                    "row x keyspace feature reads served per tier "
                    "(dense = private hot-tier slot; cms = count-min "
                    "sketch fallback after an admission miss)", tier=t)
                for t in ("dense", "cms")
            }
            tables = (("customer", fcfg.customer_source != "cms"),
                      ("terminal", True))
            self._m_slots_occ = {
                t: reg.gauge(
                    "rtfds_feature_slots_occupied",
                    "hot-tier slots currently owned by a key "
                    "(updated at compaction cadence)", table=t)
                for t, present in tables if present
            }
            self._m_slots_rec = {
                t: reg.counter(
                    "rtfds_feature_slots_reclaimed_total",
                    "hot-tier slots reclaimed by recency compaction "
                    "(the slot held only history older than "
                    "delay + max(window))", table=t)
                for t, present in tables if present
            }
        if self._exact or fcfg.state_hbm_budget_mb > 0:
            sb = state_bytes(fcfg, n_shards=self._state_shards())
            for tier in ("dense", "directory", "cms", "total"):
                reg.gauge(
                    "rtfds_feature_state_bytes",
                    "HBM bytes of the configured feature state per tier "
                    "(static accounting, features/online.state_bytes)",
                    tier=tier).set(float(sb[tier]))
            reg.gauge(
                "rtfds_feature_state_budget_bytes",
                "configured feature-state HBM budget "
                "(state_hbm_budget_mb; 0 = unchecked)").set(
                float(fcfg.state_hbm_budget_mb * 2 ** 20))

    # -- host cold tier (features.cold_store) ------------------------------

    def _cold_tables(self) -> tuple:
        """Tables with a key directory (demotable/promotable)."""
        if self.cfg.features.customer_source == "cms":
            return ("terminal",)
        return ("customer", "terminal")

    def _init_cold(self, fcfg) -> None:
        """Arm the host cold tier: the keyed store, the async promoter
        thread, the jitted promote-merge step and its telemetry."""
        from real_time_fraud_detection_system_tpu.features.online import (
            promote_rows,
        )
        from real_time_fraud_detection_system_tpu.io.coldstore import (
            ColdPromoter,
            ColdStore,
        )

        self._cold = ColdStore(fcfg.cold_store,
                               segment_mb=fcfg.cold_segment_mb)
        self._promoter = ColdPromoter(self._cold,
                                      depth=fcfg.cold_promote_queue)

        def promote(fstate, payload):
            return promote_rows(fstate, payload, fcfg)

        self._promote = jax.jit(promote, donate_argnums=self._donate)
        reg = self.metrics
        self._m_cold_keys = reg.gauge(
            "rtfds_feature_cold_keys",
            "keys resident in the host cold tier (demoted, not yet "
            "promoted back)")
        self._m_cold_bytes = reg.gauge(
            "rtfds_feature_cold_bytes",
            "host bytes of live cold-tier segments + flush buffer")
        self._m_cold_prom = reg.counter(
            "rtfds_feature_cold_promotions_total",
            "cold-tier keys promoted back into the hot tier")
        self._m_cold_dem = reg.counter(
            "rtfds_feature_cold_demotions_total",
            "hot-tier keys demoted to the cold tier by compaction "
            "pressure eviction")
        self._m_cold_wait = reg.counter(
            "rtfds_feature_cold_promote_wait_seconds_total",
            "seconds between a returning key's promotion request and "
            "its rows landing in the hot tier")
        self._m_cold_backlog = reg.gauge(
            "rtfds_feature_cold_promote_backlog",
            "promotion requests enqueued or resolved but not yet "
            "landed on device (overload-ladder pressure input)")
        reg.gauge(
            "rtfds_feature_cold_promote_queue_limit",
            "bounded capacity of the cold promoter request queue "
            "(features.cold_promote_queue)").set(
            float(fcfg.cold_promote_queue))

    def _note_cold_touches(self, cols: dict) -> None:
        """Host-side returning-key detection: the host WROTE the cold
        store, so it knows exactly which keys are cold — intersect the
        batch's folded keys with a cached sorted snapshot of the cold
        index (rebuilt only when the index mutates) and enqueue hits to
        the promoter. No extra device output, no step-arity change, no
        stall: the rows are served from CMS this batch (counted in
        ``exactness_degraded_keys``) and converge to exact state when
        the promotion lands."""
        if self._cold is None:
            return
        ver = self._cold.version()
        if ver != self._cold_index_version:
            self._cold_index = {
                t: self._cold.index_snapshot(t)
                for t in self._cold_tables()}
            self._cold_index_version = ver
        from real_time_fraud_detection_system_tpu.core.batch import (
            fold_key,
        )

        for table, col in (("customer", "customer_id"),
                           ("terminal", "terminal_id")):
            snap = self._cold_index.get(table)
            if snap is None or not snap.size:
                continue
            ids = cols.get(col)
            if ids is None or not len(ids):
                continue
            keys = fold_key(np.asarray(ids))
            # the directory canonicalizes EMPTY_KEY collisions the same
            # way (ops/keydir._canon) — mirror it or miss those keys
            keys = np.where(keys == np.uint32(0xFFFFFFFF),
                            np.uint32(0xFFFFFFFE), keys)
            for k in np.unique(keys[np.isin(keys, snap)]):
                ki = int(k)
                self._degraded_keys.add((table, ki))
                if (table, ki) in self._cold_pending:
                    continue  # already in flight
                if self._promoter.request(table, ki):
                    self._cold_pending.add((table, ki))
                # full queue: dropped — the key re-enqueues on its
                # next touch (bounded backpressure, never unbounded)
        self._m_cold_backlog.set(float(self._promoter.backlog()))

    def _append_demotions(self, payload: dict) -> None:
        """Land one compaction pass's demotion payload in the cold
        store. Normalizes the sharded stacked ``[n_dev, K, ...]`` leaves
        to flat rows; ``EMPTY_KEY`` lanes are skipped by the store. A
        demoted key with a promotion in flight has that promotion
        CANCELLED (its resolved rows pre-date this demotion): the next
        touch re-detects and promotes the fresh rows."""
        if self._cold is None:
            return
        total = 0
        for table in ("customer", "terminal"):
            pay = payload.get(table)
            if pay is None:
                continue
            keys, bd, cnt, amt, frd = (np.asarray(x) for x in pay)
            if keys.ndim > 1:  # sharded stacked payload
                keys = keys.reshape(-1)
                bd = bd.reshape(-1, bd.shape[-1])
                cnt = cnt.reshape(-1, cnt.shape[-1])
                amt = amt.reshape(-1, amt.shape[-1])
                frd = frd.reshape(-1, frd.shape[-1])
            total += self._cold.append(table, keys, bd, cnt, amt, frd)
            for k in keys[keys != np.uint32(0xFFFFFFFF)]:
                self._cold_pending.discard((table, int(k)))
        if total:
            self._m_cold_dem.inc(total)
        self._m_cold_keys.set(float(self._cold.keys_count))
        self._m_cold_bytes.set(float(self._cold.bytes))

    def _build_promote_payload(self, rows_by_table: dict) -> dict:
        """Resolved cold rows → the ONE fixed-shape promote payload the
        compiled ``("promote",)`` signature accepts (``EMPTY_KEY``-padded
        ``[K, ...]`` per present table). The sharded engine overrides
        with owner-modulo-grouped ``[n_dev, K, ...]`` leaves."""
        k = self._demote_slots
        nb = self.cfg.features.n_day_buckets
        tables = self._cold_tables()
        payload = {}
        for table in ("customer", "terminal"):
            if table not in tables:
                payload[table] = None
                continue
            keys = np.full((k,), 0xFFFFFFFF, np.uint32)
            bd = np.full((k, nb), -1, np.int32)
            cnt = np.zeros((k, nb), np.float32)
            amt = np.zeros((k, nb), np.float32)
            frd = np.zeros((k, nb), np.float32)
            for i, (key, r) in enumerate(
                    (rows_by_table.get(table) or {}).items()):
                keys[i] = key
                bd[i], cnt[i], amt[i], frd[i] = r
            payload[table] = (keys, bd, cnt, amt, frd)
        return payload

    def _maybe_promote(self) -> None:
        """Land resolved promotions between device steps (called once
        per finished batch right after ``_maybe_compact`` — the same
        single-threaded contract). Drains the promoter's ready queue up
        to the payload width, dispatches the compiled ``("promote",)``
        signature, and retires landed keys from the cold index."""
        if self._promoter is None:
            return
        k = self._demote_slots
        ready = self._promoter.poll_ready(max_items=k)
        self._m_cold_backlog.set(float(self._promoter.backlog()))
        if not ready:
            return
        rows_by_table: dict = {"customer": {}, "terminal": {}}
        wait = 0.0
        now = time.perf_counter()
        for table, key, rows, t_enq in ready:
            if (table, key) not in self._cold_pending:
                continue  # cancelled (re-demoted mid-flight) or fenced
            self._cold_pending.discard((table, key))
            wait += now - t_enq
            if rows is None:
                continue  # corrupt/missing segment: stays on CMS, counted
            rows_by_table[table][key] = rows
        if wait > 0.0:
            self._m_cold_wait.inc(wait)
        if not any(rows_by_table.values()):
            return
        payload = self._build_promote_payload(rows_by_table)
        with self.tracer.span("state_promote"):
            with self._recompile.step(step_signature(
                    static=(self.kind, "promote"))):
                fstate, stats = self._dispatch_step(
                    ("promote",), self._promote,
                    self.state.feature_state, payload)
        self.state.feature_state = fstate
        st = np.asarray(stats).reshape(-1, 2, 2).sum(axis=0)
        self._m_cold_prom.inc(int(st[:, 0].sum()))
        for i, table in enumerate(("customer", "terminal")):
            landed = list(rows_by_table[table])
            if not landed:
                continue
            if int(st[i, 1]) == 0:
                # every lane admitted: retire the keys from the index
                # (stops re-detection; segment bytes stay until gc)
                self._cold.mark_promoted(table, landed)
            # else: the free list ran dry for some lane — keys stay
            # cold and re-promote on their next touch (the merge is
            # idempotent, so the already-admitted ones are harmless)
        self._m_cold_keys.set(float(self._cold.keys_count))
        self._m_cold_bytes.set(float(self._cold.bytes))

    def drain_promotions(self, timeout_s: float = 10.0) -> bool:
        """Block until every pending cold promotion has landed (test &
        shutdown helper — never called from the serving loop). Returns
        True when pending drained within the timeout."""
        if self._promoter is None:
            return True
        t0 = time.perf_counter()
        while self._cold_pending:
            self._maybe_promote()
            if not self._cold_pending:
                break
            if time.perf_counter() - t0 > timeout_s:
                return False
            # rtfdslint: disable=blocking-call-on-loop-thread (drain helper blocks BY CONTRACT; tests/shutdown only, never reachable from the serving loop)
            time.sleep(0.005)
        return True

    def _sync_cold_after_restore(self) -> None:
        """Adopt a restored checkpoint's cold lineage exactly once:
        prune post-checkpoint segments (replay regenerates them —
        exactly-once across the tier boundary), fence the promoter
        generation, and drop in-flight pending state."""
        if self._cold is None or self._cold_synced:
            return
        lineage = getattr(self.state, "cold_lineage", None)
        if lineage is None:
            return
        self._cold_synced = True
        self._cold.sync_to(lineage)
        topo = getattr(self, "topology", None)
        if topo is not None and topo.n_processes > 1:
            # Fleet resize seam: the adopted lineage may carry keys the
            # NEW topology homes elsewhere (a consolidated shrink-merge
            # store fanned back out, or a grown fleet adopting a
            # 1-process store). Cold keys are hot-tier directory keys —
            # already residue-foldable — so prune to this process's
            # residue block; the owning peer promotes the rest from ITS
            # copy of the store.
            dropped = self._cold.rehome(lambda _t, ks: topo.owns(ks))
            if dropped:
                from real_time_fraud_detection_system_tpu.utils import (
                    get_logger,
                )

                get_logger("engine").info(
                    "cold tier re-homed for process %d/%d: dropped %d "
                    "foreign key(s)", topo.process_id,
                    topo.n_processes, dropped)
        self._promoter.reset()
        self._cold_pending.clear()
        self._cold_index_version = -1
        self._m_cold_keys.set(float(self._cold.keys_count))
        self._m_cold_bytes.set(float(self._cold.bytes))
        self._m_cold_backlog.set(0.0)

    def checkpoint_state(self) -> EngineState:
        """The state a checkpoint save should persist. With a terminal-
        sketch exchange armed this strips adopted PEER content back out
        of ``terminal_cms`` (checkpoints always store the locals-only
        partial form, so the P→1 resize merge's same-day sketch SUM
        stays exact regardless of exchange timing); otherwise it is
        ``self.state`` itself. Dynamic lineage attrs (cold_lineage,
        resize_epochs) ride along on the shallow copy."""
        xch = self.cms_exchange
        fs = self.state.feature_state
        if xch is None or fs is None or fs.terminal_cms is None:
            return self.state
        partial = xch.checkpoint_cms(fs.terminal_cms)
        if partial is None:
            return self.state
        view = copy.copy(self.state)
        view.feature_state = fs._replace(terminal_cms=partial)
        return view

    def _maybe_exchange_cms(self) -> None:
        """Run one terminal-sketch exchange round (checkpoint cadence,
        between device steps): publish this process's cumulative local
        contributions, adopt whatever peer partials are present, and
        install the merged view back into the serving state with each
        leaf re-placed under its original sharding."""
        xch = self.cms_exchange
        fs = self.state.feature_state
        if xch is None or fs is None or fs.terminal_cms is None:
            return
        from real_time_fraud_detection_system_tpu.runtime.cms_exchange \
            import install_logical

        merged = xch.exchange(fs.terminal_cms)
        if merged is None:
            return
        new_cms = install_logical(fs.terminal_cms, merged)

        def _place(old, new):
            if new is None or old is None:
                return None
            arr = jnp.asarray(np.asarray(new), dtype=old.dtype)
            sharding = getattr(old, "sharding", None)
            return jax.device_put(arr, sharding) if sharding is not None \
                else arr

        self.state.feature_state = fs._replace(
            terminal_cms=new_cms._replace(
                slice_day=_place(fs.terminal_cms.slice_day,
                                 new_cms.slice_day),
                count=_place(fs.terminal_cms.count, new_cms.count),
                amount=_place(fs.terminal_cms.amount, new_cms.amount),
                fraud=_place(fs.terminal_cms.fraud, new_cms.fraud)))

    def _note_batch_days(self, cols: dict) -> None:
        """Track the newest day the stream has seen — compaction's
        recency cutoff input (one vectorized max per batch)."""
        if not self._compact_every:
            return
        us = cols.get("tx_datetime_us")
        if us is not None and len(us):
            from real_time_fraud_detection_system_tpu.core.batch import (
                US_PER_DAY,
            )

            self._max_day = max(self._max_day,
                                int(np.max(us) // US_PER_DAY))

    def _maybe_compact(self) -> None:
        """Run the recency-compaction step on its cadence (called once
        per finished batch, between device steps — the same
        single-threaded contract as feedback). Dispatch chains through
        ``state.feature_state`` like every step, so in-flight batches
        (dispatched earlier) are unaffected and the next batch serves
        post-compaction state."""
        if (not self._compact_every
                or self.state.batches_done % self._compact_every != 0):
            return
        day = jnp.asarray(np.int32(self._max_day))
        with self.tracer.span("state_compact", day=self._max_day):
            with self._recompile.step(step_signature(
                    day, static=(self.kind, "compact"))):
                out = self._dispatch_step(
                    ("compact",), self._compact,
                    self.state.feature_state, day)
        if self._demote_slots:
            fstate, reclaimed, payload = out
            self._append_demotions(payload)
        else:
            fstate, reclaimed = out
        self.state.feature_state = fstate
        self._record_compaction(fstate, reclaimed)

    def _record_compaction(self, fstate, reclaimed) -> None:
        """Meter one compaction pass (counters, gauges, flight event) —
        the sharded engine overrides with the per-shard breakdown."""
        rec = np.asarray(reclaimed)  # [customer, terminal]
        occupied = {}
        for i, table in enumerate(("customer", "terminal")):
            if table in self._m_slots_rec:
                self._m_slots_rec[table].inc(int(rec[i]))
            kd = getattr(fstate, f"{table}_dir")
            if kd is not None and table in self._m_slots_occ:
                # the reclaimed fetch above already synced the step, so
                # this scalar read is free
                occ = int(kd.slot_capacity) - int(np.asarray(kd.free_top))
                self._m_slots_occ[table].set(occ)
                occupied[table] = occ
        rec_now = int(rec.sum())
        recorder = self.recorder if self.recorder is not None \
            else active_recorder()
        if recorder is not None:
            tiers = {t: m.value for t, m in (self._m_tier or {}).items()}
            extra = {}
            if self._cold is not None:
                # cold-tier depth + promotion backlog ride the same
                # flight event the dashboard Feature-store tile reads
                extra = {
                    "cold_keys": int(self._cold.keys_count),
                    "cold_bytes": int(self._cold.bytes),
                    "promote_backlog": int(self._promoter.backlog()),
                }
            recorder.record_event(
                "feature_state", reclaimed=rec_now,
                occupied=sum(occupied.values()),
                capacity=sum(
                    getattr(fstate, f"{t}_dir").slot_capacity
                    for t in occupied),
                dense_rows=tiers.get("dense", 0.0),
                cms_rows=tiers.get("cms", 0.0),
                batch=self.state.batches_done, **extra)

    # -- AOT bucket precompilation ----------------------------------------

    @staticmethod
    def _sds(tree):
        """Pytree → ShapeDtypeStruct pytree for .lower() (shapes, dtypes
        and — when leaves carry one — shardings; never touches buffers,
        so donation at trace time is free)."""
        def one(x):
            sh = getattr(x, "sharding", None)
            if sh is not None:
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
            a = np.asarray(x)
            return jax.ShapeDtypeStruct(a.shape, jnp.asarray(a).dtype)

        return jax.tree.map(one, tree)

    @staticmethod
    def _params_sig(params) -> tuple:
        """(shape, dtype) fingerprint of a params tree — the facts an AOT
        step executable was compiled against. A hot model reload that
        changes it invalidates the AOT cache (jit would retrace; the
        compiled executables would just reject the call)."""
        return tuple(
            (tuple(np.shape(leaf)), str(jnp.asarray(leaf).dtype))
            for leaf in jax.tree.leaves(params)
        )

    def dispatch_inventory(self) -> "List[DispatchSignature]":
        """Enumerate EVERY dispatch signature this engine can serve.

        The single source of truth for the device plane's reachable
        program set: every micro-batch pads to a ``runtime.batch_buckets``
        size (``core.batch.bucket_size``), and the step's static facts
        (kind, z_mode, selective packing, emission dtype, donation
        layout, Pallas gating) are fixed at build — so the runtime
        dispatch key is always ``("step", 7, bucket)`` for an enumerable
        bucket. :meth:`precompile` compiles exactly this list and
        ``tools/rtfdsverify`` proves contracts over exactly this list;
        neither re-derives its own enumeration, so they cannot drift.
        """
        zmode_kinds = ("tree", "forest", "gbt")
        sigs = [
            DispatchSignature(
                key=("step", 7, int(b)),
                variant="step",
                kind=self.kind,
                z_mode=self.z_mode if self.kind in zmode_kinds else None,
                bucket=int(b),
                donate=tuple(self._donate),
                selective=bool(self._selective),
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=bool(self.cfg.runtime.use_pallas),
            )
            for b in sorted(set(self.cfg.runtime.batch_buckets))
        ]
        if self._compact_every:
            # The recency-compaction pass is part of the compiled step
            # family: ONE shape (the full state + an int32 day scalar),
            # AOT-compiled at warmup like every bucket, so the cadence
            # can fire mid-stream without a recompile. No z contraction,
            # no emission, no Pallas — the per-signature checks that key
            # on those facts correctly skip it.
            sigs.append(DispatchSignature(
                key=("compact",),
                variant="compact",
                kind=self.kind,
                z_mode=None,
                bucket=0,
                donate=tuple(self._donate),
                selective=False,
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=False,
            ))
        if self._demote_slots:
            # Cold-tier promotion landing is a compiled family member
            # too: ONE shape (the full state + the EMPTY_KEY-padded
            # [K, NB] payload per table), so an async promotion can land
            # mid-stream without a recompile or a device stall.
            sigs.append(DispatchSignature(
                key=("promote",),
                variant="promote",
                kind=self.kind,
                z_mode=None,
                bucket=0,
                donate=tuple(self._donate),
                selective=False,
                emit_dtype=self.cfg.runtime.emit_dtype,
                use_pallas=False,
            ))
        return sigs

    def _promote_payload_sds(self) -> dict:
        """Shape-only template of the promote payload (the sharded
        engine overrides with its stacked per-shard layout)."""
        k = self._demote_slots
        nb = self.cfg.features.n_day_buckets
        tables = self._cold_tables()

        def tbl():
            return (
                jax.ShapeDtypeStruct((k,), jnp.uint32),
                jax.ShapeDtypeStruct((k, nb), jnp.int32),
                jax.ShapeDtypeStruct((k, nb), jnp.float32),
                jax.ShapeDtypeStruct((k, nb), jnp.float32),
                jax.ShapeDtypeStruct((k, nb), jnp.float32),
            )

        return {t: (tbl() if t in tables else None)
                for t in ("customer", "terminal")}

    def signature_templates(self, sig: DispatchSignature) -> tuple:
        """Shape-only argument templates for ``sig`` — what
        ``signature_step(sig).lower(...)`` / ``.trace(...)`` take.
        Never touches buffers (``_sds``), so tracing is free of device
        work; callers that need runtime-exact dtypes (precompile, the
        verifier) must commit scalar param leaves to arrays first (see
        :meth:`precompile`)."""
        if sig.variant == "compact":
            return (
                self._sds(self.state.feature_state),
                jax.ShapeDtypeStruct((), jnp.int32),
            )
        if sig.variant == "promote":
            return (
                self._sds(self.state.feature_state),
                self._promote_payload_sds(),
            )
        return (
            self._sds(self.state.feature_state),
            self._sds(self.state.params),
            self._sds(self.state.scaler),
            jax.ShapeDtypeStruct((7, sig.bucket), jnp.int32),
        )

    def signature_step(self, sig: DispatchSignature):
        """The jitted callable ``sig`` dispatches to (one shared step
        for the single-chip engine plus the compaction/promotion
        variants; the sharded engine overrides with its per-variant
        builds)."""
        if sig.variant == "compact":
            return self._compact
        if sig.variant == "promote":
            return self._promote
        return self._step

    def precompile(self) -> dict:
        """AOT-compile the jitted step for EVERY enumerable signature.

        Iterates :meth:`dispatch_inventory` — the same enumeration the
        device-contract verifier proves coverage over — and
        ``.lower(...).compile()``s each signature from shape-only
        templates (no step executes, no state is touched), so a stream
        that visits a bucket size for the first time mid-serve
        dispatches a ready executable instead of paying a mid-stream
        XLA compile (969 ms measured vs 8 ms steady-state on this
        hardware). Composes with the persistent compilation cache
        (``utils.enable_compilation_cache``): a ``rtfds warmup`` run
        leaves the cache hot for later serving processes too.

        Returns a manifest (bucket sizes, variants, wall seconds) for CLI
        printing. Idempotent — already-compiled keys are skipped.
        """
        t0 = time.perf_counter()
        # Scalar leaves (python floats in some param trees) trace as weak
        # types under jit but compile strong under an SDS; commit them to
        # arrays once so runtime calls match the AOT signature.
        self.state.params = jax.tree.map(jnp.asarray, self.state.params)
        self._aot_params_sig = self._params_sig(self.state.params)
        done = []
        with self.tracer.span("precompile"):
            for sig in self.dispatch_inventory():
                if sig.key in self._aot:
                    continue
                self._aot[sig.key] = self.signature_step(sig).lower(
                    *self.signature_templates(sig)).compile()
                self._m_precompiled.inc()
                done.append(sig.bucket)
        return {
            "buckets": done,
            "variants": 1,
            "seconds": round(time.perf_counter() - t0, 3),
        }

    def _note_params_swap(self, params):
        """Hot-reload hook: keep AOT serving only while the swapped-in
        params match the precompiled shape family; otherwise drop the
        cache (fall back to jit, which retraces — slower, correct)."""
        if not self._aot:
            return params
        params = jax.tree.map(jnp.asarray, params)
        if self._params_sig(params) != self._aot_params_sig:
            from real_time_fraud_detection_system_tpu.utils import (
                get_logger,
            )

            get_logger("engine").warning(
                "model reload changed the params shape family; dropping "
                "%d AOT step executables (dispatch falls back to jit — "
                "rerun precompile/warmup for the new shapes)",
                len(self._aot))
            self._aot = {}
            self._aot_params_sig = None
        return params

    def set_shadow(self, shadow) -> None:
        """Attach a shadow scorer (``runtime/learner.ShadowScorer``): the
        candidate dual-scores every emitted batch on the SAME host
        feature rows. Needs the full f32 feature matrix host-side —
        exactly the modes the feedback loop already requires."""
        if self.kind == "sequence":
            raise ValueError(
                "shadow scoring is not wired for kind='sequence' "
                "(no host-side feature matrix to dual-score)")
        if not self.cfg.runtime.emit_features or self._selective:
            raise ValueError(
                "shadow scoring consumes every row's features host-side; "
                "it does not compose with alerts-only or selective "
                "emission")
        if self.cfg.runtime.emit_dtype != "float32":
            raise ValueError(
                "shadow scoring re-consumes the emitted features; "
                "emit_dtype='bfloat16' would drift the candidate's "
                "scores — keep float32")
        self.shadow = shadow

    def clear_shadow(self) -> None:
        self.shadow = None

    def _emit_features_now(self) -> bool:
        """Whether the feature matrix crosses to the host for the batch
        being finished: the static config gate AND the overload ladder's
        dynamic rung-2 degrade (host-side only — the compiled step is
        identical either way, the matrix just stays in HBM unfetched)."""
        return self.cfg.runtime.emit_features and not self._shed_features

    def set_degraded_emission(self, on: bool) -> bool:
        """Overload rung 2: switch to alerts-only emission at runtime.

        Refused (returns False, serving unchanged) when some consumer
        needs host-side feature rows — the cpu oracle, a feedback
        feature cache, selective emission's packed transfer, or the
        sequence kind (already alerts-shaped). Shadow scoring is not a
        blocker: the ladder pauses it at rung 1 before rung 2 can
        degrade emission, and ``_emit_result`` additionally skips it
        while features are shed."""
        if not on:
            self._shed_features = False
            return True
        ok = (self.kind != "sequence"
              and self.cfg.runtime.emit_features
              and not self._selective
              and self.scorer != "cpu"
              and self.feature_cache is None)
        self._shed_features = bool(ok)
        if not ok:
            from real_time_fraud_detection_system_tpu.utils import (
                get_logger,
            )

            get_logger("engine").info(
                "overload rung 2: alerts-only degrade not applicable to "
                "this serving mode (a host-side feature consumer is "
                "wired); batch forcing still applies")
        return self._shed_features

    def _dispatch_step(self, key, jit_fn, *args):
        """Serve from the AOT executable when one exists for ``key``;
        an input-signature rejection permanently falls back to plain jit
        dispatch for the whole cache — correctness first, the
        optimization second. Only PRE-EXECUTION rejections (TypeError/
        ValueError from the compiled call's argument check) fall back:
        they leave the donated buffers intact, so the jit retry is safe.
        A runtime failure (e.g. an XLA OOM mid-execution) propagates
        unwrapped — retrying it on possibly-donated inputs would mask
        the real error behind an 'array deleted' crash."""
        fn = self._aot.get(key) if self._aot else None
        if fn is not None:
            try:
                return fn(*args)
            except (TypeError, ValueError) as e:
                self._m_aot_fallbacks.inc()
                from real_time_fraud_detection_system_tpu.utils import (
                    get_logger,
                )

                get_logger("engine").warning(
                    "AOT step dispatch for %s rejected the call (%s: "
                    "%s); disabling the AOT cache and falling back to "
                    "jit", key, type(e).__name__, str(e)[:200])
                self._aot = {}
        return jit_fn(*args)

    def _zero_features(self, n: int) -> np.ndarray:
        """Per-bucket zero [n, 15] matrix, allocated once and shared
        READ-ONLY across batches. Alerts-only and sequence serving emit a
        definitionally-zero feature matrix every batch — reallocating it
        per batch is pure host-plane overhead (every sink consumer copies
        on use: parquet astype, memory-concat). Write-protected so an
        accidental in-place mutation fails loudly instead of silently
        editing an already-emitted BatchResult."""
        buf = self._zeros_cache.get(n)
        if buf is None:
            buf = np.zeros((n, N_FEATURES), np.float32)
            buf.setflags(write=False)
            self._zeros_cache[n] = buf
        return buf

    def _issue_host_fetch(self, probs, feats) -> Optional[float]:
        """Start device→host copies for exactly the leaves
        ``_finish_batch`` will materialize — probs unless the cpu oracle
        ignores them, the feature matrix only when it actually leaves
        the device (never under alerts-only/sequence; the packed array,
        not the full fallback matrix, under selective emission). Returns
        the issue time for overlap metering, or None when disabled or
        nothing was issued (an array without the async-copy API keeps
        its blocking fetch)."""
        if not self._fetch_overlap:
            return None
        targets = []
        if isinstance(feats, dict):
            # selective emission: the packed array ALREADY carries the
            # probs — fetching handle["probs"] too would re-pay the very
            # padded-batch transfer the packing exists to avoid
            targets.append(feats["packed"])
        else:
            if self.scorer != "cpu":
                targets.append(probs)
            if (feats is not None and self.kind != "sequence"
                    and self._emit_features_now()):
                targets.append(feats)
        issued = False
        for x in targets:
            f = getattr(x, "copy_to_host_async", None)
            if f is None:
                continue
            try:
                f()
                issued = True
            # rtfdslint: disable=broad-exception-catch (copy_to_host_async is a backend-optional API probed per leaf; ANY failure degrades to the blocking fetch — the overlap optimization must never break the fetch itself)
            except Exception:
                return None
        return time.perf_counter() if issued else None

    def _meter_fetch_overlap(self, handle: dict) -> None:
        ti = handle.pop("fetch_issue_t", None)
        if ti is not None:
            self._m_fetch_overlap.inc(
                max(0.0, time.perf_counter() - ti))

    def _maybe_use_pallas_forest(self, kind: str, params) -> None:
        """Swap the tree-ensemble scorer for the fused Pallas kernel.

        Gated on ``RuntimeConfig.use_pallas``, GEMM-form params, and the
        padded tables fitting comfortably inside VMEM
        (``ops/pallas_forest.py``). A pure predict swap: engine state (and
        checkpoints) keep the ``GemmEnsemble``, and the padded kernel
        tables are re-derived from the LIVE params inside the jitted step
        (µs of pad writes) — so a checkpoint restore that overwrites
        ``state.params`` in place is served, never a stale build-time copy.
        """
        if not self.cfg.runtime.use_pallas or self.scorer == "cpu":
            return
        if kind not in ("tree", "forest", "gbt"):
            return  # keep the pallas import lazy for non-ensemble kinds
        from real_time_fraud_detection_system_tpu.models.forest import (
            GemmEnsemble,
        )
        from real_time_fraud_detection_system_tpu.models.gbt import GBTModel
        from real_time_fraud_detection_system_tpu.ops.pallas_forest import (
            admit_block,
            pallas_leaf_sum,
            pallas_predict_proba,
            to_pallas,
        )

        budget = _PALLAS_BLOCK_BUDGET
        xla_predict = self._predict
        z_mode = self.z_mode

        if kind in ("tree", "forest") and isinstance(params, GemmEnsemble):
            def _pred(p, x):
                if admit_block(p, z_mode, budget).fits:
                    return pallas_predict_proba(to_pallas(p, z_mode), x)
                return xla_predict(p, x)
            self._predict = _pred
        elif (kind == "gbt" and isinstance(params, GBTModel)
                and isinstance(params.trees, GemmEnsemble)):
            def _pred(p, x):
                if admit_block(p.trees, z_mode, budget).fits:
                    return jax.nn.sigmoid(
                        p.base_score
                        + pallas_leaf_sum(to_pallas(p.trees, z_mode), x))
                return xla_predict(p, x)
            self._predict = _pred

    def _init_sequence(self, cfg, params, scaler, feature_state,
                       feature_cache):
        """kind='sequence' setup: HistoryState + fused history step.

        The emitted feature matrix is all-zeros ([n, 15]) — the sequence
        scorer consumes raw event channels, not the engineered features;
        the analyzed schema stays stable for sinks/queries."""
        from real_time_fraud_detection_system_tpu.features.history import (
            init_history_state,
            update_and_score,
        )

        if feature_cache is not None:
            # FeedbackLoop scatters into FeatureState.terminal risk
            # windows, which a HistoryState does not have
            raise ValueError(
                "the labeled-feedback loop is not wired for "
                "kind='sequence'")
        self.feature_cache = None
        self._feedback_step = None
        self._state_feedback_step = None
        self._selective = False
        self.selective_overflows = 0
        self.state = EngineState(
            feature_state=feature_state or init_history_state(cfg.features),
            params=params,
            scaler=scaler,
        )
        self._predict = None
        self._loss = None
        fcfg = cfg.features

        def step(hstate, params, scaler, packed):
            batch = unpack_batch(packed)
            hstate, probs = update_and_score(hstate, params, batch, fcfg)
            feats = jnp.zeros((batch.size, N_FEATURES), jnp.float32)
            return hstate, params, probs, feats

        self._step = jax.jit(step, donate_argnums=self._donate)

    def _start_batch(self, cols: dict) -> dict:
        """Host prep + async device dispatch (does NOT block on results).

        The returned handle holds device futures; :meth:`_finish_batch`
        materializes them. Splitting the two lets :meth:`run` stage batch
        N+1's H2D transfer and dispatch while batch N still computes —
        the double-buffered overlap of SURVEY §2.3 item 3.
        """
        t0 = time.perf_counter()
        # Latest-wins dedup by tx_id (reference ROW_NUMBER/MERGE semantics,
        # kafka_s3_sink_transactions.py:173-222) on host — tx_ids are
        # int64. The C++ path (native/hostprep.cc) is the same math in
        # one O(n) hash pass + one fused pack pass, bit-identical
        # (differential-pinned); it lifts the host ceiling past what a
        # locally attached chip can consume. NumPy is the fallback.
        with self.tracer.span("host_prep"):
            use_native = native.hostprep_available()
            keep = latest_wins_mask_host(cols["tx_id"], cols["kafka_ts_ms"])
            cols = {k: v[keep] for k, v in cols.items()}
            validate_ingest_rows(cols)
            n = len(cols["tx_id"])
            pad = bucket_size(n, self.cfg.runtime.batch_buckets)
            if use_native:
                packed = native.pack_rows(
                    cols["tx_datetime_us"], cols["customer_id"],
                    cols["terminal_id"], cols["tx_amount_cents"],
                    cols.get("label"), pad,
                )
            else:
                packed = pack_batch(make_batch(
                    customer_id=cols["customer_id"],
                    terminal_id=cols["terminal_id"],
                    tx_datetime_us=cols["tx_datetime_us"],
                    amount_cents=cols["tx_amount_cents"],
                    label=cols.get("label"),
                    pad_to=pad,
                ))
            # t1 sits after ALL host packing on both paths, so
            # prep_s/dispatch_s attribute the same stages either way
            t1 = time.perf_counter()
        pre_state = None
        if self._nan_guard:
            # Donation is off under the guard, so these references stay
            # valid after the step — the rollback anchor for a re-score
            # without the non-finite rows.
            pre_state = (self.state.feature_state, self.state.params,
                         self.state.batches_done, self.state.rows_done)
        with self.tracer.span("dispatch", rows=n, pad=pad):
            jbatch = jnp.asarray(packed)
            # Steady-state recompile alarm: the signature keys on what
            # the jit cache keys on from the engine's side — the packed
            # batch's (shape, dtype) bucket plus the step's static facts
            # (kind, donation layout, z_mode). A compile observed inside
            # this window after warmup is a retrace paid in the serving
            # loop.
            with self._recompile.step(step_signature(
                    jbatch, static=(self.kind, "donate0", self.z_mode))):
                out = self._dispatch_step(
                    ("step",) + tuple(jbatch.shape), self._step,
                    self.state.feature_state, self.state.params,
                    self.state.scaler, jbatch,
                )
            fstate, params, probs, feats = out[:4]
            tier = out[4] if self._exact else None
            self.state.feature_state = fstate
            self.state.params = params
            self._note_batch_days(cols)
            self._note_cold_touches(cols)
            # Start the D2H copies NOW (they queue behind the step's
            # compute): by the time _finish_batch blocks, the transfer
            # has been running since compute finished.
            t_fetch = self._issue_host_fetch(probs, feats)
            t2 = time.perf_counter()
        return {"cols": cols, "n": n, "probs": probs, "feats": feats,
                "tier": tier, "t0": t0, "prep_s": t1 - t0,
                "dispatch_s": t2 - t1, "pre_state": pre_state,
                "fetch_issue_t": t_fetch}

    def _finish_batch(self, handle: dict) -> BatchResult:
        """Block on the handle's device futures; build the BatchResult."""
        n = handle["n"]
        self._meter_fetch_overlap(handle)
        if self._selective:
            probs_np, feats_np = self._unpack_selective(handle)
            return self._finish_result(handle, probs_np, feats_np)
        if not self._emit_features_now() or self.kind == "sequence":
            # alerts-only mode (configured, or the overload ladder's
            # rung-2 degrade): the feature matrix stays in HBM. The
            # sequence scorer's matrix is definitionally zeros (raw event
            # channels replace engineered features) — never worth a D2H,
            # and the host-side filler is a shared read-only buffer.
            feats_np = self._zero_features(n)
        else:
            # astype: under emit_dtype="bfloat16" the transfer was bf16
            # (half the bytes); widen back for sinks/consumers
            feats_np = np.asarray(handle["feats"])[:n].astype(
                np.float32, copy=False)
        if self.scorer == "cpu":
            # parity/baseline oracle: host-side pipeline on the same features
            # (sklearn pipeline, or a TrainedModel's pure-NumPy path)
            fn = getattr(self.cpu_model, "predict_proba_np", None) or (
                self.cpu_model.predict_proba
            )
            probs_np = fn(feats_np.astype(np.float64))
        else:
            probs_np = np.asarray(handle["probs"])[:n]
        return self._finish_result(handle, probs_np, feats_np)

    def _finish_result(self, handle: dict, probs_np: np.ndarray,
                       feats_np: np.ndarray) -> BatchResult:
        """Host-boundary tail shared by every materialize path: run the
        non-finite guard (when on), then emit."""
        if self._nan_guard:
            res = self._quarantine_nonfinite(handle, probs_np, feats_np)
            if res is not None:
                return res
        return self._emit_result(handle, probs_np, feats_np)

    def _quarantine_nonfinite(self, handle: dict, probs_np: np.ndarray,
                              feats_np: np.ndarray):
        """The opt-in data-plane guard (``runtime.nan_guard``): rows whose
        score or emitted feature vector crossed the host boundary
        non-finite are routed to the dead-letter queue
        (``reason=nonfinite``) and the batch is re-scored from the
        pre-batch state WITHOUT them — so a NaN/Inf never lands in the
        running window aggregates, where it would silently poison every
        later batch for that customer/terminal. Returns the clean
        re-scored BatchResult, or None when the batch was already clean.
        Note the guard sees only what crosses the boundary: under
        alerts-only serving that is the scores alone."""
        n = handle["n"]
        bad = ~np.isfinite(probs_np[:n])
        if feats_np is not None and feats_np.shape[0] >= n:
            bad |= ~np.isfinite(feats_np[:n]).all(axis=1)
        if not bad.any():
            return None
        cols = handle["cols"]
        bad_idx = np.flatnonzero(bad)
        self.dead_letter.put_rows(
            {k: np.asarray(v)[bad_idx] for k, v in cols.items()},
            reason="nonfinite",
            error="non-finite feature/score at the host boundary",
            batch_index=self.state.batches_done + 1,
            trace_id=handle.get("trace_id") or "",
        )
        from real_time_fraud_detection_system_tpu.utils import get_logger

        get_logger("engine").warning(
            "nan-guard: %d/%d row(s) produced non-finite outputs; "
            "quarantined to the dead-letter queue and re-scoring the "
            "batch without them", len(bad_idx), n)
        # Roll the engine back to the pre-batch anchor (donation is off
        # under the guard, so the references are intact) and re-run.
        fs, params, b_done, r_done = handle["pre_state"]
        self.state.feature_state = fs
        self.state.params = params
        self.state.batches_done = b_done
        self.state.rows_done = r_done
        good = np.flatnonzero(~bad)
        if len(good) == 0:
            self.state.batches_done += 1
            res = empty_batch_result(self.state.batches_done)
            res.latency_s = time.perf_counter() - handle["t0"] \
                - handle.get("waited", 0.0)
            return res
        h2 = self._start_batch(
            {k: np.asarray(v)[good] for k, v in cols.items()})
        for key in ("index", "trace_id", "source_offsets", "waited", "t0"):
            if key in handle:
                h2[key] = handle[key]
        # recurses through the guard: terminates because each pass
        # strictly shrinks the surviving row set
        return self._finish_batch(h2)

    def _unpack_selective(self, handle: dict) -> tuple:
        """Decode the packed selective-emission transfer.

        One flat f32 fetch carries [probs(pad) | count(1) | idx(cap) |
        feats(cap·15)]. Flagged rows' feature vectors land bit-identical
        to full emission (they ride the packed array as raw f32); rows
        below the threshold carry zeros. A count above the compaction cap
        falls back to fetching that batch's full matrix — still on device
        precisely for this — so correctness never depends on the cap.
        """
        n = handle["n"]
        em = handle["feats"]
        pad = em["full"].shape[0]
        cap = (em["packed"].shape[0] - pad - 1) // (1 + N_FEATURES)
        flat = np.asarray(em["packed"])
        # copy: a view into the packed fetch would pin the whole
        # pad+1+(1+15)·cap f32 buffer (~MBs/batch at the 262k big-batch
        # cap) for as long as any sink retains BatchResult.probs
        probs_np = flat[:n].copy()
        count = int(flat[pad])
        feats_np = np.zeros((n, N_FEATURES), np.float32)
        if count > cap:
            self.selective_overflows += 1
            feats_np = np.asarray(em["full"])[:n].astype(
                np.float32, copy=False)
        elif count:
            idx = flat[pad + 1:pad + 1 + count].astype(np.int64)
            sel = flat[pad + 1 + cap:pad + 1 + cap + count * N_FEATURES]
            feats_np[idx] = sel.reshape(count, N_FEATURES)
        return probs_np, feats_np

    def _emit_result(self, handle: dict, probs_np: np.ndarray,
                     feats_np: np.ndarray) -> BatchResult:
        """Shared result tail: feature-cache put, counters, BatchResult."""
        cols = handle["cols"]
        n = handle["n"]
        if self.feature_cache is not None and n:
            from real_time_fraud_detection_system_tpu.core.batch import (
                US_PER_DAY,
            )

            in_band = cols.get("label")
            self.feature_cache.put_batch(
                cols["tx_id"], feats_np,
                terminal_ids=cols["terminal_id"],
                days=(cols["tx_datetime_us"] // US_PER_DAY).astype(np.int32),
                # In-band labels were already scattered into the risk state
                # by the step; mark them so feedback events can't re-land.
                labeled=(np.asarray(in_band) >= 0)
                if in_band is not None else None,
            )
        if self.shadow is not None and not self.shadow_paused and n:
            # Dual-score the SAME host feature rows with the candidate
            # (runtime/learner.ShadowScorer): one extra jitted predict on
            # a bucket-padded copy — the serving step's compiled program
            # is untouched, so shadow mode can never recompile it.
            with self.tracer.span("shadow_score",
                                  batch=handle.get("trace_id")):
                self.shadow.score_batch(cols["tx_id"], feats_np, probs_np)
        if (self.online_lr > 0.0 and self._loss is not None
                and cols.get("label") is not None
                and (np.asarray(cols["label"]) >= 0).any()):
            # in-step online SGD consumed this batch's in-band labels:
            # the on-device params now lead the last published artifact
            self._online_dirty = True
        tier = handle.get("tier")
        if tier is not None and self._m_tier is not None:
            # [dense, cms] row x keyspace admissions this batch; the
            # step already materialized, so this tiny fetch is free
            t = np.asarray(tier)
            self._m_tier["dense"].inc(float(t[0]))
            self._m_tier["cms"].inc(float(t[1]))
        self.state.batches_done += 1
        self.state.rows_done += n
        self._m_batches.inc()
        self._m_rows.inc(n)
        self._m_last.set(time.time())
        self._maybe_compact()
        self._maybe_promote()
        # Device-memory gauges ride the batch cadence; on backends
        # without memory stats (CPU) this is a single boolean check.
        self._devmem.sample()
        res = BatchResult(
            tx_id=cols["tx_id"],
            tx_datetime_us=cols["tx_datetime_us"],
            customer_id=cols["customer_id"],
            terminal_id=cols["terminal_id"],
            amount_cents=cols["tx_amount_cents"],
            features=feats_np,
            probs=probs_np,
            latency_s=(
                time.perf_counter() - handle["t0"]
                - handle.get("waited", 0.0)
            ),
            batch_index=self.state.batches_done,
        )
        self._m_lat.observe(res.latency_s)
        return res

    def _ensure_layout(self) -> None:
        """Adopt a restored checkpoint written at a different device
        count: ``state.layout_devices`` records the writer's width, and
        the slot layouts are shape-identical permutations — so convert
        (exactly, via the elastic reshard) rather than serve silently
        permuted state."""
        n_old = int(getattr(self.state, "layout_devices", 1) or 1)
        if n_old == 1:
            return
        from real_time_fraud_detection_system_tpu.parallel.mesh import (
            reshard_engine_state,
        )

        self.state.feature_state = jax.tree.map(
            jnp.asarray,
            reshard_engine_state(self.kind, self.state.feature_state,
                                 self.cfg, n_old, 1))
        self.state.layout_devices = 1

    def process_batch(self, cols: dict) -> BatchResult:
        """One micro-batch: dedup → pad → device step → host result."""
        self._ensure_layout()
        tid = self.tracer.begin_batch(self.state.batches_done + 1)
        handle = self._start_batch(cols)
        with self.tracer.span("result_wait", batch=tid):
            return self._finish_batch(handle)

    @property
    def supports_online_sgd(self) -> bool:
        """True for model kinds with a gradient path (logreg/mlp/autoencoder)."""
        return self._loss is not None

    def apply_state_feedback(
        self,
        terminal_ids: np.ndarray,
        days: np.ndarray,
        labels: np.ndarray,
    ) -> None:
        """Land delayed fraud labels in the terminal risk windows.

        The in-state analogue of the reference's delayed terminal-risk
        computation (``feature_transformation.ipynb · cell 25``): fraud
        sums of PAST day buckets change; delay-shifted queries pick them
        up. Model-independent (works for tree kinds too). No-op rows:
        label < 0 (pending) and buckets whose ring slot has already
        advanced past the transaction's day.
        """
        from real_time_fraud_detection_system_tpu.core.batch import fold_key
        from real_time_fraud_detection_system_tpu.features.online import (
            apply_feedback as state_feedback,
        )

        # labels scatter by slot math — a restored cross-width state must
        # convert BEFORE any scatter, same as the scoring entry points
        self._ensure_layout()
        labels = np.asarray(labels)
        mask = labels >= 0
        if not mask.any():
            return
        if self._state_feedback_step is None:
            fcfg = self.cfg.features

            def sf(fstate, term_key, day, label, valid):
                return state_feedback(
                    fstate, term_key, day, label, valid, fcfg
                )

            self._state_feedback_step = jax.jit(sf, donate_argnums=(0,))
        biggest = max(self.cfg.runtime.batch_buckets)
        t_ids = np.asarray(terminal_ids)[mask]
        d = np.asarray(days)[mask]
        y = labels[mask]
        for s in range(0, len(y), biggest):
            n = len(y[s : s + biggest])
            pad = bucket_size(n, self.cfg.runtime.batch_buckets)
            tk = np.zeros(pad, dtype=np.uint32)
            tk[:n] = fold_key(t_ids[s : s + n])
            dd = np.zeros(pad, dtype=np.int32)
            dd[:n] = d[s : s + n]
            yy = np.zeros(pad, dtype=np.int32)
            yy[:n] = y[s : s + n]
            valid = np.zeros(pad, dtype=bool)
            valid[:n] = True
            self.state.feature_state = self._state_feedback_step(
                self.state.feature_state, jnp.asarray(tk), jnp.asarray(dd),
                jnp.asarray(yy), jnp.asarray(valid),
            )

    def apply_feedback(self, features: np.ndarray, labels: np.ndarray) -> None:
        """One SGD step from delayed labels (the feedback-topic path,
        BASELINE.json config 4; see ``runtime/feedback.py``).

        ``features`` are RAW feature rows (as cached by the scorer);
        scaling happens inside the jitted update with the engine's scaler,
        so the gradient is on exactly the serving representation.
        """
        if self._loss is None:
            raise ValueError(
                f"model kind {self.kind!r} has no gradient path for "
                "feedback updates"
            )
        lr = self.online_lr or self.cfg.train.online_learning_rate
        if self._feedback_step is None:
            loss = self._loss

            def fb(params, scaler, x_raw, y, valid, lr):
                # Backtracking step: the raw serving features can carry
                # large magnitudes (amounts in cents), so a fixed lr can
                # OVERSHOOT — one step that makes the loss worse, and
                # re-deliveries would compound it. Returning the loss at
                # both ends lets the host halve lr until the step
                # CONTRACTS (classic Armijo-style backtracking); a step
                # that cannot contract is skipped entirely, so the
                # feedback loop is monotone non-increasing by
                # construction.
                x = transform(scaler, x_raw)
                l0 = loss(params, x, y, valid)
                g = jax.grad(loss)(params, x, y, valid)
                new = jax.tree.map(lambda p, gi: p - lr * gi, params, g)
                l1 = loss(new, x, y, valid)
                return new, l0, l1

            self._feedback_step = jax.jit(fb)
        labels = np.asarray(labels)
        total = len(labels)
        if total == 0:
            return
        # A label backlog can exceed the largest jit bucket: chunk it.
        biggest = max(self.cfg.runtime.batch_buckets)
        for s in range(0, total, biggest):
            lab = labels[s : s + biggest]
            n = len(lab)
            pad = bucket_size(n, self.cfg.runtime.batch_buckets)
            x = np.zeros((pad, features.shape[1]), dtype=np.float32)
            x[:n] = features[s : s + n]
            y = np.zeros(pad, dtype=np.int32)
            y[:n] = np.maximum(lab, 0)
            valid = np.zeros(pad, dtype=bool)
            # label < 0 is the 'unlabeled' sentinel everywhere in this
            # codebase (engine step masks it the same way) — never train
            # on it.
            valid[:n] = lab >= 0
            if not valid.any():
                continue
            jx, jy, jv = jnp.asarray(x), jnp.asarray(y), jnp.asarray(valid)
            step_lr = float(lr)
            for _ in range(8):  # halvings; lr is a traced arg: no retrace
                new_params, l0, l1 = self._feedback_step(
                    self.state.params, self.state.scaler, jx, jy, jv,
                    jnp.float32(step_lr),
                )
                if bool(l1 <= l0):
                    self.state.params = new_params
                    # the on-device params now lead the last published
                    # artifact: a wholesale reload would clobber this
                    self._online_dirty = True
                    break
                step_lr *= 0.5
            # 8 failed halvings: the chunk cannot contract from here
            # (already at a minimum for these labels) — skip it rather
            # than apply a step that provably makes the model worse

    def run(
        self,
        source,
        sink=None,
        max_batches: int = 0,
        checkpointer=None,
        trigger_seconds: Optional[float] = None,
        heartbeat=None,
        feedback=None,
        model_reload=None,
        learning=None,
    ) -> dict:
        """Stream until the source is exhausted (or max_batches).

        ``feedback`` (a :class:`~.feedback.FeedbackLoop`) is polled once
        per finished batch, BETWEEN device steps — the single-threaded
        contract the loop requires (its updates touch
        ``state.params``/``state.feature_state``). This closes BASELINE
        config 4 in serving: delayed fraud labels land in the terminal
        risk windows and (for differentiable models) drive online SGD
        while the stream keeps scoring.

        The loop is software-pipelined to ``runtime.pipeline_depth``
        batches in flight: batch N+k is polled, host-prepped,
        ``device_put`` and dispatched while batch N's device step still
        runs — H2D and dispatch overhead overlap compute (SURVEY §2.3
        item 3; depth 2 is classic double-buffering, deeper depths keep
        the device fed when per-dispatch overhead such as a remote-tunnel
        RTT exceeds step compute). ``runtime.coalesce_rows`` further
        merges consecutive polls into one device batch. The pipeline
        drains to depth 0 before every checkpoint save, so a saved
        (offsets, state) pair never includes an in-flight batch's effects
        (a replay after restore would double-apply them otherwise).

        ``heartbeat`` (a :class:`~.faults.Heartbeat`) is beaten once per
        loop pass — including idle polls — so a watchdog can tell a quiet
        stream from a silently hung source or device step.

        Returns run stats (rows, batches, throughput, latency percentiles).
        """
        self._ensure_layout()  # cross-width checkpoint restores convert
        # Restored state carries cold-tier segment lineage: reconcile the
        # host store to it (prune post-checkpoint segments, fence the
        # promoter) BEFORE any batch can touch a demoted key.
        self._sync_cold_after_restore()
        if self.cfg.runtime.precompile and not self._aot:
            # AOT bucket precompilation: every bucket size compiles NOW,
            # before the first poll — no first-touch compile ever lands
            # mid-stream (rtfds_xla_recompiles_total stays 0).
            self.precompile()
        if learning is not None:
            # Continuous-learning controller (runtime/learner.py):
            # installs the shadow scorer + learner tap now, then gets
            # polled once per finished batch (after feedback, before the
            # checkpoint — the same between-device-steps contract).
            learning.attach(self)
        trigger = (
            self.cfg.runtime.trigger_seconds
            if trigger_seconds is None
            else trigger_seconds
        )
        every = self.cfg.runtime.checkpoint_every_batches
        # The nan-guard's rollback-and-rescore is only sound when no later
        # batch has been dispatched from the (possibly contaminated)
        # state — the guard serializes the pipeline. Documented cost of
        # the opt-in.
        depth = 1 if self._nan_guard else max(
            1, self.cfg.runtime.pipeline_depth)
        coalesce = self.cfg.runtime.coalesce_rows
        # Per-run percentile trackers (bounded reservoirs, exact within
        # the window) — the run-report twin of the process-lifetime
        # rtfds_phase_seconds registry histograms.
        trackers = {
            "latency": LatencyTracker(),
            "host_prep": LatencyTracker(),
            "dispatch": LatencyTracker(),
            "result_wait": LatencyTracker(),
            "sink_write": LatencyTracker(),
        }
        auto = None
        if self.cfg.runtime.autobatch:
            from real_time_fraud_detection_system_tpu.runtime.autobatch \
                import AutoBatchController

            auto = AutoBatchController(
                self.cfg.runtime.batch_buckets,
                latency_slo_ms=self.cfg.runtime.latency_slo_ms,
                registry=self.metrics)
        recorder = self.recorder if self.recorder is not None \
            else active_recorder()
        overload = None
        if self.cfg.runtime.overload.enabled:
            # Overload-survival ladder (runtime/overload.py): the
            # controller decides from registry signals; these closures
            # are the engine-side effects of each rung, all reversible.
            from real_time_fraud_detection_system_tpu.runtime.overload \
                import LadderActions, OverloadController

            ocfg = self.cfg.runtime.overload

            def _act_shed_optional(on: bool) -> None:
                # rung 1: optional work off the stream — shadow scoring
                # and learner training pause through their existing
                # hooks; the flight recorder thins to sampled records
                self.shadow_paused = bool(on)
                if learning is not None:
                    if on:
                        learning.pause()
                    else:
                        learning.resume()
                if recorder is not None:
                    recorder.set_sample_every(
                        ocfg.recorder_sample_every if on else 1)

            def _act_degrade_emission(on: bool) -> None:
                # rung 2: alerts-only emission, host-side only (the
                # compiled step — and dispatch_inventory() — unchanged)
                self.set_degraded_emission(on)

            def _act_force_max(on: bool) -> None:
                # rung 2: pin autobatch to the largest AOT bucket
                if auto is not None:
                    if on:
                        auto.force_max()
                    else:
                        auto.release_force()

            overload = OverloadController(
                self.cfg.runtime, registry=self.metrics,
                actions=LadderActions(
                    shed_optional=_act_shed_optional,
                    degrade_emission=_act_degrade_emission,
                    force_max_batch=_act_force_max),
                recorder_fn=lambda: recorder)
        phase_hist = self._m_phase
        # Source-poll time since the last finished batch — attributed to
        # the NEXT batch's flight record so per-batch phases sum to the
        # loop's wall time (minus trigger pacing, reported separately).
        pending = {"poll_s": 0.0}
        t_start = time.perf_counter()
        # CPU time of the serving loop proper (precompile excluded —
        # the AOT block above ran before this line). rows / cpu_s is the
        # load-immune per-process rate the multihost scaling bench
        # gates on: on shared CI cores, wall-clock rows/s of N
        # concurrent processes measures the box, not the coordination
        # cost this repo is accountable for.
        t_cpu0 = time.process_time()
        rows0 = self.state.rows_done  # report THIS run's throughput, not
        batches0 = self.state.batches_done  # lifetime totals (warmup runs)
        ovf0 = self.selective_overflows
        degraded0 = len(self._degraded_keys)
        from collections import deque

        # rtfdslint: disable=unbounded-queue (loop-local in-flight handle FIFO, drained to below pipeline_depth on every dispatch (`while len(q) >= depth: _finish`) — bounded at `depth` by construction; a maxlen would silently drop dispatched device work)
        q: deque = deque()  # in-flight batch handles, FIFO
        if feedback is not None and checkpointer is not None:
            # Feedback offsets must TRAIL the state checkpoint (the same
            # invariant as the source commit below): defer the loop's
            # broker commits to the checkpoint cadence.
            feedback.auto_commit = False

        def _finish(handle: dict) -> None:
            t_block = time.perf_counter()
            # explicit batch= : with pipeline_depth > 1 this handle's
            # trace id is OLDER than the tracer's current batch
            with self.tracer.span("result_wait",
                                  batch=handle.get("trace_id")):
                res = self._finish_batch(handle)
            # Loop-time decomposition: host prep (dedup + pad) vs H2D +
            # dispatch (the per-step overhead pipelining hides) vs the
            # result wait (device compute minus overlap).
            prep_s = handle.get("prep_s", 0.0)
            dispatch_s = handle.get("dispatch_s", 0.0)
            wait_s = time.perf_counter() - t_block
            trackers["host_prep"].record(prep_s)
            trackers["dispatch"].record(dispatch_s)
            trackers["result_wait"].record(wait_s)
            trackers["latency"].record(res.latency_s, rows=len(res.tx_id))
            phase_hist["host_prep"].observe(prep_s)
            phase_hist["dispatch"].observe(dispatch_s)
            phase_hist["result_wait"].observe(wait_s)
            self.state.offsets = handle["source_offsets"]
            sink_s = 0.0
            if sink is not None:
                # With an AsyncSink this measures the ENQUEUE (plus any
                # backpressure block) — the loop thread's actual cost;
                # the write itself runs on the sink's writer thread and
                # reports through rtfds_sink_write_seconds.
                t_sink = time.perf_counter()
                with self.tracer.span("sink_write",
                                      batch=handle.get("trace_id")):
                    sink.append(res)
                sink_s = time.perf_counter() - t_sink
                phase_hist["sink_write"].observe(sink_s)
                trackers["sink_write"].record(sink_s)
            if auto is not None:
                auto.observe(len(res.tx_id), res.latency_s)
            if overload is not None:
                rr = handle.pop("overload_replay_rows", None)
                if rr is not None:
                    # counted at FINISH: replay accounting reflects
                    # state updates that landed, not dispatches
                    overload.note_replayed(rr)
                overload.observe_batch(len(res.tx_id), res.latency_s)
            if recorder is not None:
                extra = {}
                if handle.get("trace_id"):
                    # cross-reference: a slow batch in the flight record
                    # names its span waterfall in the exported trace
                    extra["trace_id"] = handle["trace_id"]
                recorder.record_batch(
                    res.batch_index, len(res.tx_id),
                    {"source_poll": pending["poll_s"],
                     "host_prep": prep_s, "dispatch": dispatch_s,
                     "result_wait": wait_s, "sink_write": sink_s},
                    queue_depth=len(q), latency_s=res.latency_s, **extra,
                )
                pending["poll_s"] = 0.0
            if feedback is not None:
                # Between-batch label application (before the checkpoint,
                # so saved state includes the landed labels).
                applied = feedback.poll_and_apply()
                if recorder is not None and applied:
                    recorder.record_event("feedback", applied=applied,
                                          batch=res.batch_index)
            if model_reload is not None:
                # Hot model swap (the reference restarts the Spark job to
                # pick up a retrained pickle; here the loop swaps weights
                # between device steps — same single-threaded contract as
                # feedback). The callable returns None (no change) or
                # (params, scaler) ready for the engine's kind; a shape
                # change simply retraces the jitted step. Eventual-swap
                # semantics: up to pipeline_depth batches already in
                # flight complete on the old weights.
                swap = model_reload()
                if swap is not None:
                    new_params, new_scaler = swap
                    # Reload × online SGD: a wholesale swap discards any
                    # on-device SGD updates accumulated since the last
                    # swap/artifact. That used to be a one-time startup
                    # warning; now EVERY swap is counted by outcome, so
                    # the operator can see exactly how many reloads
                    # clobbered learned updates.
                    outcome = ("clobbered_online_updates"
                               if self._online_dirty else "clean")
                    self._m_reloads[outcome].inc()
                    self._online_dirty = False
                    self.state.params = self._note_params_swap(new_params)
                    if new_scaler is not None:
                        self.state.scaler = new_scaler
                    if recorder is not None:
                        recorder.record_event("model_reload",
                                              outcome=outcome)
                    if learning is not None:
                        # a reload is a versioned event: register the
                        # swapped params in the registry lineage
                        # (publish + promote, source=reload)
                        learning.note_external_swap(
                            self.state.params, self.state.scaler, outcome,
                            engine=self)
            if learning is not None:
                # candidate install / promotion / rollback decisions ride
                # the batch cadence, between device steps
                learning.on_batch(self)
            if checkpointer is not None and self.state.batches_done % every == 0:
                # Drain an async sink BEFORE the state save: checkpointed
                # offsets must TRAIL durable sink output (a crash then
                # replays rows into parts that already landed — the
                # exactly-once overwrite — never records progress for
                # writes still sitting in a queue).
                drain = getattr(sink, "drain", None)
                if drain is not None:
                    drain()
                if self._cold is not None:
                    # Buffered demotions become durable segments NOW so
                    # the lineage the checkpoint records is on disk, and
                    # restore can rebuild the exact cold index from
                    # manifests alone.
                    self._cold.flush()
                    self.state.cold_lineage = self._cold.lineage()
                self._maybe_exchange_cms()
                checkpointer.save(self.checkpoint_state())
                # Broker-side offsets (sources that have them, e.g. Kafka)
                # are committed only AFTER the framework checkpoint lands:
                # they trail it, never lead, so a crash replays — never
                # skips — rows. Same for consumed feedback labels.
                commit = getattr(source, "commit", None)
                if commit is not None:
                    commit()
                if feedback is not None:
                    feedback.commit()
                if self._cold is not None:
                    # Only after the checkpoint (and its offset commits)
                    # landed is it safe to delete fully-promoted
                    # segments: a crash before this point restores a
                    # lineage that still lists them.
                    self._cold.gc()
            # NOTE: trigger pacing used to sleep HERE, once per finished
            # handle — so _drain() stacked one sleep per queued batch
            # before every checkpoint/idle flush. Pacing now happens once
            # per loop pass on the poll side (see the main loop).

        def _add_wait(dt: float) -> None:
            # Waiting for the NEXT batch to arrive is not part of any
            # in-flight batch's processing latency — subtract it so the
            # reported percentiles (and trigger pacing) measure the
            # pipeline, not source quiescence.
            for h in q:
                h["waited"] = h.get("waited", 0.0) + dt

        def _drain() -> None:
            while q:
                _finish(q.popleft())

        def _poll():
            t_poll = time.perf_counter()
            # Attribute the poll to the batch that will CONSUME it (the
            # same next-batch attribution the flight record uses via
            # pending["poll_s"]): begin_batch(idx) only runs after the
            # poll returns, so the current trace id here is still the
            # PREVIOUS batch's.
            nid = (f"b{self.state.batches_done + len(q) + 1:08d}"
                   if self.tracer.enabled else None)
            with self.tracer.span("source_poll", batch=nid):
                c = source.poll_batch()
            dt = time.perf_counter() - t_poll
            _add_wait(dt)
            phase_hist["source_poll"].observe(dt)
            pending["poll_s"] += dt
            return c

        def _launch(cols, offs, replay_rows=None) -> None:
            """Dispatch one assembled batch into the pipeline (shared by
            live traffic and overload replay — a replayed deferred batch
            takes EXACTLY the live path, so its state updates and sink
            lineage are indistinguishable from never having deferred)."""
            nonlocal t_last_start
            if checkpointer is not None and any(
                h["index"] % every == 0 for h in q
            ):
                # A queued batch's completion will checkpoint: drain
                # first so no newer batch is in flight at save time.
                _drain()
            idx = self.state.batches_done + len(q) + 1
            tid = self.tracer.begin_batch(idx)
            handle = self._start_batch(cols)
            t_last_start = time.perf_counter()
            handle["index"] = idx
            handle["trace_id"] = tid
            handle["source_offsets"] = offs
            if replay_rows is not None:
                handle["overload_replay_rows"] = replay_rows
            q.append(handle)
            self._m_qdepth.set(len(q))
            while len(q) >= depth:
                _finish(q.popleft())
                self._m_qdepth.set(len(q))

        exhausted = False
        capped = False  # max_batches stopped the run (resumable break)
        carry = None  # (cols, offsets): a poll beyond the coalesce cap
        cap = max(self.cfg.runtime.batch_buckets)
        t_last_start = None  # previous batch's dispatch time (pacing)
        while not exhausted:
            if heartbeat is not None:
                heartbeat.beat()
            started = self.state.batches_done + len(q)
            if max_batches and started >= max_batches:
                capped = True
                break
            if self.stop_event is not None and self.stop_event.is_set():
                # Coordinated drain (fleet resize / graceful SIGTERM):
                # stop at a batch boundary with the capped-run tail —
                # deferred/shed batches stay behind the checkpointed
                # offsets by the defer() contract, so the caller's final
                # checkpoint resumes them exactly-once under the next
                # topology instead of force-draining them here.
                capped = True
                break
            if trigger > 0 and t_last_start is not None:
                # Trigger pacing, once per loop pass on the POLL side:
                # batch starts stay >= trigger apart while already-
                # dispatched batches keep computing through the sleep.
                # (Pacing used to run inside _finish, stacking one sleep
                # per queued handle on every drain.) The slept time is
                # credited as wait so in-flight latencies measure the
                # pipeline, not the pacing.
                dt = trigger - (time.perf_counter() - t_last_start)
                if dt > 0:
                    # rtfdslint: disable=blocking-call-on-loop-thread (sanctioned pacing wait point: --trigger-interval spacing on the poll side, slept time credited as wait; regression-pinned in test_runtime trigger-pacing tests)
                    time.sleep(dt)
                    _add_wait(dt)
            if overload is not None and overload.want_replay():
                # Descending from rung 3 (or the spill hit its memory
                # cap): the deferred FIFO's head replays through the
                # normal scoring path BEFORE any live poll — rows reach
                # the feature state in exactly the order a
                # never-overloaded run would have seen them.
                item = overload.next_replay()
                if item is not None:
                    _launch(item.cols, item.offsets,
                            replay_rows=item.rows)
                    continue
            if carry is not None:
                cols, offs = carry
                carry = None
            else:
                cols = _poll()
                if cols is None:
                    break
                if len(next(iter(cols.values()), ())) == 0:
                    # Idle live source (e.g. KafkaSource on a quiet
                    # topic): not a batch — no sink append, no step, no
                    # checkpoint cadence, no max_batches consumption.
                    # Flush the in-flight batches (their results must not
                    # wait for future traffic), then wait a trigger.
                    _drain()
                    if overload is not None:
                        # the quiet period is the ladder's recovery
                        # window: tick the controller so descend dwell
                        # accumulates and deferred batches replay even
                        # if live traffic never returns
                        overload.idle_tick()
                    if trigger > 0:
                        # rtfdslint: disable=blocking-call-on-loop-thread (sanctioned wait point: idle live source with nothing in flight — sleeping one trigger IS the correct behavior, there is no work to stall)
                        time.sleep(trigger)
                    continue
                offs = list(source.offsets)
            # The adaptive controller overrides the static coalesce
            # target while active (it only MERGES small polls upward —
            # an oversized poll still bucket-pads as before).
            assemble = auto.target_rows() if auto is not None else coalesce
            if assemble > 0:
                # Never assemble past the largest jit bucket: a poll that
                # would overflow is carried into the NEXT batch, and its
                # rows stay excluded from this batch's checkpoint offsets
                # (a crash must replay them, not skip them).
                target = min(assemble, cap)
                parts = [cols]
                total = len(next(iter(cols.values())))
                while total < target:
                    more = _poll()
                    if more is None:
                        exhausted = True  # serve the tail, then stop
                        break
                    m = len(next(iter(more.values()), ()))
                    if m == 0:
                        break  # idle: serve what we have now
                    if total + m > cap:
                        carry = (more, list(source.offsets))
                        break
                    parts.append(more)
                    total += m
                    offs = list(source.offsets)
                if len(parts) > 1:
                    cols = {k: np.concatenate([p[k] for p in parts])
                            for k in parts[0]}
            if overload is not None and overload.should_defer():
                # Rung 3 admission control: the whole assembled batch
                # defers to the durable spill instead of dispatching. It
                # consumes no batch_index (sink lineage stays gap-free)
                # and state.offsets stays at the last SCORED batch, so a
                # crash replays deferred rows from the checkpoint.
                # Batches dispatched BEFORE the climb finish first —
                # rung 3 holds nothing in flight, so their results land
                # instead of idling in the pipeline behind the deferral.
                _drain()
                overload.defer(cols, offs)
                continue
            _launch(cols, offs)
        try:
            if overload is not None and not capped:
                # Source exhausted with batches still deferred: the
                # stream must not end owing rows — force-drain the FIFO
                # through the normal scoring path (scored == polled).
                # A max_batches stop is different: the cap wins, and the
                # deferred rows stay durably spilled with state.offsets
                # still BEHIND them, so a resumed run re-polls them.
                overload.finish_stream()
                while True:
                    if heartbeat is not None:
                        # a large deferred backlog drains for minutes —
                        # beat per replayed batch so the stall watchdog
                        # can tell this healthy drain from a wedge
                        heartbeat.beat()
                    item = overload.next_replay()
                    if item is None:
                        break
                    _launch(item.cols, item.offsets,
                            replay_rows=item.rows)
            _drain()
        finally:
            if overload is not None:
                # revert every engine-side degrade so a later run() on
                # this engine starts clean (rung metrics stay honest)
                overload.deactivate()
        self._m_qdepth.set(0)
        # Async sinks drain before run() returns: the caller's follow-up
        # (final checkpoint save, offset commits, reading the output)
        # must see fully-landed writes, and a deferred writer error must
        # surface in THIS run, not on some later call.
        sink_drain = getattr(sink, "drain", None)
        if sink_drain is not None:
            sink_drain()
        if self._cold is not None:
            # Land in-flight promotions and persist buffered demotions so
            # the caller's follow-up save records fresh segment lineage.
            self.drain_promotions()
            self._cold.flush()
            self.state.cold_lineage = self._cold.lineage()
        wall = time.perf_counter() - t_start
        cpu_s = time.process_time() - t_cpu0
        # LatencyTracker-backed snapshots: exact percentiles over the
        # bounded recent window (identical to the old full-list math for
        # runs under the window size, O(1) memory beyond it).
        snaps = {k: t.snapshot() for k, t in trackers.items()}
        stats = {
            "rows": self.state.rows_done - rows0,
            "batches": self.state.batches_done - batches0,
            "wall_s": wall,
            "cpu_s": cpu_s,
            "rows_per_s": (
                (self.state.rows_done - rows0) / wall if wall > 0 else 0.0
            ),
            "latency_p50_ms": snaps["latency"].get("p50_ms", 0.0),
            "latency_p99_ms": snaps["latency"].get("p99_ms", 0.0),
            "host_prep_p50_ms": snaps["host_prep"].get("p50_ms", 0.0),
            "dispatch_p50_ms": snaps["dispatch"].get("p50_ms", 0.0),
            "result_wait_p50_ms": snaps["result_wait"].get("p50_ms", 0.0),
            "sink_write_p50_ms": snaps["sink_write"].get("p50_ms", 0.0),
            "pipeline_depth": depth,
            # the z-contraction mode the serving step compiled with —
            # the run-report twin of the rtfds_z_mode gauge
            "z_mode": self.z_mode,
        }
        if auto is not None:
            stats["autobatch_target_rows"] = auto.target_rows()
            stats["autobatch_adjustments"] = auto.adjustments
        if self._selective:
            # per-run delta, like rows/batches — nonzero tells the
            # operator the threshold/cap calibration is sending full
            # fetches (correct output, just slower; recalibrate
            # emit_threshold or raise emit_cap_fraction)
            stats["selective_overflows"] = self.selective_overflows - ovf0
        if self._cold is not None:
            # Keys scored from the CMS sketch while their promotion was
            # still in flight — the honest scope of the bit-identity
            # claim. 0 means every returning key converged before it was
            # touched again (or was never demoted).
            stats["exactness_degraded_keys"] = (
                len(self._degraded_keys) - degraded0)
        return stats
