"""Profiler integration — jax.profiler traces for the step loop.

The reference had no tracing at all (SURVEY §5.1; Spark UI existed but was
unconfigured). Here any run can capture an XLA/TensorBoard trace::

    with profile_to("/tmp/trace"):
        engine.run(...)

and individual host-side phases can be annotated with ``trace_span`` so they
show up on the profiler timeline next to device ops.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional


def enable_compilation_cache(path: Optional[str] = None) -> None:
    """Turn on jax's persistent XLA compilation cache (best-effort).

    Over the axon TPU tunnel every compile is a ~20-40 s remote call;
    caching makes re-runs (bench retries, the parity gate, the kernel
    profiler) skip them. Default path is user-scoped (``~/.cache``) so a
    shared /tmp on a multi-user host can't collide or be pre-created by
    another user. jax fingerprints backend/config into the cache key, so
    stale entries are never reused incorrectly."""
    import jax

    if path is None:
        path = os.path.join(
            os.path.expanduser("~"), ".cache", "rtfds", "xla"
        )
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    # rtfdslint: disable=broad-exception-catch (cache enablement must degrade to a LOUD warning whatever jax.config raises across versions — a silently-cold cache costs 20-40 s per compile over the tunnel)
    except Exception as e:
        # A silently-cold cache costs 20-40 s PER COMPILE over the
        # tunnel on every restart — the operator must see why.
        from real_time_fraud_detection_system_tpu.utils.logging import (
            get_logger,
        )

        get_logger("tracing").warning(
            "persistent XLA compilation cache could not be enabled at "
            "%s (%s: %s); every compile will run cold", path,
            type(e).__name__, e)


@contextlib.contextmanager
def profile_to(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Named host-side span on the profiler timeline."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
