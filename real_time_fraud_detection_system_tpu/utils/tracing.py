"""Profiler integration — jax.profiler traces for the step loop.

The reference had no tracing at all (SURVEY §5.1; Spark UI existed but was
unconfigured). Here any run can capture an XLA/TensorBoard trace::

    with profile_to("/tmp/trace"):
        engine.run(...)

and individual host-side phases can be annotated with ``trace_span`` so they
show up on the profiler timeline next to device ops.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_to(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Named host-side span on the profiler timeline."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
