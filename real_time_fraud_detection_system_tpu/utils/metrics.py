"""Unified telemetry: metrics registry, renderers, flight recorder, HTTP.

The reference's observability is ``print()`` plus whatever the Spark UI
happens to show (SURVEY §5.1/§5.5); the framework previously had only
fragments (``utils/timing.LatencyTracker``, per-module log lines). This
module is the one measurement substrate every layer reports into:

- :class:`MetricsRegistry` — process-wide, thread-safe Counter / Gauge /
  Histogram registry (histograms use fixed log-spaced latency buckets so
  series from different runs are mergeable), with two renderers: the
  Prometheus text exposition format (:meth:`~MetricsRegistry.
  render_prometheus`) and a JSON snapshot (:meth:`~MetricsRegistry.
  snapshot`).
- :class:`FlightRecorder` — one JSONL record per micro-batch (batch id,
  rows, per-phase timings, queue depth) plus event records (checkpoint,
  feedback, fault injection, restart), all under a run manifest
  (:func:`run_manifest`: config hash, backend, mesh shape, model kind,
  start time). The per-phase breakdown is what makes bottleneck
  attribution — and therefore every later perf PR — possible
  (arXiv:1612.01437's lesson for Spark ML pipelines applies verbatim).
- :class:`MetricsServer` — a stdlib-only background HTTP server exposing
  ``/metrics`` (Prometheus text), ``/metrics.json`` (snapshot) and
  ``/healthz`` (source-lag + last-batch-age thresholds), opt-in from the
  CLI via ``--metrics-port``.

Everything here is stdlib + nothing: importable from the hottest paths
(sources, sinks, the engine loop) without pulling jax/numpy.
"""

from __future__ import annotations

import json
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

# Fixed log-spaced latency ladder (1-2.5-5 per decade, 10µs .. 60s).
# Shared by every duration histogram in the framework so per-phase,
# source, sink, and checkpoint series line up bucket-for-bucket.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
    1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
)


def _fmt_num(v: float) -> str:
    """Prometheus sample/`le` formatting: shortest exact-ish repr."""
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotone float counter."""

    __slots__ = ("labels", "_v", "_lock")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("labels", "_v", "_lock")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed-bucket histogram (Prometheus cumulative-`le` semantics).

    Percentiles are estimated by linear interpolation inside the owning
    bucket — good to a bucket width, plenty for dashboards; exact
    percentiles stay the job of :class:`~.timing.LatencyTracker`'s
    reservoir where the engine needs them.
    """

    __slots__ = ("labels", "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, labels: Dict[str, str],
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS_S):
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count)] including (+Inf, total)."""
        out = []
        acc = 0
        with self._lock:
            counts = list(self._counts)
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile (q in [0, 100]) in observed units."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = total * min(max(q, 0.0), 100.0) / 100.0
        acc = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo_acc = acc
            acc += c
            if acc >= target:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i] if i < len(self.bounds) else lo
                frac = (target - lo_acc) / c
                return lo + (hi - lo) * frac
        return self.bounds[-1]


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Thread-safe name → (typed metric family) → labeled series store.

    ``counter/gauge/histogram(name, help, **labels)`` is get-or-create:
    hot paths may resolve their series once and hold the object (zero
    lookup cost per event), or re-resolve by name (one dict get under a
    lock). Re-registering a name as a different type raises — a name
    means one thing process-wide.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._meta: Dict[str, Tuple[str, str]] = {}  # name -> (type, help)
        self._series: Dict[str, Dict[Tuple, object]] = {}
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}

    def _get(self, typ: str, name: str, help_: str, labels: Dict[str, str],
             **kwargs):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            meta = self._meta.get(name)
            if meta is None:
                self._meta[name] = (typ, help_)
                self._series[name] = {}
            elif meta[0] != typ:
                raise ValueError(
                    f"metric {name!r} already registered as {meta[0]}, "
                    f"requested {typ}")
            elif help_ and not meta[1]:
                self._meta[name] = (typ, help_)
            if typ == "histogram":
                # One bucket ladder per family (series must be mergeable
                # and a name means one thing process-wide): an explicit
                # mismatch raises like a type mismatch would; omitted
                # buckets adopt the family's ladder.
                want = kwargs.pop("buckets", None)
                have = self._hist_buckets.get(name)
                if want is not None:
                    want = tuple(sorted(float(b) for b in want))
                    if have is not None and want != have:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {have}, requested {want}")
                kwargs["buckets"] = want or have or LATENCY_BUCKETS_S
                self._hist_buckets.setdefault(name, kwargs["buckets"])
            fam = self._series[name]
            m = fam.get(key)
            if m is None:
                m = _TYPES[typ]({k: str(v) for k, v in labels.items()},
                                **kwargs)
                fam[key] = m
            return m

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        return self._get("counter", name, help, labels)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._get("gauge", name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Tuple[float, ...]] = None,
                  **labels) -> Histogram:
        """``buckets=None`` adopts the family's ladder (or the default
        :data:`LATENCY_BUCKETS_S` on first registration); an explicit
        ladder that disagrees with the family's raises."""
        return self._get("histogram", name, help, labels, buckets=buckets)

    def get(self, name: str, **labels):
        """Existing series or None (never creates) — the read-side API
        the health checks use."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            return self._series.get(name, {}).get(key)

    def family_total(self, name: str) -> Optional[float]:
        """Sum of a counter/gauge family's values across ALL label sets
        (e.g. ``rtfds_engine_restarts_total`` over its ``cause`` labels),
        or None when the family was never registered. Read-only — never
        creates. Histogram families have no single total and return
        None."""
        with self._lock:
            fam = self._series.get(name)
            if fam is None:
                return None
            vals = [m.value for m in fam.values()
                    if not isinstance(m, Histogram)]
        if not vals:
            return None
        return float(sum(vals))

    def family_series(self, name: str) -> List[Tuple[Dict[str, str],
                                                     float]]:
        """Read-only ``[(labels, value)]`` rows for a counter/gauge
        family (histograms excluded; [] when never registered) — the
        introspection the healthz per-shard breakdowns use. Never
        creates."""
        with self._lock:
            fam = self._series.get(name)
            if fam is None:
                return []
            return [(dict(m.labels), m.value) for m in fam.values()
                    if not isinstance(m, Histogram)]

    def clear(self) -> None:
        """Drop every registered family (test isolation)."""
        with self._lock:
            self._meta.clear()
            self._series.clear()
            self._hist_buckets.clear()

    def _families(self):
        with self._lock:
            return [
                (name, *self._meta[name], list(fam.values()))
                for name, fam in sorted(self._series.items())
            ]

    # -- renderers -----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able snapshot of every family and series."""
        out: Dict[str, dict] = {}
        for name, typ, help_, series in self._families():
            rows = []
            for m in series:
                if isinstance(m, Histogram):
                    rows.append({
                        "labels": m.labels,
                        "count": m.count,
                        "sum": m.sum,
                        "buckets": [[b if b != float("inf") else "+Inf", c]
                                    for b, c in m.cumulative()],
                        "p50": m.percentile(50),
                        "p99": m.percentile(99),
                    })
                else:
                    rows.append({"labels": m.labels, "value": m.value})
            out[name] = {"type": typ, "help": help_, "series": rows}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        for name, typ, help_, series in self._families():
            if help_:
                lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            for m in series:
                if isinstance(m, Histogram):
                    for b, c in m.cumulative():
                        lab = dict(m.labels)
                        lab["le"] = _fmt_num(b)
                        lines.append(
                            f"{name}_bucket{_label_str(lab)} {c}")
                    ls = _label_str(m.labels)
                    lines.append(f"{name}_sum{ls} {_fmt_num(m.sum)}")
                    lines.append(f"{name}_count{ls} {m.count}")
                else:
                    lines.append(
                        f"{name}{_label_str(m.labels)} {_fmt_num(m.value)}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every layer reports into."""
    return _default_registry


# ---------------------------------------------------------------------------
# Multi-host aggregation: per-process snapshots → one fleet view
# ---------------------------------------------------------------------------

def merge_process_snapshots(snaps: Dict[str, dict]) -> dict:
    """Merge per-process registry snapshots (``MetricsRegistry.
    snapshot()`` / ``/metrics.json`` payloads) into ONE fleet-wide
    snapshot — the coordinator-side ``/metrics`` aggregation view.

    ``snaps`` maps process id → snapshot. Every series gains a
    ``process=<pid>`` label unless the worker already stamped one (the
    sharded engine labels its per-shard series itself, with GLOBAL
    shard ids, so the merged view reads as one engine's shard space).
    Values are never summed here: aggregation is the scraper's job;
    this view only makes the per-process series distinguishable."""
    out: Dict[str, dict] = {}
    for pid, snap in sorted(snaps.items(), key=lambda kv: str(kv[0])):
        for name, fam in (snap or {}).items():
            dst = out.setdefault(name, {
                "type": fam.get("type"), "help": fam.get("help"),
                "series": []})
            for row in fam.get("series", []):
                labels = dict(row.get("labels") or {})
                labels.setdefault("process", str(pid))
                dst["series"].append({**row, "labels": labels})
    return out


def render_snapshot_prometheus(snap: dict) -> str:
    """Prometheus text for a snapshot dict — the fleet aggregator's
    renderer, emitting the same exposition format as
    :meth:`MetricsRegistry.render_prometheus` (histograms re-expanded
    from their snapshot bucket rows)."""
    lines: List[str] = []
    for name in sorted(snap):
        fam = snap[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam.get('type', 'gauge')}")
        for row in fam.get("series", []):
            labels = dict(row.get("labels") or {})
            if "buckets" in row:
                for b, c in row["buckets"]:
                    lab = dict(labels)
                    lab["le"] = (str(b) if isinstance(b, str)
                                 else _fmt_num(float(b)))
                    lines.append(f"{name}_bucket{_label_str(lab)} {c}")
                ls = _label_str(labels)
                lines.append(
                    f"{name}_sum{ls} {_fmt_num(float(row['sum']))}")
                lines.append(f"{name}_count{ls} {row['count']}")
            else:
                lines.append(
                    f"{name}{_label_str(labels)} "
                    f"{_fmt_num(float(row['value']))}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

def run_manifest(cfg=None, model_kind: str = "", **extra) -> dict:
    """Build the flight-record manifest: everything needed to interpret
    the per-batch records later (config hash, backend, mesh shape, model
    kind, start time). jax is imported lazily so non-jax processes can
    still write flight records."""
    man = {
        "model_kind": model_kind,
        "start_unix_s": time.time(),
        **extra,
    }
    if cfg is not None:
        import dataclasses
        import hashlib

        try:
            blob = json.dumps(dataclasses.asdict(cfg), sort_keys=True,
                              default=str)
        except TypeError:
            blob = repr(cfg)
        man["config_hash"] = hashlib.sha256(blob.encode()).hexdigest()[:16]
    try:
        import jax

        man.setdefault("backend", jax.default_backend())
        man.setdefault("n_devices", jax.device_count())
    except (ImportError, RuntimeError, AttributeError):
        pass  # no backend in this process: manifest still valid
    return man


class FlightRecorder:
    """Append-only JSONL event log, one record per micro-batch.

    Line 1 is the run manifest (``{"kind": "manifest", ...}``); batch
    records carry ``{"kind": "batch", "batch": i, "rows": n, "phases":
    {phase: seconds}, "queue_depth": d, "t": unix}``; everything else
    (checkpoints, feedback applications, fault injections, restarts)
    lands as ``{"kind": "event", "event": name, ...}``. Thread-safe —
    the supervisor and engine threads may interleave events. Writes are
    line-buffered appends: a crash loses at most the current line, and
    every preceding line stays parseable (the same tail-tolerance as a
    Kafka log).
    """

    def __init__(self, path: str, manifest: Optional[dict] = None,
                 max_bytes: Optional[int] = None):
        """``max_bytes`` caps the JSONL's size: when an append pushes the
        file past it, the file rotates to ``<path>.1`` (one generation,
        overwritten on the next trip — disk use is bounded at ~2×cap)
        and the fresh file opens with the manifest plus a ``rotated``
        event. ``None``/0 = unbounded (the pre-rotation behavior)."""
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else 0
        self._lock = threading.Lock()
        # Sampled mode (overload rung 1): record every k-th batch
        # record; event records always land. 1 = every batch.
        self._sample_every = 1
        self._batch_tick = 0
        self._f = open(path, "a", encoding="utf-8")
        self.manifest = dict(manifest or {})
        self.manifest.setdefault("start_unix_s", time.time())
        if self._f.tell() > 0:
            # Resuming an existing record: if the previous writer died
            # mid-line, start on a fresh line so the torn tail corrupts
            # exactly one record, not two.
            with open(path, "rb") as rf:
                rf.seek(-1, 2)
                if rf.read(1) != b"\n":
                    self._f.write("\n")
                    self._f.flush()
        # EVERY open writes its manifest — a segment marker. A second
        # run appending to the same path (new config/model) must not be
        # silently attributed to the first run's manifest; read() hands
        # back the LAST segment's manifest.
        self._write({"kind": "manifest", **self.manifest})

    def _write(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"), default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
            if self.max_bytes and self._f.tell() > self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Size-cap rotation (caller holds the lock): current file moves
        to ``<path>.1``; a fresh segment opens with the manifest and a
        ``rotated`` event, so readers of the live path see an honest
        marker instead of silently missing history."""
        import os

        rotated_bytes = self._f.tell()
        self._f.close()
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a", encoding="utf-8")
        for obj in (
            {"kind": "manifest", **self.manifest},
            {"kind": "event", "t": time.time(), "event": "rotated",
             "previous": self.path + ".1",
             "previous_bytes": rotated_bytes},
        ):
            self._f.write(json.dumps(obj, separators=(",", ":"),
                                     default=str) + "\n")
        self._f.flush()

    def set_sample_every(self, k: int) -> None:
        """Batch-record sampling (overload rung 1 drops the recorder to
        sampled mode; 1 restores full recording). Events — rung
        transitions, shed/replay, faults — are NEVER sampled out: the
        record must stay a complete account of what degraded and why,
        only the per-batch bulk thins."""
        with self._lock:
            self._sample_every = max(1, int(k))
            self._batch_tick = 0

    def record_batch(self, batch_index: int, rows: int,
                     phases: Dict[str, float], queue_depth: int = 0,
                     **extra) -> None:
        with self._lock:
            self._batch_tick += 1
            if self._sample_every > 1 \
                    and self._batch_tick % self._sample_every != 1:
                return
        self._write({
            "kind": "batch", "t": time.time(), "batch": int(batch_index),
            "rows": int(rows),
            "phases": {k: float(v) for k, v in phases.items()},
            "queue_depth": int(queue_depth), **extra,
        })

    def record_event(self, event: str, **fields) -> None:
        self._write({"kind": "event", "t": time.time(), "event": event,
                     **fields})

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    @staticmethod
    def read_segments(path: str) -> List[Tuple[Optional[dict], List[dict]]]:
        """Replay a flight record as run segments: → [(manifest,
        records), ...]. Each writer open appends a manifest marker that
        starts a new segment; unparseable lines (torn final write after
        a crash) are skipped. Records before any manifest land in a
        leading ``(None, records)`` segment."""
        segments: List[Tuple[Optional[dict], List[dict]]] = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if obj.get("kind") == "manifest":
                    segments.append((obj, []))
                else:
                    if not segments:
                        segments.append((None, []))
                    segments[-1][1].append(obj)
        return segments

    @staticmethod
    def read(path: str) -> Tuple[Optional[dict], List[dict]]:
        """→ the LAST run segment's (manifest, records): the most recent
        run owns the record's interpretation, and its batches are never
        mixed with an earlier run's appended to the same path. Use
        :meth:`read_segments` for the full history."""
        segments = FlightRecorder.read_segments(path)
        return segments[-1] if segments else (None, [])


_active_recorder: Optional[FlightRecorder] = None


def set_active_recorder(rec: Optional[FlightRecorder]) -> None:
    """Install the process-wide flight recorder (CLI serve loop does
    this). Layers without an engine handle — fault injectors, the
    checkpointer, the recovery supervisor — record through it."""
    global _active_recorder
    _active_recorder = rec


def active_recorder() -> Optional[FlightRecorder]:
    return _active_recorder


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------

class MetricsServer:
    """Stdlib-only background HTTP server: ``/metrics`` (Prometheus
    text), ``/metrics.json`` (snapshot), ``/healthz``, ``/trace``
    (the process tracer's span ring buffer as Chrome-trace JSON —
    save the response body to a file and load it in ui.perfetto.dev).

    ``/healthz`` is 200 when the serving loop is making progress:

    - last-batch age (now − ``rtfds_last_batch_unix_seconds``) is within
      ``max_batch_age_s`` — a hung source or device step trips it the
      same way the :class:`~..runtime.faults.Heartbeat` watchdog does;
      before the first batch lands the check passes (startup grace).
    - source lag (``rtfds_source_lag_rows``, set by sources that can
      compute a backlog) is within ``max_source_lag_rows`` when that
      threshold is configured.

    The body additionally reports the failure-handling counters ops
    alert on — ``restarts`` (``rtfds_engine_restarts_total`` summed over
    causes), ``crash_loops`` and ``dead_letter_rows`` — and a ``status``
    field: ``"ok"``, ``"unhealthy"`` (503), or ``"degraded"`` (still
    200: the stream is alive and making progress, but rows sit
    quarantined in the dead-letter queue awaiting triage, serving runs
    off a fallback restore, or the overload ladder is active /
    deferred rows await replay — the ``overload`` block then carries
    the rung, shed rows pending replay, and the lag trend).

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``self.port`` after :meth:`start`.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 max_batch_age_s: float = 300.0,
                 max_source_lag_rows: Optional[float] = None):
        self.host = host
        self.port = int(port)
        self.registry = registry if registry is not None else get_registry()
        self.max_batch_age_s = float(max_batch_age_s)
        self.max_source_lag_rows = max_source_lag_rows
        self._httpd = None
        self._thread = None

    def health(self) -> Tuple[bool, dict]:
        checks: Dict[str, dict] = {}
        ok = True
        last = self.registry.get("rtfds_last_batch_unix_seconds")
        if last is not None and last.value > 0:
            # rtfdslint: disable=wall-clock-duration (liveness age vs a wall-clock gauge the serving process stamps; /healthz may be queried from any process, so both ends must be wall clock)
            age = time.time() - last.value
            good = age <= self.max_batch_age_s
            checks["last_batch_age_s"] = {
                "value": round(age, 3), "max": self.max_batch_age_s,
                "ok": good}
            ok = ok and good
        else:
            checks["last_batch_age_s"] = {"value": None, "ok": True,
                                          "note": "no batches yet"}
        lag = self.registry.get("rtfds_source_lag_rows")
        if lag is not None and self.max_source_lag_rows is not None:
            good = lag.value <= self.max_source_lag_rows
            checks["source_lag_rows"] = {
                "value": lag.value, "max": self.max_source_lag_rows,
                "ok": good}
            ok = ok and good
        elif lag is not None:
            checks["source_lag_rows"] = {"value": lag.value, "ok": True,
                                         "note": "no threshold set"}
        # Durable-state plane: age of the last checkpoint save, lineage
        # depth, and corruption/fallback counters — present only once
        # the serving loop checkpoints, so a checkpoint-less run's body
        # stays clean.
        last_ck = self.registry.get("rtfds_last_checkpoint_unix_seconds")
        if last_ck is not None and last_ck.value > 0:
            checks["last_checkpoint_age_s"] = {
                # rtfdslint: disable=wall-clock-duration (age vs the wall-clock checkpoint stamp — same cross-process contract as last_batch_age_s above)
                "value": round(time.time() - last_ck.value, 3), "ok": True}
        # Failure-handling counters (degraded-but-alive serving): present
        # only once their families exist, so a clean run's body stays
        # clean.
        extras: Dict[str, float] = {}
        for fam, key in (("rtfds_engine_restarts_total", "restarts"),
                         ("rtfds_crash_loops_total", "crash_loops"),
                         ("rtfds_dead_letter_rows", "dead_letter_rows"),
                         ("rtfds_checkpoint_corrupt_total",
                          "checkpoint_corrupt_total"),
                         ("rtfds_checkpoint_fallbacks_total",
                          "checkpoint_fallbacks"),
                         ("rtfds_checkpoint_lineage_depth",
                          "checkpoint_lineage_depth")):
            v = self.registry.family_total(fam)
            if v is not None:
                extras[key] = v
        # Feedback feature cache: shadow/live precision-recall quality
        # silently degrades when labeled rows miss the cache (their
        # labels are dropped on the floor) — surface the hit rate so the
        # operator can SEE it, not infer it from starved metric windows.
        c_hit = self.registry.get("rtfds_feature_cache_lookups_total",
                                  outcome="hit")
        c_miss = self.registry.get("rtfds_feature_cache_lookups_total",
                                   outcome="miss")
        if c_hit is not None or c_miss is not None:
            hits = c_hit.value if c_hit is not None else 0.0
            misses = c_miss.value if c_miss is not None else 0.0
            total = hits + misses
            cache: Dict[str, float] = {
                "hit_rate": round(hits / total, 4) if total else 1.0,
                "lookups": total,
            }
            occ = self.registry.get("rtfds_feature_cache_occupancy")
            cap = self.registry.get("rtfds_feature_cache_capacity")
            if occ is not None:
                cache["occupancy"] = occ.value
            if cap is not None:
                cache["capacity"] = cap.value
            ev = self.registry.family_total(
                "rtfds_feature_cache_evictions_total")
            if ev is not None:
                cache["evictions"] = ev
            extras["feature_cache"] = cache
        # Tiered feature store (key_mode="exact"): per-table hot-tier
        # occupancy, compaction reclaim totals, the dense-tier hit rate,
        # and state bytes vs the configured HBM budget — present only
        # once an exact-mode engine registered the occupancy gauges, so
        # direct/hash runs keep a clean body.
        occ_tables: Dict[str, float] = {}
        for table in ("customer", "terminal"):
            g = self.registry.get("rtfds_feature_slots_occupied",
                                  table=table)
            if g is not None:
                occ_tables[table] = g.value
        if occ_tables:
            fstate: Dict[str, object] = {"slots_occupied": occ_tables}
            # Sum the TABLE-level series only: the sharded engine also
            # registers shard-labeled rows of the same family (they
            # break the same totals down, so a blind family_total would
            # double-count).
            rec_rows = [
                v for labels, v in self.registry.family_series(
                    "rtfds_feature_slots_reclaimed_total")
                if "shard" not in labels]
            if rec_rows:
                fstate["slots_reclaimed"] = float(sum(rec_rows))
            # Per-shard breakdown (sharded exact serving): occupancy per
            # shard summed over tables, plus the worst shard — skew is
            # the failure mode the modulo ownership hides, so it gets a
            # first-class health surface.
            shard_occ: Dict[str, float] = {}
            for labels, v in self.registry.family_series(
                    "rtfds_feature_slots_occupied"):
                s = labels.get("shard")
                if s is not None:
                    shard_occ[s] = shard_occ.get(s, 0.0) + v
            if shard_occ:
                fstate["slots_occupied_per_shard"] = {
                    s: shard_occ[s]
                    for s in sorted(shard_occ, key=int)}
                worst = max(shard_occ, key=lambda s: shard_occ[s])
                fstate["worst_shard"] = {
                    "shard": int(worst), "occupied": shard_occ[worst]}
                shard_tiers: Dict[str, Dict[str, float]] = {}
                for labels, v in self.registry.family_series(
                        "rtfds_feature_tier_rows_total"):
                    s = labels.get("shard")
                    if s is not None:
                        shard_tiers.setdefault(
                            s, {})[labels.get("tier", "?")] = v
                if shard_tiers:
                    fstate["tier_rows_per_shard"] = {
                        s: shard_tiers[s]
                        for s in sorted(shard_tiers, key=int)}
            dense = self.registry.get("rtfds_feature_tier_rows_total",
                                      tier="dense")
            cms_t = self.registry.get("rtfds_feature_tier_rows_total",
                                      tier="cms")
            if dense is not None or cms_t is not None:
                d = dense.value if dense is not None else 0.0
                c = cms_t.value if cms_t is not None else 0.0
                fstate["tier_rows"] = {"dense": d, "cms": c}
                total = d + c
                # both tiers serve correct-contract features; the hit
                # rate tells the operator how EXACT the serving mix is
                fstate["dense_hit_rate"] = (round(d / total, 4)
                                            if total else 1.0)
            sb = self.registry.get("rtfds_feature_state_bytes",
                                   tier="total")
            if sb is not None:
                fstate["state_bytes"] = sb.value
                budget = self.registry.get(
                    "rtfds_feature_state_budget_bytes")
                if budget is not None and budget.value > 0:
                    fstate["budget_bytes"] = budget.value
                    fstate["budget_used"] = round(
                        sb.value / budget.value, 4)
            # Host cold tier (features.cold_store): depth, promotion
            # traffic and the promoter backlog — present only once an
            # engine armed the cold store, so two-tier runs keep the
            # block absent rather than zero-filled.
            ck = self.registry.get("rtfds_feature_cold_keys")
            if ck is not None:
                cold: Dict[str, float] = {"keys": ck.value}
                for name, key in (
                        ("rtfds_feature_cold_bytes", "bytes"),
                        ("rtfds_feature_cold_promotions_total",
                         "promotions"),
                        ("rtfds_feature_cold_demotions_total",
                         "demotions"),
                        ("rtfds_feature_cold_promote_wait_seconds_total",
                         "promote_wait_seconds"),
                        ("rtfds_feature_cold_promote_backlog",
                         "promote_backlog"),
                        ("rtfds_feature_cold_promote_queue_limit",
                         "promote_queue_limit")):
                    m = self.registry.get(name)
                    if m is not None:
                        cold[key] = m.value
                fstate["cold"] = cold
            extras["feature_state"] = fstate
        # Device plane: the z-contraction mode the serving step compiled
        # with and whether the fused Pallas path is on — present only
        # once an engine registered the gauges, so non-serving processes
        # stay clean.
        active_z = None
        for mode in ("f32", "bf16", "int8"):
            g = self.registry.get("rtfds_z_mode", mode=mode)
            if g is not None and g.value > 0:
                active_z = mode
        if active_z is not None:
            device_plane: Dict[str, object] = {"z_mode": active_z}
            up = self.registry.get("rtfds_use_pallas")
            if up is not None:
                device_plane["use_pallas"] = bool(up.value)
            extras["device_plane"] = device_plane
        # Continuous-learning plane: which versions are serving/shadowing
        # and whether promotions/rollbacks have fired — present only once
        # a registry/learning loop exists, so other runs stay clean.
        champ = self.registry.get("rtfds_model_version", role="champion")
        if champ is not None:
            learning: Dict[str, float] = {
                "champion_version": champ.value}
            cand = self.registry.get("rtfds_model_version",
                                     role="candidate")
            if cand is not None:
                learning["candidate_version"] = cand.value
            # promotions/refusals are DIFFERENT outcomes of one family —
            # summing them would report a refused corrupt candidate as a
            # successful promotion
            for outcome, key in (("promoted", "promotions"),
                                 ("refused_corrupt", "refusals")):
                m = self.registry.get("rtfds_model_promotions_total",
                                      outcome=outcome)
                if m is not None:
                    learning[key] = m.value
            for fam, key in (
                    ("rtfds_model_rollbacks_total", "rollbacks"),
                    ("rtfds_shadow_divergence_total",
                     "shadow_divergence"),
                    ("rtfds_model_artifact_corrupt_total",
                     "model_artifact_corrupt")):
                v = self.registry.family_total(fam)
                if v is not None:
                    learning[key] = v
            extras["learning"] = learning
        # Overload ladder (runtime/overload.py): present only once a
        # controller registered the rung gauge. Degraded-but-alive while
        # any rung is active OR deferred rows await replay — the same
        # 200-with-status-"degraded" contract as the DLQ and
        # fallback-restore states (the stream is serving; an operator
        # should look before the spill fills).
        rung = self.registry.get("rtfds_overload_rung")
        if rung is not None:
            overload: Dict[str, float] = {"rung": rung.value}
            pend = self.registry.get("rtfds_shed_pending_rows")
            if pend is not None:
                overload["shed_rows_pending_replay"] = pend.value
            for fam, key in (("rtfds_shed_rows_total", "shed_rows"),
                             ("rtfds_shed_replayed_rows_total",
                              "replayed_rows"),
                             ("rtfds_overload_transitions_total",
                              "transitions")):
                v = self.registry.family_total(fam)
                if v is not None:
                    overload[key] = v
            trend = self.registry.get("rtfds_source_lag_trend_rows_per_s")
            if trend is not None:
                overload["lag_trend_rows_per_s"] = trend.value
            extras["overload"] = overload
        status = "ok" if ok else "unhealthy"
        if ok and rung is not None and (
                rung.value > 0
                or extras["overload"].get("shed_rows_pending_replay",
                                          0) > 0):
            status = "degraded"
        if ok and extras.get("dead_letter_rows", 0) > 0:
            # alive and progressing, but quarantined rows await triage
            status = "degraded"
        fb = self.registry.get("rtfds_checkpoint_serving_fallback")
        if ok and fb is not None and fb.value > 0:
            # the engine restored PAST a corrupt checkpoint and is
            # serving off an older fence — alive (200) but an operator
            # should look at the quarantined lineage before the next
            # incident eats the remaining fallback depth
            status = "degraded"
            extras["serving_off_fallback_restore"] = True
        return ok, {"healthy": ok, "status": status, "checks": checks,
                    **extras}

    def start(self) -> "MetricsServer":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            server.registry.render_prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/metrics.json":
                        self._send(
                            200,
                            json.dumps(server.registry.snapshot()).encode(),
                            "application/json")
                    elif path == "/healthz":
                        ok, body = server.health()
                        self._send(200 if ok else 503,
                                   json.dumps(body).encode(),
                                   "application/json")
                    elif path == "/trace":
                        # lazy import: metrics stays importable without
                        # the trace module (and vice versa — trace
                        # imports metrics for its span counter)
                        from real_time_fraud_detection_system_tpu.utils \
                            .trace import get_tracer

                        self._send(
                            200,
                            json.dumps(get_tracer().export_chrome())
                            .encode(),
                            "application/json")
                    else:
                        self._send(404, b'{"error":"not found"}',
                                   "application/json")
                except BrokenPipeError:  # client went away mid-write
                    pass

            def log_message(self, *a):  # endpoint scrapes are not log news
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="rtfds-metrics")
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
