"""Structured logging — replaces the reference's bare ``print()``/``.show()``
observability (SURVEY §5.5, e.g. ``fraud_detection.py:56``)."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


def get_logger(name: str = "rtfds") -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        root = logging.getLogger("rtfds")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True
    if name == "rtfds" or name.startswith("rtfds."):
        return logging.getLogger(name)
    return logging.getLogger(f"rtfds.{name}")
