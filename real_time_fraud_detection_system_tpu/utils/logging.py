"""Structured logging — replaces the reference's bare ``print()``/``.show()``
observability (SURVEY §5.5, e.g. ``fraud_detection.py:56``).

Environment knobs (read once, at first ``get_logger`` call):

- ``RTFDS_LOG_LEVEL`` — root level for the ``rtfds`` logger tree
  (``DEBUG``/``INFO``/``WARNING``/``ERROR``/``CRITICAL`` or a numeric
  level; unknown values keep the INFO default and say so).
- ``RTFDS_LOG_JSON=1`` — emit JSON lines instead of the human format.
  Each record carries the current per-batch trace id
  (``utils/trace.py``), so a log line lands next to its span waterfall:
  ``jq 'select(.trace_id=="b00000042")'`` over the log is the textual
  twin of filtering that batch in Perfetto.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, message, and the
    current trace/batch id for log↔span correlation."""

    def format(self, record: logging.LogRecord) -> str:
        # lazy import: logging must stay importable first (trace.py
        # itself logs through get_logger)
        from real_time_fraud_detection_system_tpu.utils.trace import (
            current_ids,
        )

        trace_id, batch = current_ids()
        out = {
            "t": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if trace_id:
            out["trace_id"] = trace_id
            out["batch"] = batch
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"), default=str)


def _resolve_level(spec: str) -> int:
    try:
        return int(spec)
    except ValueError:
        pass
    level = logging.getLevelName(spec.strip().upper())
    return level if isinstance(level, int) else -1


def get_logger(name: str = "rtfds") -> logging.Logger:
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        if os.environ.get("RTFDS_LOG_JSON", "") not in ("", "0"):
            handler.setFormatter(JsonLineFormatter())
        else:
            handler.setFormatter(
                logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        root = logging.getLogger("rtfds")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        spec = os.environ.get("RTFDS_LOG_LEVEL", "")
        if spec:
            level = _resolve_level(spec)
            if level >= 0:
                root.setLevel(level)
            else:
                root.warning(
                    "RTFDS_LOG_LEVEL=%r is not a known level; keeping "
                    "INFO", spec)
        root.propagate = False
        _configured = True
    if name == "rtfds" or name.startswith("rtfds."):
        return logging.getLogger(name)
    return logging.getLogger(f"rtfds.{name}")
