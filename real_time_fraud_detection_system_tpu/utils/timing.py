"""Latency/throughput instrumentation.

The reference records wall-clock per model fit/predict into result dicts
(``shared_functions.py:312-320``) and otherwise relies on ``print``. Here
every micro-batch is timed by default: a bounded reservoir keeps the recent
window, percentiles come from the exact sorted sample, and the tracker is
cheap enough for the 1M txns/s target loop.
"""

from __future__ import annotations

import datetime as _dt
import time
from typing import Dict, Optional

import numpy as np


def date_to_epoch_s(date: str) -> int:
    """ISO date string → seconds since the unix epoch (UTC midnight)."""
    d = _dt.date.fromisoformat(date)
    return int((d - _dt.date(1970, 1, 1)).days) * 86400


class Timer:
    """Context-manager wall timer: ``with Timer() as t: ...; t.seconds``."""

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        self.seconds = 0.0
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0


class LatencyTracker:
    """Sliding-window latency stats (p50/p90/p99/max) + counters."""

    def __init__(self, window: int = 4096):
        self._buf = np.zeros(window, dtype=np.float64)
        self._n = 0
        self._total = 0
        self._rows = 0
        self._t_start = time.perf_counter()

    def record(self, seconds: float, rows: int = 0) -> None:
        self._buf[self._n % len(self._buf)] = seconds
        self._n += 1
        self._total += 1
        self._rows += rows

    def snapshot(self) -> Dict[str, float]:
        k = min(self._n, len(self._buf))
        wall = time.perf_counter() - self._t_start
        if k == 0:
            return {"count": 0, "rows": 0, "wall_s": wall}
        window = np.sort(self._buf[:k])
        return {
            "count": self._total,
            "rows": self._rows,
            "wall_s": wall,
            "rows_per_s": self._rows / wall if wall > 0 else 0.0,
            "p50_ms": float(np.percentile(window, 50) * 1e3),
            "p90_ms": float(np.percentile(window, 90) * 1e3),
            "p99_ms": float(np.percentile(window, 99) * 1e3),
            "max_ms": float(window[-1] * 1e3),
        }
