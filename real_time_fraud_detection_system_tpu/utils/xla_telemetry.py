"""XLA/device telemetry: compile counters, steady-state recompile
detection, and device-memory gauges.

Round-5 benching had to reverse-engineer device step time from RTT
decomposition, and a silent in-loop retrace costs ~1 s on this hardware
(969 ms measured vs 8 ms steady-state, ``runtime/sharded_engine.py``).
This module makes the XLA layer report instead of being inferred:

- :func:`install_compile_telemetry` hooks ``jax.monitoring``'s
  duration-event stream once per process and turns every backend
  compile into ``rtfds_xla_compiles_total`` + an
  ``rtfds_xla_compile_seconds`` histogram observation, plus an
  ``xla_compile`` span on the active tracer so compiles appear on the
  Perfetto timeline next to the batch phases they stall.
- :class:`RecompileDetector` wraps the engine's jitted step calls. It
  tracks the (shapes, dtypes, donation) signature of every call; a
  compile observed during a call AFTER the warmup window increments
  ``rtfds_xla_recompiles_total`` and warn-logs the signature diff — the
  alarm for shape churn, silent donation loss, or a hot model reload
  that changed the params' shape family mid-serve.
- :class:`DeviceMemoryTelemetry` samples ``device.memory_stats()`` into
  ``rtfds_device_memory_bytes{kind=in_use|peak}`` gauges each batch
  (backends without memory stats — CPU — are detected once and sampling
  becomes a no-op).

Compile events are process-global (the jit cache is process-global), so
the listener always reports into the DEFAULT registry; the per-engine
recompile counter honors the engine's own registry, matching how every
other engine series behaves.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from real_time_fraud_detection_system_tpu.utils.logging import get_logger
from real_time_fraud_detection_system_tpu.utils.metrics import (
    MetricsRegistry,
    get_registry,
)

log = get_logger("xla")

# The jax.monitoring duration event that marks one backend (XLA)
# compilation. Trace/lowering events are reported separately by jax and
# excluded — "a compile" here means "XLA built a new executable".
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_install_lock = threading.Lock()
_installed = False
# Monotone count of backend compiles observed since install — the
# RecompileDetector samples deltas of this around each step call.
_compile_count = 0


def install_compile_telemetry() -> bool:
    """Register the ``jax.monitoring`` listener (idempotent; one per
    process). Returns True when the listener is active, False when jax
    (or its monitoring API) is unavailable in this process."""
    global _installed
    with _install_lock:
        if _installed:
            return True
        try:
            import jax.monitoring as monitoring
        except (ImportError, AttributeError, RuntimeError):
            # RuntimeError: mismatched jax/jaxlib raises at import time —
            # telemetry answers "unavailable", it never crashes the host
            return False
        reg = get_registry()
        m_compiles = reg.counter(
            "rtfds_xla_compiles_total",
            "XLA backend compilations in this process")
        m_seconds = reg.histogram(
            "rtfds_xla_compile_seconds",
            "wall time per XLA backend compilation")

        def _listener(name: str, duration_s: float, **kw) -> None:
            if not name.endswith(_COMPILE_EVENT_SUFFIX):
                return
            global _compile_count
            _compile_count += 1
            m_compiles.inc()
            m_seconds.observe(float(duration_s))
            # Put the compile on the trace timeline: the event fires at
            # compile END, so the span is backdated by its duration.
            from real_time_fraud_detection_system_tpu.utils.trace import (
                get_tracer,
            )

            tracer = get_tracer()
            if tracer.enabled:
                t1 = time.perf_counter()
                tracer.add_span("xla_compile", t1 - float(duration_s), t1)

        monitoring.register_event_duration_secs_listener(_listener)
        _installed = True
        return True


def compile_count() -> int:
    """Backend compiles observed since :func:`install_compile_telemetry`
    (0 until installed)."""
    return _compile_count


def step_signature(*arrays, static: Tuple = ()) -> Tuple:
    """Build a (shapes, dtypes, static) call signature for the recompile
    detector from the arrays an engine step receives. ``static`` carries
    whatever else keys the jit cache (donation layout, model kind,
    routed/local variant)."""
    return tuple(
        (tuple(a.shape), str(getattr(a, "dtype", type(a).__name__)))
        for a in arrays
    ) + tuple(static)


class _StepWindow:
    """Context manager produced by :meth:`RecompileDetector.step`."""

    __slots__ = ("_det", "_sig", "_before")

    def __init__(self, det: "RecompileDetector", sig: Tuple):
        self._det = det
        self._sig = sig

    def __enter__(self):
        self._before = _compile_count
        return self

    def __exit__(self, *exc):
        self._det._after_call(self._sig, _compile_count - self._before)
        return False


class RecompileDetector:
    """Steady-state recompile alarm for a jitted step.

    Warmup semantics: the first ``warmup_calls`` step calls may compile
    freely (bucket-size jit-cache fills are expected there). After
    warmup, ANY compile observed during a tracked step call increments
    ``rtfds_xla_recompiles_total`` and warn-logs the diff between the
    offending call's signature and the known signature set — whether the
    signature is new (late bucket size, reload-changed params shapes:
    a real compile paid inside the serving loop either way) or already
    seen (donation/weak-type/sharding churn: the jit cache is thrashing).

    Requires :func:`install_compile_telemetry`; without a listener the
    compile delta is always 0 and the detector stays silent (never
    wrong, just blind — e.g. a jax-free process importing the engine).
    """

    DEFAULT_WARMUP_CALLS = 4

    def __init__(self, warmup_calls: int = DEFAULT_WARMUP_CALLS,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "engine_step"):
        self.warmup_calls = int(warmup_calls)
        self.name = name
        reg = registry if registry is not None else get_registry()
        self._m_recompiles = reg.counter(
            "rtfds_xla_recompiles_total",
            "XLA compilations observed during step calls after warmup "
            "(steady-state serving should hold this at 0)")
        self._seen: dict = {}   # signature -> first call index
        self._calls = 0
        self._last_sig: Optional[Tuple] = None

    @property
    def calls(self) -> int:
        return self._calls

    @property
    def recompiles(self) -> float:
        return self._m_recompiles.value

    def step(self, signature: Tuple) -> _StepWindow:
        """Wrap one jitted step call::

            with detector.step(step_signature(jbatch, static=("donate0",))):
                out = self._step(...)
        """
        return _StepWindow(self, signature)

    def _diff(self, sig: Tuple) -> str:
        """Human diff of ``sig`` vs the previous call's signature."""
        prev = self._last_sig
        if prev is None:
            return f"first signature: {sig}"
        if prev == sig:
            return (f"signature unchanged ({sig}) — the retrace is keyed "
                    "on something outside the tracked signature "
                    "(input sharding, weak types, or donation)")
        changed = []
        for i in range(max(len(prev), len(sig))):
            a = prev[i] if i < len(prev) else "<absent>"
            b = sig[i] if i < len(sig) else "<absent>"
            if a != b:
                changed.append(f"arg[{i}]: {a} -> {b}")
        return "; ".join(changed) or f"{prev} -> {sig}"

    def _after_call(self, sig: Tuple, compiles: int) -> None:
        self._calls += 1
        new_sig = sig not in self._seen
        if compiles and self._calls > self.warmup_calls:
            self._m_recompiles.inc(compiles)
            log.warning(
                "%s recompiled at call %d (%d compile%s after a "
                "%d-call warmup): %s",
                self.name, self._calls, compiles,
                "s" if compiles > 1 else "", self.warmup_calls,
                self._diff(sig))
            from real_time_fraud_detection_system_tpu.utils.trace import (
                get_tracer,
            )

            tracer = get_tracer()
            if tracer.enabled:
                tracer.instant("xla_recompile", call=self._calls,
                               signature=str(sig), diff=self._diff(sig))
        if new_sig:
            self._seen[sig] = self._calls
        self._last_sig = sig


class DeviceMemoryTelemetry:
    """Per-batch ``rtfds_device_memory_bytes{kind=in_use|peak}`` gauges.

    Samples ``device.memory_stats()`` for every local device. Backends
    that return no stats (CPU) are detected on the first sample and the
    instance turns itself off — the steady-state cost on such backends
    is a single boolean check per batch."""

    # memory_stats() key -> gauge `kind` label
    _KINDS = (("bytes_in_use", "in_use"), ("peak_bytes_in_use", "peak"))

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._reg = registry if registry is not None else get_registry()
        self._devices = None
        self._gauges: dict = {}
        self._dead = False

    def sample(self) -> None:
        if self._dead:
            return
        if self._devices is None:
            try:
                import jax

                self._devices = jax.local_devices()
            except (ImportError, RuntimeError, AttributeError):
                # AttributeError: partially-broken jax (import succeeds,
                # local_devices missing) — this runs per batch on the
                # loop thread, so telemetry self-disables, never crashes
                self._dead = True
                return
        any_stats = False
        for i, d in enumerate(self._devices):
            try:
                stats = d.memory_stats()
            # rtfdslint: disable=broad-exception-catch (memory_stats is a per-backend C++ binding that can raise arbitrary plugin errors; telemetry must sample-or-skip, never kill the batch)
            except Exception:
                stats = None
            if not stats:
                continue
            any_stats = True
            for key, kind in self._KINDS:
                v = stats.get(key)
                if v is None:
                    continue
                g = self._gauges.get((i, kind))
                if g is None:
                    g = self._reg.gauge(
                        "rtfds_device_memory_bytes",
                        "device memory from memory_stats(), sampled "
                        "per batch", device=str(i), kind=kind)
                    self._gauges[(i, kind)] = g
                g.set(float(v))
        if not any_stats:
            self._dead = True  # CPU-style backend: stop sampling
