"""Per-batch trace spans with Chrome-trace/Perfetto export.

The metrics registry (``utils/metrics.py``) answers *how long* each phase
takes in aggregate; this module answers *what happened inside a batch*:
every phase of a micro-batch becomes a named span under that batch's
trace id, completed spans land in a bounded in-memory ring buffer, and
the buffer exports as Chrome-trace (catapult) JSON — the format
Perfetto, ``chrome://tracing``, and TensorBoard's trace viewer all load.
Each live host span is additionally wrapped in
``jax.profiler.TraceAnnotation`` (when jax is importable), so a
``jax.profiler`` device capture taken over the same run shows the host
phases aligned with the XLA device timeline in one view.

Design constraints, in order:

1. **Disabled is free.** The serving hot loop calls :meth:`Tracer.span`
   per phase whether or not anyone is tracing; the disabled path is one
   attribute check returning a shared no-op context manager (measured
   ~0.1 µs/span, bounded by ``tests/test_trace.py``).
2. **Enabled is cheap.** A span is two ``perf_counter`` reads, one small
   object, and a deque append — no locks on the single-threaded engine
   loop path beyond the deque's internal thread safety; ~2-5 µs/span,
   <50 µs for a full 7-span batch.
3. **Bounded.** The ring buffer holds the most recent ``capacity``
   completed spans (default 16384 ≈ 2000+ batches of 7 spans); long
   ``score`` runs cannot grow host memory.
4. **Stdlib-only import.** jax is imported lazily and only when
   annotation is possible; the module stays importable from any process
   (the same contract as ``utils/metrics.py``).

Usage::

    tracer = get_tracer()
    tracer.configure(enabled=True)
    tid = tracer.begin_batch(42)            # per-batch trace id "b00000042"
    with tracer.span("host_prep", rows=4096):
        ...
    tracer.export("trace.json")             # load in ui.perfetto.dev
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Span",
    "Tracer",
    "get_tracer",
    "current_ids",
    "summarize_chrome",
]


class Span:
    """One completed span: name, trace id, [t0, t1) in tracer-relative
    seconds, owning thread, and free-form args."""

    __slots__ = ("name", "trace_id", "batch", "t0", "t1", "tid", "args")

    def __init__(self, name: str, trace_id: str, batch: int,
                 t0: float, t1: float, tid: int, args: Optional[dict]):
        self.name = name
        self.trace_id = trace_id
        self.batch = batch
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.args = args

    @property
    def dur_s(self) -> float:
        return self.t1 - self.t0


class _NoopSpan:
    """Shared disabled-path context manager: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """Enabled-path context manager: records the span on exit and keeps
    an optional ``jax.profiler.TraceAnnotation`` open for its duration so
    host phases line up with the device timeline in a jax trace."""

    __slots__ = ("_tracer", "_name", "_trace_id", "_batch", "_args",
                 "_t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 batch: int, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._trace_id = trace_id
        self._batch = batch
        self._args = args
        self._ann = None

    def __enter__(self):
        ann_cls = self._tracer._annotation_cls
        if ann_cls is not None:
            # name#batch keeps repeated phases distinguishable on the
            # profiler timeline without exploding the name cardinality
            self._ann = ann_cls(f"rtfds.{self._name}#{self._batch}")
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer._record(Span(
            self._name, self._trace_id, self._batch,
            self._t0 - self._tracer._t0, t1 - self._tracer._t0,
            threading.get_ident(), self._args))
        return False


class Tracer:
    """Span collector with per-batch trace ids and a bounded ring buffer.

    The engine loop is single-threaded, so the "current batch" context is
    a plain attribute (spans from other threads — the metrics server, a
    supervisor — attribute to whatever batch is current, which is the
    honest answer for a process-wide timeline). Spans may also name
    their batch explicitly (``span(..., batch=...)``) — the pipelined
    engine does this for ``result_wait``/``sink_write``, which complete
    for batch N while batch N+k is already current.
    """

    def __init__(self, capacity: int = 16384, enabled: bool = False):
        self.enabled = bool(enabled)
        self._buf: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()  # buffer swaps/exports only
        self._t0 = time.perf_counter()
        self._epoch_unix_s = time.time()
        self._cur_id = ""
        self._cur_batch = 0
        self._seq = 0
        self._annotation_cls = None
        self._m_spans = None  # rtfds_trace_spans_total, resolved lazily

    # -- configuration -------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  annotate: bool = True) -> "Tracer":
        """Enable/disable and (re)size the buffer. ``annotate=True``
        wires ``jax.profiler.TraceAnnotation`` around live spans when
        jax is importable; pass False for jax-free processes."""
        if capacity is not None and capacity != self._buf.maxlen:
            with self._lock:
                self._buf = deque(self._buf, maxlen=int(capacity))
        if enabled is not None:
            self.enabled = bool(enabled)
        if self.enabled and annotate and self._annotation_cls is None:
            try:
                import jax

                self._annotation_cls = jax.profiler.TraceAnnotation
            except (ImportError, AttributeError, RuntimeError):
                # stdlib-only process, or a broken jax/jaxlib pairing
                # (raises RuntimeError at import): tracing degrades to
                # plain spans, never kills the run
                self._annotation_cls = None
        if not annotate:
            self._annotation_cls = None
        if self.enabled and self._m_spans is None:
            from real_time_fraud_detection_system_tpu.utils.metrics import (
                get_registry,
            )

            self._m_spans = get_registry().counter(
                "rtfds_trace_spans_total", "completed trace spans recorded")
        return self

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    def __len__(self) -> int:
        return len(self._buf)

    # -- trace-id context ----------------------------------------------

    def begin_batch(self, batch_index: Optional[int] = None) -> str:
        """Start a new per-batch trace id; subsequent spans attribute to
        it. Returns the id ("" when disabled — callers can cheaply skip
        cross-referencing it into flight records)."""
        if not self.enabled:
            return ""
        if batch_index is None:
            self._seq += 1
            batch_index = self._seq
        self._cur_batch = int(batch_index)
        self._cur_id = f"b{int(batch_index):08d}"
        return self._cur_id

    def current_ids(self) -> Tuple[str, int]:
        """→ (trace_id, batch_index) of the current batch ("" / 0 when
        disabled or before the first batch). The JSON log formatter uses
        this for log↔span correlation."""
        return (self._cur_id, self._cur_batch) if self.enabled else ("", 0)

    # -- span recording ------------------------------------------------

    def span(self, name: str, batch: Optional[str] = None, **args):
        """Context manager for a live span. ``batch`` overrides the
        current trace id (the pipelined engine finishes batch N while
        batch N+k is current). Extra kwargs land in the exported event's
        ``args``."""
        if not self.enabled:
            return _NOOP
        if batch is None:
            trace_id, bidx = self._cur_id, self._cur_batch
        else:
            trace_id = batch
            try:
                bidx = int(batch.lstrip("b")) if batch else 0
            except ValueError:
                bidx = 0
        return _LiveSpan(self, name, trace_id, bidx, args or None)

    def add_span(self, name: str, t0_perf: float, t1_perf: float,
                 batch: Optional[str] = None, **args) -> None:
        """Record an already-measured span from raw ``perf_counter``
        readings — for call sites that already timed the work (source
        polls, sink writes) and must not pay a second pair of clock
        reads. No TraceAnnotation (the work already happened)."""
        if not self.enabled:
            return
        trace_id = self._cur_id if batch is None else batch
        bidx = self._cur_batch
        if batch is not None:
            try:
                bidx = int(batch.lstrip("b")) if batch else 0
            except ValueError:
                bidx = 0
        self._record(Span(name, trace_id, bidx, t0_perf - self._t0,
                          t1_perf - self._t0, threading.get_ident(),
                          args or None))

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (recompile events, model reloads)."""
        if not self.enabled:
            return
        t = time.perf_counter() - self._t0
        self._record(Span(name, self._cur_id, self._cur_batch, t, t,
                          threading.get_ident(), args or None))

    def _record(self, span: Span) -> None:
        self._buf.append(span)  # deque append is atomic + O(1) eviction
        if self._m_spans is not None:
            self._m_spans.inc()

    # -- export --------------------------------------------------------

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def export_chrome(self) -> dict:
        """→ Chrome-trace (catapult) JSON object: ``{"traceEvents":
        [...], "displayTimeUnit": "ms", ...}``. Events are complete
        ("ph": "X") spans with µs timestamps, sorted by ``ts`` so any
        streaming consumer sees a monotone timeline; per-batch trace ids
        ride in ``args.trace_id``. Loadable in ui.perfetto.dev /
        chrome://tracing as-is."""
        import os

        pid = os.getpid()
        spans = self.snapshot()
        events: List[dict] = [{
            # process metadata: names the track in Perfetto's UI
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": "rtfds"},
        }]
        for s in sorted(spans, key=lambda s: s.t0):
            ev = {
                "ph": "X",
                "name": s.name,
                "cat": "rtfds",
                "ts": round(s.t0 * 1e6, 3),     # µs, tracer-relative
                "dur": round((s.t1 - s.t0) * 1e6, 3),
                "pid": pid,
                "tid": s.tid,
                "args": {"trace_id": s.trace_id, "batch": s.batch},
            }
            if s.args:
                ev["args"].update(s.args)
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "rtfds",
                # an empty /trace response must say WHY it is empty
                "tracing_enabled": self.enabled,
                "epoch_unix_s": self._epoch_unix_s,
                "spans_dropped_by_ring": max(
                    0, (self._m_spans.value if self._m_spans else 0)
                    - len(spans)),
            },
        }

    def export(self, path: str) -> dict:
        """Write the Chrome-trace JSON to ``path``; returns a small
        manifest (path, event count) for CLI printing."""
        trace = self.export_chrome()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f, separators=(",", ":"))
        return {"trace": path, "events": len(trace["traceEvents"])}


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer every layer records into (disabled until
    ``configure(enabled=True)`` — the CLI's ``--trace-out`` does that)."""
    return _default_tracer


def current_ids() -> Tuple[str, int]:
    """(trace_id, batch_index) of the default tracer's current batch —
    the log formatter's hook (see ``utils/logging.py``)."""
    return _default_tracer.current_ids()


# ---------------------------------------------------------------------------
# Trace analysis (the `rtfds trace` subcommand's engine)
# ---------------------------------------------------------------------------

def _batch_events(events: List[dict]) -> Dict[str, List[dict]]:
    """Group duration events by their per-batch trace id (events with no
    trace id — compiles outside any batch — group under "")."""
    by: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        tid = str((ev.get("args") or {}).get("trace_id", ""))
        by.setdefault(tid, []).append(ev)
    return by


def summarize_chrome(trace: dict, top_k: int = 10) -> dict:
    """Digest a Chrome-trace JSON object (as exported above) into the
    per-batch critical path, the top-K slowest spans, and the XLA
    compile/recompile events — everything ``rtfds trace`` prints.

    Per batch: total span time, per-phase durations, and the *critical
    phase* (the longest span — in a serial per-batch waterfall that IS
    the critical path's dominant edge)."""
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"]
    batches = []
    for tid, evs in sorted(_batch_events(events).items()):
        if not tid:
            continue
        phases: Dict[str, float] = {}
        for e in evs:
            phases[e["name"]] = phases.get(e["name"], 0.0) \
                + float(e.get("dur", 0.0))
        crit = max(phases.items(), key=lambda kv: kv[1]) \
            if phases else ("", 0.0)
        batches.append({
            "trace_id": tid,
            "batch": (evs[0].get("args") or {}).get("batch"),
            "total_ms": round(sum(phases.values()) / 1e3, 3),
            "critical_phase": crit[0],
            "critical_ms": round(crit[1] / 1e3, 3),
            "phases_ms": {k: round(v / 1e3, 3)
                          for k, v in sorted(phases.items())},
        })
    slowest = sorted(events, key=lambda e: -float(e.get("dur", 0.0)))
    top = [{
        "name": e["name"],
        "dur_ms": round(float(e.get("dur", 0.0)) / 1e3, 3),
        "trace_id": (e.get("args") or {}).get("trace_id", ""),
        "ts_ms": round(float(e.get("ts", 0.0)) / 1e3, 3),
    } for e in slowest[:top_k]]
    compiles = [{
        "name": e["name"],
        "dur_ms": round(float(e.get("dur", 0.0)) / 1e3, 3),
        "trace_id": (e.get("args") or {}).get("trace_id", ""),
        "args": {k: v for k, v in (e.get("args") or {}).items()
                 if k not in ("trace_id", "batch")},
    } for e in events if e["name"] in ("xla_compile", "xla_recompile")]
    return {
        "batches": batches,
        "slowest_spans": top,
        "compile_events": compiles,
        "n_events": len(events),
    }
