from real_time_fraud_detection_system_tpu.utils.timing import (  # noqa: F401
    LatencyTracker,
    Timer,
    date_to_epoch_s,
)
from real_time_fraud_detection_system_tpu.utils.logging import (  # noqa: F401
    get_logger,
)
from real_time_fraud_detection_system_tpu.utils.tracing import (  # noqa: F401
    enable_compilation_cache,
    trace_span,
    profile_to,
)
from real_time_fraud_detection_system_tpu.utils.metrics import (  # noqa: F401
    LATENCY_BUCKETS_S,
    FlightRecorder,
    MetricsRegistry,
    MetricsServer,
    active_recorder,
    get_registry,
    run_manifest,
    set_active_recorder,
)
from real_time_fraud_detection_system_tpu.utils.trace import (  # noqa: F401
    Tracer,
    get_tracer,
    summarize_chrome,
)
from real_time_fraud_detection_system_tpu.utils.xla_telemetry import (  # noqa: F401,E501
    DeviceMemoryTelemetry,
    RecompileDetector,
    install_compile_telemetry,
    step_signature,
)
