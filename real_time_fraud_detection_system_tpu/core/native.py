"""ctypes loader for the C++ envelope decoder (``native/envelope.cc``).

Compiles the shared library on first use (g++ available in the image; the
build is one translation unit, <1 s) and caches the handle. All callers go
through :func:`decode_transaction_envelopes_native`, which has the same
interface as the pure-Python
:func:`..core.envelope.decode_transaction_envelopes` — the dispatcher there
prefers this path when available.

Validity contract (differential-fuzz-pinned, ``tests/test_native.py``):
the scanner extracts the required payload fields WITHOUT validating the
whole JSON document — that is what makes it line-rate. Consequently it is
strictly MORE lenient than the Python decoder: every message the scanner
rejects, the strict parser rejects too, and on messages both accept the
decoded columns are bit-identical; but a message whose required fields are
intact inside otherwise-broken JSON (truncated tail, garbage between
tokens) decodes here and is rejected by the strict parser. For
well-formed Debezium traffic the two are exactly equivalent. (The scanner
also does not un-escape ``\\uXXXX`` key names — Debezium never emits
them.)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np


class NativeUnavailableError(RuntimeError):
    """The native .so could not be built/loaded in this process.

    A deploy/toolchain condition, not a data fault: callers gate via
    :func:`native_available` / :func:`hostprep_available`, so reaching
    this raise means a caller skipped the gate — fail fast with a type
    the supervisor taxonomy can tell apart from a jax-internal
    RuntimeError (subclasses RuntimeError for back-compat with any
    external catcher)."""


_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _slab_hist():
    """Per-slab decode-time histogram. Resolved once per decode BATCH
    (one get-or-create under the registry lock, ~µs at batch
    granularity), not cached module-level: the process registry can be
    cleared between runs and a cached series would go orphan."""
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        get_registry,
    )

    return get_registry().histogram(
        "rtfds_decode_slab_seconds",
        "wall time of one ingest-decode slab (a contiguous envelope "
        "range scanned by one worker)")


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _build_and_load(name: str, configure) -> "Tuple[Optional[ctypes.CDLL], Optional[str]]":
    """Shared compile-on-first-use recipe for every native unit:
    recompile when the source is newer than the .so, load via ctypes,
    hand the handle to ``configure(lib)`` for argtype setup, and report
    (lib, None) or (None, error). Caller holds ``_lock``."""
    src = os.path.join(_repo_root(), "native", f"{name}.cc")
    so = os.path.join(_repo_root(), "native", f"lib{name}.so")
    try:
        if not os.path.exists(so) or \
                os.path.getmtime(so) < os.path.getmtime(src):
            # rtfdslint: disable=blocking-call-on-loop-thread (one-time native build on first decode; .so is cached for the process/filesystem lifetime)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", so, src],
                check=True, capture_output=True, text=True, timeout=120,
            )
        lib = ctypes.CDLL(so)
        configure(lib)
        return lib, None
    except (subprocess.CalledProcessError, OSError,
            subprocess.TimeoutExpired) as exc:
        return None, str(exc)


def _configure_envelope(lib) -> None:
    out_cols = [
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    ] * 5 + [
        np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
    ]
    lib.decode_envelopes.restype = ctypes.c_int64
    lib.decode_envelopes.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_int64,
    ] + out_cols


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is None and _build_error is None:
            _lib, _build_error = _build_and_load(
                "envelope", _configure_envelope)
        return _lib


def native_available() -> bool:
    return _load() is not None


_pools: dict = {}  # worker count -> ThreadPoolExecutor
_AUTO_WORKERS = min(8, os.cpu_count() or 1)
_decode_workers = 0  # 0 = auto (_AUTO_WORKERS)
_PARALLEL_MIN = 8192  # below this, thread fan-out costs more than it saves


def set_decode_workers(n: int) -> int:
    """Set the process-wide ingest-decode worker count (0 = auto:
    min(8, cores); 1 = serial). Returns the resolved count. The pool is
    rebuilt lazily on the next decode, so this is safe to call between
    runs (the CLI calls it once at startup from --decode-workers)."""
    global _decode_workers
    n = max(0, int(n))
    with _lock:
        _decode_workers = n
    resolved = n or _AUTO_WORKERS
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        get_registry,
    )

    get_registry().gauge(
        "rtfds_decode_workers",
        "configured ingest-decode worker threads").set(resolved)
    return resolved


def get_decode_workers() -> int:
    """The resolved decode worker count (auto applied)."""
    return _decode_workers or _AUTO_WORKERS


def _get_pool(workers: int):
    """Decode pool for ``workers``, one per distinct size. Never shut
    down on a size change: another thread (a prefetch producer, a
    concurrent bench variant) may be mid-``pool.map`` on the old pool,
    and a shutdown there raises into ITS in-flight decode. Distinct
    sizes in one process are a handful (explicit test/bench overrides +
    the configured serving count), so the idle-thread cost is bounded."""
    with _lock:
        pool = _pools.get(workers)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(workers,
                                      thread_name_prefix="envelope-decode")
            _pools[workers] = pool
        return pool


def decode_envelopes_slab(
    buf: bytes,
    offsets: np.ndarray,
    a: int,
    b: int,
    tx_id: np.ndarray,
    t_us: np.ndarray,
    cust: np.ndarray,
    term: np.ndarray,
    cents: np.ndarray,
    op: np.ndarray,
    valid: np.ndarray,
) -> None:
    """Decode envelopes [a, b) of one packed byte-batch into rows [a, b)
    of the output columns — the per-worker unit of the parallel decode.
    ``offsets`` is the full absolute offset table (n+1 entries into
    ``buf``); each slab writes a disjoint slice of the shared columnar
    staging arrays, so concurrent slabs never contend. Public so tests
    can pin per-slab exactness against the whole-batch decode."""
    lib = _load()
    if lib is None:
        raise NativeUnavailableError(
            f"native decoder unavailable: {_build_error}")
    if b > a:
        lib.decode_envelopes(
            buf, offsets[a : b + 1], b - a,
            tx_id[a:b], t_us[a:b], cust[a:b], term[a:b], cents[a:b],
            op[a:b], valid[a:b],
        )


def decode_transaction_envelopes_native(
    messages: Iterable[bytes],
    kafka_timestamps_ms: Optional[Sequence[int]] = None,
    workers: Optional[int] = None,
) -> Tuple[dict, np.ndarray]:
    """Columnar decode via the C++ scanner. Same contract as the Python
    decoder; raises RuntimeError if the native library is unavailable.

    Large batches are sharded into contiguous offset slabs decoded
    concurrently over a thread pool (:func:`decode_envelopes_slab`): the
    ctypes call releases the GIL, the offset table is absolute into one
    shared packed buffer, and each slab writes a disjoint slice of the
    preallocated columnar staging arrays — the scan scales with cores
    (SURVEY's host-ingress hard part: 1M txns/s of JSON would bottleneck
    on a single-threaded parse before the TPU). ``workers`` overrides
    the process-wide :func:`set_decode_workers` setting for this call
    (1 = serial); per-slab wall time lands in
    ``rtfds_decode_slab_seconds``. The packed-buffer join beats a
    zero-copy pointer array here: building a ctypes ``c_char_p`` array
    costs ~2× the join (measured 108 ms vs 54 ms at 200k messages)."""
    lib = _load()
    if lib is None:
        raise NativeUnavailableError(
            f"native decoder unavailable: {_build_error}")
    msgs: List[bytes] = (
        messages if isinstance(messages, list) else list(messages)
    )
    n = len(msgs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    if n:
        np.cumsum(
            np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n),
            out=offsets[1:],
        )
    buf = b"".join(msgs)

    tx_id = np.zeros(n, dtype=np.int64)
    t_us = np.zeros(n, dtype=np.int64)
    cust = np.zeros(n, dtype=np.int64)
    term = np.zeros(n, dtype=np.int64)
    cents = np.zeros(n, dtype=np.int64)
    op = np.zeros(n, dtype=np.int8)
    valid = np.zeros(n, dtype=np.uint8)

    n_workers = max(1, int(workers) if workers else get_decode_workers())
    outs = (tx_id, t_us, cust, term, cents, op, valid)
    slab_hist = _slab_hist()

    def _scan(a: int, b: int) -> None:
        t0 = time.perf_counter()
        decode_envelopes_slab(buf, offsets, a, b, *outs)
        slab_hist.observe(time.perf_counter() - t0)

    if n >= _PARALLEL_MIN and n_workers > 1:
        bounds = np.linspace(0, n, n_workers + 1, dtype=np.int64)
        list(_get_pool(n_workers).map(
            lambda ab: _scan(int(ab[0]), int(ab[1])),
            zip(bounds[:-1], bounds[1:]),
        ))
    else:
        _scan(0, n)

    if kafka_timestamps_ms is None:
        kts = t_us // 1000
    else:
        kts = np.asarray(kafka_timestamps_ms, dtype=np.int64)
    cols = {
        "tx_id": tx_id,
        "tx_datetime_us": t_us,
        "customer_id": cust,
        "terminal_id": term,
        "tx_amount_cents": cents,
        "op": op,
        "kafka_ts_ms": kts,
    }
    return cols, valid == 0


# ---------------------------------------------------------------------------
# host-prep library (native/hostprep.cc): dedup + pack for the serving loop
# ---------------------------------------------------------------------------

_hp_lib: Optional[ctypes.CDLL] = None
_hp_error: Optional[str] = None


def _configure_hostprep(lib) -> None:
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.latest_wins_keep.restype = ctypes.c_int64
    lib.latest_wins_keep.argtypes = [
        i64p, i64p, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
    ]
    lib.pack_rows.restype = None
    lib.pack_rows.argtypes = [
        i64p, i64p, i64p, i64p,
        ctypes.c_void_p,  # label, nullable
        ctypes.c_int64, ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
    ]


def _load_hostprep() -> Optional[ctypes.CDLL]:
    global _hp_lib, _hp_error
    with _lock:
        if _hp_lib is None and _hp_error is None:
            _hp_lib, _hp_error = _build_and_load(
                "hostprep", _configure_hostprep)
        return _hp_lib


def hostprep_available() -> bool:
    return _load_hostprep() is not None


def latest_wins_keep(tx_id: np.ndarray, ts: np.ndarray) -> np.ndarray:
    """bool [n] latest-wins mask (same semantics as
    ops.dedup.latest_wins_mask_np with all rows valid), O(n) hash pass."""
    lib = _load_hostprep()
    if lib is None:
        raise NativeUnavailableError(
            f"native hostprep unavailable: {_hp_error}")
    n = len(tx_id)
    keep = np.zeros(n, dtype=np.uint8)
    if n:
        lib.latest_wins_keep(
            np.ascontiguousarray(tx_id, np.int64),
            np.ascontiguousarray(ts, np.int64), n, keep)
    return keep.view(bool)


def pack_rows(
    tx_datetime_us: np.ndarray,
    customer_id: np.ndarray,
    terminal_id: np.ndarray,
    amount_cents: np.ndarray,
    label: Optional[np.ndarray],
    pad: int,
) -> np.ndarray:
    """Fused make_batch + pack_batch: → int32 [7, pad] (zeros-padded),
    bit-identical to the NumPy composition (tests/test_native.py)."""
    lib = _load_hostprep()
    if lib is None:
        raise NativeUnavailableError(
            f"native hostprep unavailable: {_hp_error}")
    n = len(tx_datetime_us)
    if pad < n:
        raise ValueError(f"pad={pad} < batch rows {n}")
    packed = np.empty((7, pad), dtype=np.int32)
    lab = (np.ascontiguousarray(label, np.int64)
           if label is not None else None)
    lib.pack_rows(
        np.ascontiguousarray(tx_datetime_us, np.int64),
        np.ascontiguousarray(customer_id, np.int64),
        np.ascontiguousarray(terminal_id, np.int64),
        np.ascontiguousarray(amount_cents, np.int64),
        lab.ctypes.data if lab is not None else None,
        n, pad, packed)
    return packed
