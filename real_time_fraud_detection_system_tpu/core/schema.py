"""Typed table schemas for the payment domain.

Mirrors the reference OLTP DDL (``postgres/init.sql:8-42``) and the scorer's
output table (``pyspark/scripts/fraud_detection.py:136-163``,
``analyzed_transactions``). Money is int64 **cents** in memory (DECIMAL(10,2)
fidelity); timestamps are int64 µs since the unix epoch (the Debezium
MicroTimestamp wire unit, ``kafka_s3_sink_transactions.py:167``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass(frozen=True)
class TableSchema:
    name: str
    key: str
    fields: Tuple[Tuple[str, str], ...]  # (name, numpy dtype str)

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(list(self.fields))

    @property
    def columns(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.fields)

    def empty(self, n: int = 0) -> dict:
        return {name: np.zeros(n, dtype=dt) for name, dt in self.fields}


CUSTOMERS = TableSchema(
    name="customers",
    key="customer_id",
    fields=(
        ("customer_id", "int64"),
        ("x_location", "float64"),
        ("y_location", "float64"),
    ),
)

TERMINALS = TableSchema(
    name="terminals",
    key="terminal_id",
    fields=(
        ("terminal_id", "int64"),
        ("x_location", "float64"),
        ("y_location", "float64"),
    ),
)

TRANSACTIONS = TableSchema(
    name="transactions",
    key="tx_id",
    fields=(
        ("tx_id", "int64"),
        ("tx_datetime_us", "int64"),  # µs since unix epoch
        ("customer_id", "int64"),
        ("terminal_id", "int64"),
        ("tx_amount_cents", "int64"),  # DECIMAL(10,2) as integer cents
    ),
)

# Output sink schema — analytic row per scored transaction, column-compatible
# with the reference's ``nessie.payment.analyzed_transactions`` so that the
# downstream Trino/Superset stack keeps working unchanged.
ANALYZED_TRANSACTIONS_FIELDS = (
    ("tx_id", "int64"),
    ("tx_datetime_us", "int64"),
    ("customer_id", "int64"),
    ("terminal_id", "int64"),
    ("tx_amount", "float64"),
    ("tx_during_weekend", "int32"),
    ("tx_during_night", "int32"),
    ("customer_id_nb_tx_1day_window", "int32"),
    ("customer_id_avg_amount_1day_window", "float64"),
    ("customer_id_nb_tx_7day_window", "int32"),
    ("customer_id_avg_amount_7day_window", "float64"),
    ("customer_id_nb_tx_30day_window", "int32"),
    ("customer_id_avg_amount_30day_window", "float64"),
    ("terminal_id_nb_tx_1day_window", "int32"),
    ("terminal_id_risk_1day_window", "float64"),
    ("terminal_id_nb_tx_7day_window", "int32"),
    ("terminal_id_risk_7day_window", "float64"),
    ("terminal_id_nb_tx_30day_window", "int32"),
    ("terminal_id_risk_30day_window", "float64"),
    ("processed_at_us", "int64"),
    ("prediction", "float64"),
)
