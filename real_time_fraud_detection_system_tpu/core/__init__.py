from real_time_fraud_detection_system_tpu.core.schema import (  # noqa: F401
    ANALYZED_TRANSACTIONS_FIELDS,
    CUSTOMERS,
    TERMINALS,
    TRANSACTIONS,
    TableSchema,
)
from real_time_fraud_detection_system_tpu.core.envelope import (  # noqa: F401
    decode_decimal_bytes,
    decode_transaction_envelopes,
    encode_decimal_cents,
    encode_transaction_envelope,
)
from real_time_fraud_detection_system_tpu.core.batch import (  # noqa: F401
    TxBatch,
    bucket_size,
    make_batch,
    pad_batch,
)
