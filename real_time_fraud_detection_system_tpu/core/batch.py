"""Device micro-batch representation.

A ``TxBatch`` is the columnar unit of work the jitted step consumes — the
TPU-side analogue of one Spark micro-batch DataFrame (reference
``foreachBatch``, ``kafka_s3_sink_transactions.py:160``). Ragged stream
batches are padded to a small set of bucket sizes so the jit cache stays warm
(SURVEY §7 "ragged micro-batches").

Device arrays are 32-bit on purpose (TPU-friendly, no jax x64 flag):
timestamps are carried as (day, second-of-day) pairs instead of µs epochs;
64-bit identifiers stay host-side and rows are re-joined by position after
scoring. Weekday/night flags derive in-kernel from (day, tod_s).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

US_PER_DAY = 86_400_000_000


class TxBatch(NamedTuple):
    """Columnar transaction micro-batch (pytree of device arrays).

    All arrays have leading dim B (padded bucket size). ``valid`` masks the
    padding; padded rows never touch state or sinks.
    """

    customer_key: jnp.ndarray  # uint32 [B] — hashed/truncated customer id
    terminal_key: jnp.ndarray  # uint32 [B]
    day: jnp.ndarray  # int32 [B] — days since unix epoch
    tod_s: jnp.ndarray  # int32 [B] — second within day
    amount: jnp.ndarray  # float32 [B] — dollars (display/features)
    label: jnp.ndarray  # int32 [B] — -1 unknown, else 0/1 fraud
    valid: jnp.ndarray  # bool [B]

    @property
    def size(self) -> int:
        return int(self.customer_key.shape[0])


def bucket_size(n: int, buckets: Sequence[int]) -> int:
    """Smallest configured bucket that fits n rows (largest bucket if none)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def fold_key(ids: np.ndarray) -> np.ndarray:
    """Fold int64 ids to uint32 keys (xor-fold hi/lo words)."""
    v = ids.astype(np.uint64)
    return ((v ^ (v >> np.uint64(32))) & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def make_batch(
    customer_id: np.ndarray,
    terminal_id: np.ndarray,
    tx_datetime_us: np.ndarray,
    amount_cents: np.ndarray,
    label: Optional[np.ndarray] = None,
    pad_to: Optional[int] = None,
) -> TxBatch:
    """Build a (host-side numpy) TxBatch from columnar int64 inputs."""
    n = len(customer_id)
    m = pad_to if pad_to is not None else n
    if m < n:
        raise ValueError(f"pad_to={m} < batch rows {n}")

    def _pad(a: np.ndarray) -> np.ndarray:
        out = np.zeros(m, dtype=a.dtype)
        out[:n] = a
        return out

    day = (tx_datetime_us // US_PER_DAY).astype(np.int32)
    tod = ((tx_datetime_us % US_PER_DAY) // 1_000_000).astype(np.int32)
    lab = (label if label is not None else np.full(n, -1)).astype(np.int32)
    valid = np.zeros(m, dtype=bool)
    valid[:n] = True
    return TxBatch(
        customer_key=_pad(fold_key(customer_id)),
        terminal_key=_pad(fold_key(terminal_id)),
        day=_pad(day),
        tod_s=_pad(tod),
        amount=_pad((amount_cents.astype(np.float64) / 100.0).astype(np.float32)),
        label=_pad(lab),
        valid=valid,
    )


def pad_batch(batch: TxBatch, pad_to: int) -> TxBatch:
    """Pad an existing (numpy) TxBatch up to ``pad_to`` rows."""
    n = batch.size
    if pad_to == n:
        return batch
    if pad_to < n:
        raise ValueError(f"pad_to={pad_to} < batch rows {n}")

    def _pad(a):
        a = np.asarray(a)
        out = np.zeros((pad_to,) + a.shape[1:], dtype=a.dtype)
        out[:n] = a
        return out

    return TxBatch(*[_pad(x) for x in batch])


def pack_batch(batch: TxBatch) -> np.ndarray:
    """Host-side TxBatch → ONE int32 array [7, B] for a single H2D copy.

    Each device transfer pays a per-call overhead (an RPC round trip when
    the chip sits behind a remote tunnel; a dispatch otherwise), so moving
    a batch as 7 separate leaves costs 7× the fixed overhead of moving it
    as one array. uint32 keys and float32 amounts travel as their int32
    bit patterns; :func:`unpack_batch` bitcasts them back inside jit, so
    the round trip is exact.
    """
    return np.stack([
        np.asarray(batch.customer_key).view(np.int32),
        np.asarray(batch.terminal_key).view(np.int32),
        np.asarray(batch.day),
        np.asarray(batch.tod_s),
        np.asarray(batch.amount).view(np.int32),
        np.asarray(batch.label),
        np.asarray(batch.valid).astype(np.int32),
    ])


def unpack_batch(packed: jnp.ndarray) -> TxBatch:
    """Device-side inverse of :func:`pack_batch` (inside jit; free after
    XLA fusion — bitcasts and a compare, no copies of consequence)."""
    import jax

    bitcast = jax.lax.bitcast_convert_type
    return TxBatch(
        customer_key=bitcast(packed[0], jnp.uint32),
        terminal_key=bitcast(packed[1], jnp.uint32),
        day=packed[2],
        tod_s=packed[3],
        amount=bitcast(packed[4], jnp.float32),
        label=packed[5],
        valid=packed[6] != 0,
    )
