"""Debezium CDC envelope codec — vectorized host-side decode.

The reference consumes Debezium JSON envelopes from Kafka and decodes them
row-at-a-time in Spark UDFs: the big-endian signed unscaled-int encoding of
``DECIMAL(10,2)`` (``kafka_s3_sink_transactions.py:63-73``) and µs-epoch
timestamps (``:167``). Here the decode is columnar: parse the JSON envelopes,
gather the base64 amount payloads, and convert ALL amounts in one NumPy pass
(pad-to-8-bytes sign-extended → big-endian int64 view). A C++ fast path
(``native/envelope.cc``) drops in behind the same function signature for
benchmark ingest rates.

Both directions are implemented — ``encode_*`` builds byte-identical
envelopes for fixtures, replay files, and the synthetic load generator, so
tests can round-trip without a live Debezium.

Envelope shape (reference schema at ``kafka_s3_sink_transactions.py:77-126``)::

    {"schema": {...}, "payload": {"before": ..., "after": {"tx_id": ...,
     "tx_datetime": <µs epoch int>, "customer_id": ..., "terminal_id": ...,
     "tx_amount": "<base64 big-endian signed unscaled int>"},
     "source": {...}, "op": "c"|"u"|"d"|"r", "ts_ms": ...}}
"""

from __future__ import annotations

import base64
import json
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

DECIMAL_SCALE = 2  # DECIMAL(10,2): unscaled int = cents


def encode_decimal_cents(cents: int) -> str:
    """int cents -> base64(big-endian signed minimal bytes), Debezium-style."""
    n = int(cents)
    length = max(1, (n.bit_length() + 8) // 8)  # +8 keeps room for sign bit
    raw = n.to_bytes(length, byteorder="big", signed=True)
    # Minimalize: strip redundant leading sign bytes like Debezium does.
    while len(raw) > 1 and (
        (raw[0] == 0x00 and raw[1] < 0x80) or (raw[0] == 0xFF and raw[1] >= 0x80)
    ):
        raw = raw[1:]
    return base64.b64encode(raw).decode("ascii")


def decode_decimal_bytes(raw: bytes) -> int:
    """big-endian signed bytes -> int cents (scalar reference decoder)."""
    return int.from_bytes(raw, byteorder="big", signed=True)


def decode_decimal_batch(raws: Sequence[bytes]) -> np.ndarray:
    """Vectorized decode of many big-endian signed byte strings to int64 cents.

    One packed pass: join every value into a single byte buffer, view it
    with ``np.frombuffer``, and scatter bytes right-aligned into an
    ``[n, 8]`` grid by (row, column) index arithmetic — no per-row Python
    loop (the old fallback paid a short memcpy + branch per row). Sign
    extension fills the leading pad bytes of negative values with 0xFF in
    one masked assignment, then the grid reinterprets as big-endian
    int64. Bit-identical to the scalar reference decoder and the C++
    scanner (differential-pinned in tests).
    """
    n = len(raws)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    lens = np.fromiter((len(r) for r in raws), dtype=np.int64, count=n)
    if lens.max() > 8:
        raise ValueError(
            f"decimal wider than 8 bytes: {int(lens.max())}")
    flat = np.frombuffer(b"".join(raws), dtype=np.uint8)
    buf = np.zeros((n, 8), dtype=np.uint8)
    if len(flat):
        ends = np.cumsum(lens)
        starts = ends - lens
        # right-aligned scatter: byte j of row i lands at column
        # 8 - len_i + j
        row = np.repeat(np.arange(n), lens)
        col = (np.arange(len(flat)) - np.repeat(starts, lens)
               + np.repeat(8 - lens, lens))
        buf[row, col] = flat
        # sign-extend: rows whose first byte has the sign bit set get
        # their leading pad bytes filled with 0xFF
        nonempty = lens > 0
        first = np.zeros(n, dtype=np.uint8)
        first[nonempty] = flat[starts[nonempty]]
        neg = nonempty & (first >= 0x80)
        pad_cols = np.arange(8)[None, :] < (8 - lens)[:, None]
        buf[neg[:, None] & pad_cols] = 0xFF
    return buf.view(">i8").astype(np.int64).ravel()


def encode_transaction_envelope(
    tx_id: int,
    tx_datetime_us: int,
    customer_id: int,
    terminal_id: int,
    amount_cents: int,
    op: str = "c",
    ts_ms: int = 0,
    before: Optional[dict] = None,
) -> bytes:
    """Build one Debezium-style transaction envelope (fixture/replay format)."""
    after = {
        "tx_id": int(tx_id),
        "tx_datetime": int(tx_datetime_us),
        "customer_id": int(customer_id),
        "terminal_id": int(terminal_id),
        "tx_amount": encode_decimal_cents(amount_cents),
    }
    env = {
        "schema": {"type": "struct", "name": "debezium.payment.transactions.Envelope"},
        "payload": {
            "before": before,
            "after": after,
            "source": {
                "connector": "postgresql",
                "db": "postgres",
                "schema": "payment",
                "table": "transactions",
                "ts_ms": int(ts_ms),
            },
            "op": op,
            "ts_ms": int(ts_ms),
        },
    }
    return json.dumps(env, separators=(",", ":")).encode("utf-8")


def encode_transaction_envelopes(
    tx_id: np.ndarray,
    tx_datetime_us: np.ndarray,
    customer_id: np.ndarray,
    terminal_id: np.ndarray,
    amount_cents: np.ndarray,
    ts_ms: Optional[np.ndarray] = None,
) -> List[bytes]:
    """Columnar arrays -> list of envelope messages (the load-gen hot path)."""
    if ts_ms is None:
        ts_ms = tx_datetime_us // 1000
    return [
        encode_transaction_envelope(i, t, c, m, a, ts_ms=s)
        for i, t, c, m, a, s in zip(
            tx_id.tolist(), tx_datetime_us.tolist(), customer_id.tolist(),
            terminal_id.tolist(), amount_cents.tolist(), ts_ms.tolist()
        )
    ]


def decode_transaction_envelopes(
    messages: Iterable[bytes],
    kafka_timestamps_ms: Optional[Sequence[int]] = None,
) -> Tuple[dict, np.ndarray]:
    """Decode a micro-batch of envelopes into columnar int64 arrays.

    Returns ``(columns, tombstone_mask)`` where columns match the
    ``TRANSACTIONS`` schema plus ``op`` (int8: 0=c,1=u,2=d,3=r) and
    ``kafka_ts_ms``. Delete events (``op=='d'`` with ``after==null``) take
    their row image from ``before``; pure tombstones (null payload) are
    masked out.

    Semantics match the reference sink job's extraction SQL
    (``kafka_s3_sink_transactions.py:160-190``): take ``payload.after``,
    µs-epoch ``tx_datetime``, binary-decimal ``tx_amount``.
    """
    msgs = list(messages)
    n = len(msgs)
    tx_id = np.zeros(n, dtype=np.int64)
    t_us = np.zeros(n, dtype=np.int64)
    cust = np.zeros(n, dtype=np.int64)
    term = np.zeros(n, dtype=np.int64)
    op = np.zeros(n, dtype=np.int8)
    valid = np.zeros(n, dtype=bool)
    raw_amounts: List[bytes] = []
    op_codes = {"c": 0, "u": 1, "d": 2, "r": 3}

    for i, m in enumerate(msgs):
        try:
            payload = json.loads(m)["payload"]
        except (ValueError, KeyError, TypeError):
            raw_amounts.append(b"\x00")
            continue
        if payload is None:
            raw_amounts.append(b"\x00")
            continue
        row = payload.get("after") or payload.get("before")
        if row is None:
            raw_amounts.append(b"\x00")
            continue
        try:
            tx_id[i] = row["tx_id"]
            t_us[i] = row["tx_datetime"]
            cust[i] = row["customer_id"]
            term[i] = row["terminal_id"]
            amt = row.get("tx_amount")
            raw = base64.b64decode(amt) if amt is not None else b"\x00"
        except (KeyError, TypeError, ValueError):
            # incomplete/mistyped row image: mask, don't crash the batch
            # (matches the native decoder's behavior)
            raw_amounts.append(b"\x00")
            continue
        op[i] = op_codes.get(payload.get("op", "c"), 0)
        raw_amounts.append(raw)
        valid[i] = True

    cents = decode_decimal_batch(raw_amounts)
    if kafka_timestamps_ms is None:
        kts = t_us // 1000
    else:
        kts = np.asarray(kafka_timestamps_ms, dtype=np.int64)
    cols = {
        "tx_id": tx_id,
        "tx_datetime_us": t_us,
        "customer_id": cust,
        "terminal_id": term,
        "tx_amount_cents": cents,
        "op": op,
        "kafka_ts_ms": kts,
    }
    return cols, ~valid


def encode_profile_envelope(
    table: str,
    row: dict,
    op: str = "c",
    ts_ms: int = 0,
) -> bytes:
    """One Debezium envelope for a dimension-table row (customers/terminals).

    The reference's job1/job2 consume these from
    ``debezium.payment.{customers,terminals}`` with plain numeric columns
    (``kafka_s3_sink_customers.py:51-90``) — no binary decimals involved.
    """
    env = {
        "schema": {
            "type": "struct",
            "name": f"debezium.payment.{table}.Envelope",
        },
        "payload": {
            "before": None,
            "after": {
                k: (float(v) if isinstance(v, (float, np.floating)) else int(v))
                for k, v in row.items()
            },
            "source": {
                "connector": "postgresql",
                "db": "postgres",
                "schema": "payment",
                "table": table,
                "ts_ms": int(ts_ms),
            },
            "op": op,
            "ts_ms": int(ts_ms),
        },
    }
    return json.dumps(env, separators=(",", ":")).encode("utf-8")


def encode_profile_envelopes(
    table: str,
    columns: dict,
    ts_ms: int = 0,
) -> List[bytes]:
    """Columnar dict → list of envelopes, one per row."""
    names = list(columns)
    n = len(columns[names[0]]) if names else 0
    return [
        encode_profile_envelope(
            table, {k: columns[k][i] for k in names}, ts_ms=ts_ms
        )
        for i in range(n)
    ]


def decode_profile_envelopes(
    messages: Iterable[bytes],
    fields: Sequence[Tuple[str, str]],
    kafka_timestamps_ms: Optional[Sequence[int]] = None,
) -> Tuple[dict, np.ndarray]:
    """Decode dimension-table envelopes into columns per a TableSchema.

    Returns ``(columns, tombstone_mask)`` with ``op`` and ``kafka_ts_ms``
    columns appended, mirroring :func:`decode_transaction_envelopes`.
    Extraction semantics follow ``kafka_s3_sink_customers.py:124-160``:
    take ``payload.after`` (or ``before`` for deletes), mask null payloads.
    """
    msgs = list(messages)
    n = len(msgs)
    cols = {name: np.zeros(n, dtype=dt) for name, dt in fields}
    op = np.zeros(n, dtype=np.int8)
    valid = np.zeros(n, dtype=bool)
    op_codes = {"c": 0, "u": 1, "d": 2, "r": 3}
    for i, m in enumerate(msgs):
        try:
            payload = json.loads(m)["payload"]
        except (ValueError, KeyError, TypeError):
            continue
        if payload is None:
            continue
        row = payload.get("after") or payload.get("before")
        if row is None:
            continue
        try:
            for name, _ in fields:
                cols[name][i] = row[name]
        except (KeyError, TypeError, ValueError):
            for name, _ in fields:
                cols[name][i] = 0
            continue
        op[i] = op_codes.get(payload.get("op", "c"), 0)
        valid[i] = True
    cols["op"] = op
    if kafka_timestamps_ms is None:
        cols["kafka_ts_ms"] = np.zeros(n, dtype=np.int64)
    else:
        cols["kafka_ts_ms"] = np.asarray(kafka_timestamps_ms, dtype=np.int64)
    return cols, ~valid


def decode_transaction_envelopes_fast(
    messages: Iterable[bytes],
    kafka_timestamps_ms: Optional[Sequence[int]] = None,
) -> Tuple[dict, np.ndarray]:
    """Dispatcher: C++ scanner when buildable (≈6× faster), Python otherwise."""
    from real_time_fraud_detection_system_tpu.core import native

    if native.native_available():
        return native.decode_transaction_envelopes_native(
            messages, kafka_timestamps_ms
        )
    return decode_transaction_envelopes(messages, kafka_timestamps_ms)
