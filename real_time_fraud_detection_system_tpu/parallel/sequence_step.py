"""Multi-chip serving of the sequence (long-context) scorer.

The history state is the easiest of the engine states to shard: it is
keyed ONLY by customer, so with rows partitioned by ``customer % n_dev``
(the same Kafka-partition→device affinity as the window state,
``partition_batch_spill`` chunk 0) every update and gather is device-
local — zero collectives on the common path. Transformer params are
replicated (tiny next to the state).

Hot-key spill chunks place rows on arbitrary devices; those run the
ROUTED variant: (key, day, tod, amount) quadruples ride one
``all_to_all`` to the customer's owner, the owner runs the same fused
history step, and the probabilities ride the inverse ``all_to_all``
back — exactly the terminal-routing pattern of :mod:`.step`.

Cross-chunk semantics match the window state's: a spill chunk sees
prior chunks' state updates, i.e. chunks behave like consecutive
micro-batches. Within any one chunk the fused step time-sorts rows, so
ordering semantics equal the single-chip engine whenever the source
delivers per-customer rows in time order (the Kafka per-partition
guarantee).

Sharded layout: every :class:`~..features.history.HistoryState` leaf
gains a leading device axis ([n_dev, cap_local+1, ...], sharded on it);
each device block is a self-contained local HistoryState (with its own
padding-sink row), so the single-chip kernel runs unchanged inside
``shard_map``. Local slot for key k on its owner: ``(k // n_dev) &
(cap_local - 1)`` — mirroring the window layout (``step.py``), and like
it requiring ``key_mode="direct"``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.core.batch import TxBatch
from real_time_fraud_detection_system_tpu.parallel.mesh import (
    compat_shard_map,
)

# NOTE: features.history imports models.sequence, which imports
# parallel.ring_attention — importing history at module top would close
# an import cycle through this package's __init__; defer to call time.


def _stacked_blank(fcfg, n_dev: int, as_jnp: bool):
    """ONE source of truth for the sharded layout: n_dev stacked local
    blocks, each a self-contained HistoryState (own sink row)."""
    import numpy as np

    from real_time_fraud_detection_system_tpu.features.history import (
        init_history_state,
    )

    local = init_history_state(
        dataclasses.replace(
            fcfg, customer_capacity=fcfg.customer_capacity // n_dev))
    if as_jnp:
        return jax.tree.map(
            lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), local)
    return jax.tree.map(
        lambda a: np.broadcast_to(
            np.asarray(a)[None], (n_dev,) + a.shape).copy(), local)


def _require_pow2_local(cap_local: int) -> None:
    """Local slot math is ``(key // n) & (cap_local - 1)`` — a modulo only
    when cap_local is a power of two. A non-pow2 local capacity would pass
    the divisibility check yet silently merge distinct customers' history
    (breaking the EXACT elastic-reshard contract), so reject it here."""
    if cap_local <= 0 or (cap_local & (cap_local - 1)):
        raise ValueError(
            f"customer_capacity / n_devices must be a power of two, got "
            f"{cap_local}")


def init_sharded_history_state(
    cfg: Config, mesh: Mesh, axis: "str | tuple" = "data"
):
    """[n_dev, cap_local+1, ...] leaves, sharded on the device axis."""
    n_dev = int(mesh.devices.size)
    fcfg = cfg.features
    if fcfg.customer_capacity % n_dev:
        raise ValueError("customer_capacity must divide by n_devices")
    _require_pow2_local(fcfg.customer_capacity // n_dev)
    if fcfg.key_mode != "direct":
        raise ValueError(
            "sharded sequence serving requires key_mode='direct' "
            "(owner = key % n_dev, local slot = key // n_dev)")
    stacked = _stacked_blank(fcfg, n_dev, as_jnp=True)
    sh = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)


def shard_history_state(
    state, mesh: Mesh, axis: "str | tuple" = "data"
):
    """Re-place an already-stacked state onto the mesh (checkpoint
    restore)."""
    sh = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sh), state)


def reshard_history_state(state, cfg: Config, n_dev_new: int):
    """Elastic re-layout of a history state between device counts.

    In ``direct`` key mode with ids < capacity the maps are bijective
    (single-chip slot = key; sharded owner = key % n, local slot =
    key // n), so conversion is EXACT — restore a single-chip
    checkpoint into an 8-way sharded engine, or re-shard n→m after a
    topology change, with identical serving behavior (SURVEY §5.3's
    elastic-recovery role for the long-context state).

    Accepts either layout (single-chip ``[C+1, ...]`` leaves or stacked
    ``[n, C/n+1, ...]``) and returns host-side arrays in the target
    layout (``n_dev_new == 1`` → single-chip); callers place them on a
    mesh with :func:`shard_history_state`.
    """
    import numpy as np

    from real_time_fraud_detection_system_tpu.features.history import (
        HistoryState,
        init_history_state,
    )

    fcfg = cfg.features
    cap = fcfg.customer_capacity
    if fcfg.key_mode != "direct":
        raise ValueError("elastic re-shard requires key_mode='direct'")

    def to_single(s) -> HistoryState:
        leaves = [np.asarray(a) for a in s]
        if leaves[0].ndim == 3:  # already single-chip [C+1, K, F]
            if leaves[0].shape[0] != cap + 1:
                raise ValueError(
                    f"state capacity {leaves[0].shape[0] - 1} != "
                    f"config capacity {cap}")
            return HistoryState(*leaves)
        n_old = leaves[0].shape[0]
        cap_local = leaves[0].shape[1] - 1
        _require_pow2_local(cap_local)
        if n_old * cap_local != cap:
            raise ValueError(
                f"state layout {n_old}x{cap_local} != config "
                f"capacity {cap} — re-sharding a checkpoint taken under "
                "a different customer_capacity would silently merge or "
                "drop customers")
        single = jax.tree.map(
            np.asarray, init_history_state(fcfg))
        out = [np.array(a) for a in single]
        keys = np.arange(cap)
        owner, local = keys % n_old, (keys // n_old) & (cap_local - 1)
        for i, a in enumerate(leaves):
            out[i][keys] = a[owner, local]
        return HistoryState(*out)

    single = to_single(state)
    if n_dev_new == 1:
        return HistoryState(*[jnp.asarray(a) for a in single])
    if cap % n_dev_new:
        raise ValueError("customer_capacity must divide by n_dev_new")
    cap_local = cap // n_dev_new
    _require_pow2_local(cap_local)
    out = list(_stacked_blank(fcfg, n_dev_new, as_jnp=False))
    keys = np.arange(cap)
    owner, local = keys % n_dev_new, (keys // n_dev_new) & (cap_local - 1)
    for i, a in enumerate(single):
        out[i][owner, local] = np.asarray(a)[keys]
    return HistoryState(*[jnp.asarray(a) for a in out])


def make_sharded_sequence_step(
    cfg: Config,
    mesh: Mesh,
    axis: "str | tuple" = "data",
    route: bool = False,
):
    """→ jitted ``step(hstate, params, batch, order_key) -> (hstate, probs)``.

    ``batch`` leaves are [n_dev * B_local], sharded on axis 0 (the
    engine's partitioned chunk); ``order_key`` [n_dev * B_local] int32
    carries each row's ORIGINAL batch position (the same-second
    tiebreaker — chunk packing and routing both permute rows).
    ``route=False`` expects owner-placed rows; ``route=True`` exchanges
    rows to their owner first and routes probabilities back (spill
    chunks).
    """
    from real_time_fraud_detection_system_tpu.features.history import (
        init_history_state,
        update_and_score,
    )

    n_dev = int(mesh.devices.size)
    fcfg = cfg.features
    cap_local = fcfg.customer_capacity // n_dev
    lcfg = dataclasses.replace(fcfg, customer_capacity=cap_local)


    def slot_fn(key):
        return ((key // jnp.uint32(n_dev))
                & jnp.uint32(cap_local - 1)).astype(jnp.int32)

    def local_step(hstate, params, batch: TxBatch, order_key):
        from real_time_fraud_detection_system_tpu.parallel.step import (
            owner_route,
        )

        hs = jax.tree.map(lambda x: jnp.squeeze(x, 0), hstate)
        bl = batch.customer_key.shape[0]

        if route:
            dest = (batch.customer_key % jnp.uint32(n_dev)).astype(jnp.int32)
            send_pos, xchg, scatter = owner_route(
                dest, batch.valid, n_dev, axis, bl)
            rb = TxBatch(
                customer_key=xchg(scatter(batch.customer_key)),
                terminal_key=jnp.zeros(n_dev * bl, jnp.uint32),
                day=xchg(scatter(batch.day)),
                tod_s=xchg(scatter(batch.tod_s)),
                amount=xchg(scatter(batch.amount)),
                label=jnp.full(n_dev * bl, -1, jnp.int32),
                valid=xchg(scatter(batch.valid, fill=False)),
            )
            # the ORIGINAL batch row index rides along as the same-second
            # tiebreaker — both the dense spill packing (round-robin
            # across devices) and the all_to_all regrouping would
            # otherwise reorder ties relative to the single-chip engine
            r_order = xchg(scatter(order_key))
            hs, r_probs = update_and_score(
                hs, params, rb, lcfg, slot_fn, order_key=r_order)
            probs = xchg(r_probs)[send_pos]
        else:
            hs, probs = update_and_score(
                hs, params, batch, lcfg, slot_fn, order_key=order_key)

        return jax.tree.map(lambda x: x[None], hs), probs

    # eval_shape: spec structure without allocating a throwaway state
    state_spec = jax.tree.map(
        lambda _: P(axis),
        jax.eval_shape(lambda: init_history_state(lcfg)))
    batch_spec = jax.tree.map(
        lambda _: P(axis),
        TxBatch(*([0] * len(TxBatch._fields))))
    fn = compat_shard_map(
        local_step,
        mesh,
        # P() prefix: params replicated; order_key sharded like the batch
        (state_spec, P(), batch_spec, P(axis)),
        (state_spec, P(axis)),
    )
    return jax.jit(fn, donate_argnums=(0,))
