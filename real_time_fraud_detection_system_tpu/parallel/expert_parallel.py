"""Expert parallelism: top-1-routed MoE FFN with all_to_all dispatch.

Completes the parallelism suite (dp: sharded engines, tp:
``tensor_parallel``, pp: ``pipeline_parallel``, sp: ``ring_attention``):
one expert MLP per device, tokens routed to their expert's owner over
the same bucketed ``all_to_all`` primitive the terminal/sequence
exchanges use (:func:`..step.owner_route`), computed there, and routed
back scaled by the router gate.

The reference has no MoE — this is capacity the framework carries for
scorers past one chip's FLOPs, in the same spirit as TP/PP. Semantics
are pinned against :func:`moe_apply_dense` (the single-device oracle
that computes every token's expert locally): the worst-case exchange
buffer (n_dev × B_local per device, like the terminal exchange) means
NO token is ever dropped, so parity is exact — there is no
capacity-factor approximation to reason about.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class MoEParams(NamedTuple):
    """E experts, stacked on the leading axis (sharded one-per-device)."""

    w_router: jnp.ndarray  # [D, E] (replicated — tiny)
    w1: jnp.ndarray  # [E, D, F]
    b1: jnp.ndarray  # [E, F]
    w2: jnp.ndarray  # [E, F, D]
    b2: jnp.ndarray  # [E, D]

    @property
    def n_experts(self) -> int:
        return int(self.w1.shape[0])


def init_moe(d_model: int, d_ff: int, n_experts: int,
             seed: int = 0) -> MoEParams:
    key = jax.random.PRNGKey(seed)
    kr, k1, k2 = jax.random.split(key, 3)
    return MoEParams(
        w_router=jax.random.normal(kr, (d_model, n_experts)) / np.sqrt(d_model),
        w1=np.sqrt(2.0 / d_model)
        * jax.random.normal(k1, (n_experts, d_model, d_ff)),
        b1=jnp.zeros((n_experts, d_ff)),
        w2=np.sqrt(2.0 / d_ff)
        * jax.random.normal(k2, (n_experts, d_ff, d_model)),
        b2=jnp.zeros((n_experts, d_model)),
    )


def _route_and_gate(params: MoEParams, x: jnp.ndarray):
    """Top-1 router: → (expert id [B], gate value [B])."""
    logits = x @ params.w_router
    e = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    gate = jnp.take_along_axis(
        jax.nn.softmax(logits, axis=-1), e[:, None], axis=1)[:, 0]
    return e, gate


def _expert_ffn(params: MoEParams, e, x):
    """Per-token expert MLP via stacked-weight gathers (oracle path)."""
    h = jax.nn.relu(
        jnp.einsum("bd,bdf->bf", x, params.w1[e]) + params.b1[e])
    return jnp.einsum("bf,bfd->bd", h, params.w2[e]) + params.b2[e]


def moe_apply_dense(params: MoEParams, x: jnp.ndarray) -> jnp.ndarray:
    """Single-device oracle: every token's expert computed locally."""
    e, gate = _route_and_gate(params, x)
    return gate[:, None] * _expert_ffn(params, e, x)


def make_ep_apply(mesh: Mesh, params: MoEParams,
                  axis: Optional[str] = None):
    """→ (sharded_params, apply(params, x) → y): expert-parallel MoE.

    ``x [B, D]`` rows shard over ``axis`` (dp); experts shard one per
    device (requires n_experts == axis size). Each device routes its
    tokens to their expert's owner (one ``all_to_all`` out, the inverse
    back), computes ONLY its own expert's FFN, and scales by the gate
    computed where the token lives.
    """
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[0]
    n_dev = int(mesh.shape[axis])
    if params.n_experts != n_dev:
        raise ValueError(
            f"{params.n_experts} experts on a {n_dev}-device '{axis}' "
            "axis (want one expert per device)")
    specs = MoEParams(
        w_router=P(None, None),
        w1=P(axis), b1=P(axis), w2=P(axis), b2=P(axis),
    )
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, specs)

    def local_apply(p, x):
        from real_time_fraud_detection_system_tpu.parallel.step import (
            owner_route,
        )

        # local expert block: leading axis length 1
        w1, b1 = p.w1[0], p.b1[0]
        w2, b2 = p.w2[0], p.b2[0]
        bl = x.shape[0]
        e, gate = _route_and_gate(p, x)  # router replicated, tokens local
        send_pos, xchg, scatter = owner_route(
            e, jnp.ones(bl, bool), n_dev, axis, bl)
        received = xchg(scatter(x))  # tokens whose expert lives here
        out = jax.nn.relu(received @ w1 + b1) @ w2 + b2
        back = xchg(out)[send_pos]  # inverse exchange, un-bucketed
        return gate[:, None] * back

    apply_fn = jax.jit(compat_shard_map(
        local_apply, mesh, (specs, P(axis)), P(axis)))
    return sharded, apply_fn
