"""Tensor parallelism: Megatron-style sharded MLP forward/backward.

The reference has no model large enough to shard (its dormant PyTorch MLP,
``shared_functions.py:1312-1707``, is single-device), but a TPU-native
framework must scale its deep scorers past one chip's HBM/FLOPs: this
module shards the MLP of :mod:`..models.mlp` over a mesh axis the
standard way —

- layer 1 **column-parallel**: ``W1 [F, H]`` split on H; each device
  computes its slice of the hidden activation locally;
- layer 2 **row-parallel**: ``W2 [H, H2]`` split on H (the contraction
  axis); partial products are ``psum``-reduced over ICI — the ONE
  collective in the forward pass;
- remaining layers replicated (they are tiny: the head is ``[H2, 1]``).

The same function differentiates under ``shard_map``, with one caveat:
the forward all-reduce must carry a custom identity backward
(:func:`_allreduce_g` — Megatron's *g*; a plain ``psum`` re-transposes
to ``psum`` and inflates sharded-weight gradients by the axis size).
With that in place gradients of sharded weights come out sharded —
exactly what a per-device optax update wants.

:func:`make_tp_mlp`/:func:`make_tp_step` on a 1-axis mesh are PURE
tensor parallelism (batch replicated, weights split).
:func:`make_dp_tp_step` composes both on a 2-axis ``(dp, tp)`` mesh:
batch rows shard over ``dp``, weights over ``tp``, and the backward pass
adds the one extra collective DP requires — gradient ``psum`` over
``dp`` — while the TP weight grads stay shard-local exactly as in the
1-axis case. This is the standard 2D layout deep scorers deploy with.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.models.mlp import MLPParams


def tp_specs(params: MLPParams) -> List[Tuple[P, P]]:
    """PartitionSpecs per (W, b): col-parallel L1, row-parallel L2,
    replicated rest. The placeholder axis name "tp" is substituted with
    the mesh's real axis via :func:`_rename`."""
    specs: List[Tuple[P, P]] = []
    for i in range(len(params)):
        if i == 0:
            specs.append((P(None, "tp"), P("tp")))
        elif i == 1:
            specs.append((P("tp", None), P(None)))
        else:
            specs.append((P(None, None), P(None)))
    return specs


def _rename(spec: P, axis: str) -> P:
    return P(*[axis if s == "tp" else s for s in spec])


def shard_mlp_params(params: MLPParams, mesh: Mesh, axis: str) -> MLPParams:
    """Place params on the mesh with the TP layout (host → device)."""
    out: MLPParams = []
    for (w, b), (ws, bs) in zip(params, tp_specs(params)):
        out.append((
            jax.device_put(w, NamedSharding(mesh, _rename(ws, axis))),
            jax.device_put(b, NamedSharding(mesh, _rename(bs, axis))),
        ))
    return out


def _check_tp(params: MLPParams, n_shards: int) -> None:
    if len(params) < 3:
        # with 2 layers, the row-parallel layer would BE the head and the
        # hidden relu below would corrupt the logits
        raise ValueError(
            "tensor-parallel MLP needs >= 2 hidden layers "
            "(mlp_hidden=(H1, H2, ...))"
        )
    h_dim = params[0][0].shape[1]
    if h_dim % n_shards:
        raise ValueError(
            f"hidden width {h_dim} not divisible by {n_shards} shards"
        )


def _allreduce_g(axis: str):
    """Megatron's *g* function: ``psum`` forward, IDENTITY backward.

    Under ``shard_map`` with replication checks off, plain ``psum``
    transposes to another ``psum`` — but the cotangent arriving from the
    (replicated) downstream is already identical on every shard, so that
    second psum inflates sharded-weight gradients by the axis size
    (measured: exactly 8× on an 8-shard mesh; the loss still descends,
    which is why a learns-test can't catch it). The custom VJP passes
    the cotangent through unchanged — the mathematically correct
    transpose given replicated downstream compute.
    """

    @jax.custom_vjp
    def g(x):
        return jax.lax.psum(x, axis)

    def fwd(x):
        return jax.lax.psum(x, axis), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


def _identity_f(axis: str):
    """Megatron's *f* function: IDENTITY forward, ``psum`` backward.

    Placed at the ENTRY of a column-sharded region (before Q/K/V or the
    MLP up-projection): forward is a no-op on the replicated activation;
    backward sums the per-shard cotangents so gradients of replicated
    UPSTREAM params (embeddings, layernorms, earlier blocks) count every
    shard's heads/hidden-slice, not just the local one."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (jax.lax.psum(ct, axis),)

    f.defvjp(fwd, bwd)
    return f


def tp_mlp_logits(params: MLPParams, x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Per-shard forward (call under ``shard_map``): x [B, F] replicated,
    L1 weights column-sharded, L2 row-sharded → full logits [B] on every
    device after one psum."""
    (w1, b1), (w2, b2) = params[0], params[1]
    h = jax.nn.relu(x @ w1 + b1)  # [B, H/n] local
    partial_h2 = h @ w2  # [B, H2] partial over the contraction
    h2 = _allreduce_g(axis)(partial_h2) + b2  # the ONE forward collective
    h = jax.nn.relu(h2)
    for w, b in params[2:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return (h @ w + b)[..., 0]


def make_tp_mlp(mesh: Mesh, params: MLPParams, axis: Optional[str] = None):
    """→ (sharded_params, predict_proba(params, x)) jitted over the mesh.

    ``x`` is replicated (pure TP); compose with the row-sharded engine
    step for DP×TP. Requires ≥ 2 hidden layers and hidden width divisible
    by the axis size.
    """
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[-1]
    _check_tp(params, mesh.shape[axis])
    sharded = shard_mlp_params(params, mesh, axis)
    specs = [
        (_rename(ws, axis), _rename(bs, axis))
        for ws, bs in tp_specs(params)
    ]

    def _predict(p, x):
        return jax.nn.sigmoid(tp_mlp_logits(p, x, axis))

    predict_proba = jax.jit(
        compat_shard_map(_predict, mesh, (specs, P()), P()))
    return sharded, predict_proba


def make_tp_step(mesh: Mesh, params: MLPParams, lr: float = 1e-2,
                 axis: Optional[str] = None,
                 dp_axis: Optional[str] = None):
    """→ (sharded_params, step(params, x, y) → (params, loss)): one SGD
    step with TP-sharded weights; weight grads stay shard-local
    (:func:`_allreduce_g` gives each shard exactly its gradient slice).

    With ``dp_axis`` set (2-axis mesh), batch rows shard over it and the
    backward adds the one collective DP requires: grads (and the
    reported loss) are mean-``psum``'d over ``dp_axis`` so every dp
    replica applies the identical update — the standard 2D DP×TP layout.
    Batch size must divide by the dp axis.
    """
    import optax

    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[-1]
    _check_tp(params, mesh.shape[axis])
    sharded = shard_mlp_params(params, mesh, axis)
    specs = [
        (_rename(ws, axis), _rename(bs, axis)) for ws, bs in tp_specs(params)
    ]
    n_dp = mesh.shape[dp_axis] if dp_axis else 1
    x_spec = P(dp_axis) if dp_axis else P()

    def loss_fn(p, x, y):
        logits = tp_mlp_logits(p, x, axis)
        per = optax.sigmoid_binary_cross_entropy(
            logits, y.astype(jnp.float32))
        return per.mean()

    def _step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        if dp_axis:
            # the ONE extra DP collective: average across row groups
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g, dp_axis) / n_dp, grads)
            loss = jax.lax.psum(loss, dp_axis) / n_dp
        new = jax.tree.map(lambda w, g: w - lr * g, p, grads)
        return new, loss

    step = jax.jit(compat_shard_map(
        _step, mesh, (specs, x_spec, x_spec), (specs, P())))
    return sharded, step


def make_dp_tp_step(
    mesh: Mesh,
    params: MLPParams,
    lr: float = 1e-2,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
):
    """2D DP×TP training step on a 2-axis mesh — see :func:`make_tp_step`."""
    return make_tp_step(mesh, params, lr=lr, axis=tp_axis, dp_axis=dp_axis)


# ---------------------------------------------------------------------------
# Transformer TP (Megatron attention + MLP split for models/sequence.py)
# ---------------------------------------------------------------------------


def transformer_tp_specs(params, axis: str):
    """PartitionSpecs for :class:`..models.sequence.TransformerParams`:
    per block, Q/K/V sharded on the HEAD axis (each shard attends with
    its own heads — softmax is per-head, so head sharding is exact), the
    output projection row-parallel, the MLP column/row split; embeddings,
    layernorms, and the scalar head replicated."""
    from real_time_fraud_detection_system_tpu.models.sequence import (
        BlockParams,
        TransformerParams,
    )

    rep2, rep1 = P(None, None), P(None)
    blk = BlockParams(
        ln1_g=rep1, ln1_b=rep1,
        wq=P(None, axis, None), wk=P(None, axis, None),
        wv=P(None, axis, None),
        wo=P(axis, None, None),
        ln2_g=rep1, ln2_b=rep1,
        w1=P(None, axis), b1=P(axis),
        w2=P(axis, None), b2=rep1,
    )
    return TransformerParams(
        embed_w=rep2, embed_b=rep1,
        blocks=tuple(blk for _ in params.blocks),
        lnf_g=rep1, lnf_b=rep1,
        head_w=rep2, head_b=rep1,
    )


def tp_transformer_logits(params, x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Per-shard causal-transformer forward (under ``shard_map``): the
    SAME :func:`..models.sequence.transformer_logits` code path, with its
    two row-parallel contractions per block all-reduced via
    :func:`_allreduce_g` (the ``reduce_fn`` hook). Attention is
    naive-causal over the LOCAL heads (head-sharded attention is exact;
    ring/blockwise attention composes with sequence parallelism, not
    this head split)."""
    from real_time_fraud_detection_system_tpu.models.sequence import (
        transformer_logits,
    )

    return transformer_logits(
        params, x,
        reduce_fn=_allreduce_g(axis),
        enter_fn=_identity_f(axis),
    )


def _shard_transformer(mesh: Mesh, params, axis: str):
    """Validate divisibility and place TransformerParams with the TP
    layout. Shared by the logits factory and the train-step factory."""
    n = mesh.shape[axis]
    n_heads = params.blocks[0].wq.shape[1]
    d_ff = params.blocks[0].w1.shape[1]
    if n_heads % n or d_ff % n:
        raise ValueError(
            f"n_heads {n_heads} and d_ff {d_ff} must divide by {n} shards"
        )
    specs = transformer_tp_specs(params, axis)
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, specs,
    )
    return specs, sharded


def make_tp_transformer_step(
    mesh: Mesh,
    params,
    lr: float = 1e-3,
    pos_weight: float = 1.0,
    axis: Optional[str] = None,
    dp_axis: Optional[str] = None,
):
    """→ (sharded_params, step(params, x, y, mask) → (params, loss)):
    one SGD step of the head/MLP-sharded transformer (masked BCE, the
    sequence family's loss). Optionally DP×TP on a 2-axis mesh: rows
    shard over ``dp_axis``; per-group losses/grads combine with a
    weight-proportional psum (masked-mean losses weight by each group's
    mask mass, matching the full-batch masked mean when groups carry
    different numbers of live positions)."""
    from real_time_fraud_detection_system_tpu.models.sequence import (
        transformer_loss,
    )
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[-1]
    specs, sharded = _shard_transformer(mesh, params, axis)
    x_spec = P(dp_axis) if dp_axis else P()

    def _step(p, x, y, mask):
        def loss_fn(p_):
            return transformer_loss(
                p_, x, y, mask, pos_weight=pos_weight,
                reduce_fn=_allreduce_g(axis),
                enter_fn=_identity_f(axis))

        loss, grads = jax.value_and_grad(loss_fn)(p)
        if dp_axis:
            # masked (pos-weighted) mean over dp groups: weight each
            # group's loss/grads by its weight mass — the same mass the
            # loss normalizes by — so the combined update equals the
            # full-batch masked mean; an empty group carries zero weight
            wts = jnp.where(y.astype(jnp.float32) > 0, pos_weight, 1.0)
            w = (wts * mask.astype(jnp.float32)).sum()
            tot = jnp.maximum(jax.lax.psum(w, dp_axis), 1.0)
            # the group loss normalized by max(w, 1) — mirror that clamp
            # here, or a group with mass in (0,1) would be down-weighted
            # by w twice (loss=s/1 scaled by w/tot vs the true s/tot)
            scale = jnp.maximum(w, 1.0) / tot
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g * scale, dp_axis), grads)
            loss = jax.lax.psum(loss * scale, dp_axis)
        new = jax.tree.map(lambda v, g: v - lr * g, p, grads)
        return new, loss

    step = jax.jit(compat_shard_map(
        _step, mesh, (specs, x_spec, x_spec, x_spec), (specs, P())))
    return sharded, step


def make_tp_transformer(mesh: Mesh, params, axis: Optional[str] = None):
    """→ (sharded_params, logits(params, x)) with heads + MLP hidden
    sharded over ``axis``. Requires n_heads and d_ff divisible by the
    axis size."""
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[-1]
    specs, sharded = _shard_transformer(mesh, params, axis)

    def _logits(p, x):
        return tp_transformer_logits(p, x, axis)

    logits = jax.jit(compat_shard_map(_logits, mesh, (specs, P()), P()))
    return sharded, logits
