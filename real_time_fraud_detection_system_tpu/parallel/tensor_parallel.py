"""Tensor parallelism: Megatron-style sharded MLP forward/backward.

The reference has no model large enough to shard (its dormant PyTorch MLP,
``shared_functions.py:1312-1707``, is single-device), but a TPU-native
framework must scale its deep scorers past one chip's HBM/FLOPs: this
module shards the MLP of :mod:`..models.mlp` over a mesh axis the
standard way —

- layer 1 **column-parallel**: ``W1 [F, H]`` split on H; each device
  computes its slice of the hidden activation locally;
- layer 2 **row-parallel**: ``W2 [H, H2]`` split on H (the contraction
  axis); partial products are ``psum``-reduced over ICI — the ONE
  collective in the forward pass;
- remaining layers replicated (they are tiny: the head is ``[H2, 1]``).

The same function differentiates under ``shard_map`` (JAX transposes the
``psum`` to the backward broadcast automatically), so the online-SGD path
works sharded without extra code. Gradients of sharded weights come out
sharded — exactly what a per-device optax update wants.

This module implements PURE tensor parallelism: the batch is replicated
and only weights are split. Composing with data parallelism (rows
sharded over a second mesh axis + gradient ``psum`` over it) is what
:func:`..step.make_sharded_step` does for the serving models; a DP×TP
MLP would add that axis here — not yet wired, so use a 1-axis mesh.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.models.mlp import MLPParams


def tp_specs(params: MLPParams) -> List[Tuple[P, P]]:
    """PartitionSpecs per (W, b): col-parallel L1, row-parallel L2,
    replicated rest. The placeholder axis name "tp" is substituted with
    the mesh's real axis via :func:`_rename`."""
    specs: List[Tuple[P, P]] = []
    for i in range(len(params)):
        if i == 0:
            specs.append((P(None, "tp"), P("tp")))
        elif i == 1:
            specs.append((P("tp", None), P(None)))
        else:
            specs.append((P(None, None), P(None)))
    return specs


def _rename(spec: P, axis: str) -> P:
    return P(*[axis if s == "tp" else s for s in spec])


def shard_mlp_params(params: MLPParams, mesh: Mesh, axis: str) -> MLPParams:
    """Place params on the mesh with the TP layout (host → device)."""
    out: MLPParams = []
    for (w, b), (ws, bs) in zip(params, tp_specs(params)):
        out.append((
            jax.device_put(w, NamedSharding(mesh, _rename(ws, axis))),
            jax.device_put(b, NamedSharding(mesh, _rename(bs, axis))),
        ))
    return out


def _check_tp(params: MLPParams, n_shards: int) -> None:
    if len(params) < 3:
        # with 2 layers, the row-parallel layer would BE the head and the
        # hidden relu below would corrupt the logits
        raise ValueError(
            "tensor-parallel MLP needs >= 2 hidden layers "
            "(mlp_hidden=(H1, H2, ...))"
        )
    h_dim = params[0][0].shape[1]
    if h_dim % n_shards:
        raise ValueError(
            f"hidden width {h_dim} not divisible by {n_shards} shards"
        )


def tp_mlp_logits(params: MLPParams, x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Per-shard forward (call under ``shard_map``): x [B, F] replicated,
    L1 weights column-sharded, L2 row-sharded → full logits [B] on every
    device after one psum."""
    (w1, b1), (w2, b2) = params[0], params[1]
    h = jax.nn.relu(x @ w1 + b1)  # [B, H/n] local
    partial_h2 = h @ w2  # [B, H2] partial over the contraction
    h2 = jax.lax.psum(partial_h2, axis) + b2  # the ONE forward collective
    h = jax.nn.relu(h2)
    for w, b in params[2:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return (h @ w + b)[..., 0]


def make_tp_mlp(mesh: Mesh, params: MLPParams, axis: Optional[str] = None):
    """→ (sharded_params, predict_proba(params, x)) jitted over the mesh.

    ``x`` is replicated (pure TP); compose with the row-sharded engine
    step for DP×TP. Requires ≥ 2 hidden layers and hidden width divisible
    by the axis size.
    """
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[0]
    _check_tp(params, mesh.shape[axis])
    sharded = shard_mlp_params(params, mesh, axis)
    specs = [
        (_rename(ws, axis), _rename(bs, axis))
        for ws, bs in tp_specs(params)
    ]

    def _predict(p, x):
        return jax.nn.sigmoid(tp_mlp_logits(p, x, axis))

    predict_proba = jax.jit(
        compat_shard_map(_predict, mesh, (specs, P()), P()))
    return sharded, predict_proba


def make_tp_step(mesh: Mesh, params: MLPParams, lr: float = 1e-2,
                 axis: Optional[str] = None):
    """→ (sharded_params, step(params, x, y) → (params, loss)): one SGD
    step with TP-sharded weights; weight grads stay shard-local (the psum
    transpose gives each shard exactly its gradient slice)."""
    import optax

    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[0]
    _check_tp(params, mesh.shape[axis])
    sharded = shard_mlp_params(params, mesh, axis)
    specs = [
        (_rename(ws, axis), _rename(bs, axis)) for ws, bs in tp_specs(params)
    ]

    def loss_fn(p, x, y):
        logits = tp_mlp_logits(p, x, axis)
        per = optax.sigmoid_binary_cross_entropy(
            logits, y.astype(jnp.float32))
        return per.mean()

    def _step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        new = jax.tree.map(lambda w, g: w - lr * g, p, grads)
        return new, loss

    step = jax.jit(
        compat_shard_map(_step, mesh, (specs, P(), P()), (specs, P())))
    return sharded, step
