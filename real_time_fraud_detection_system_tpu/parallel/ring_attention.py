"""Ring attention: sequence-parallel attention over an ICI ring.

The reference's only sequence model is the dead commented seq2seq-attention
section (``fraud_detection_model/shared_functions.py:1649-1707``) — additive
attention over a per-customer transaction history, single device, O(T^2)
memory. This module is its live, TPU-first successor for LONG histories:
the sequence axis is sharded across the device mesh, and attention runs as
a ring — each device holds its local Q block resident, and K/V blocks
rotate around the ring via ``ppermute`` while an online-softmax accumulator
(the Flash-Attention recurrence) folds in one block per step. Peak memory is
O(T_local^2 / n_dev) per device and the K/V transfer rides ICI, overlapping
with the block matmuls.

Design notes (TPU/XLA):

- static shapes throughout: the rotation loop is a ``lax.fori_loop`` with a
  static ``ppermute`` ring permutation — one compiled step, n_dev trips;
- the online-softmax state (m, l, o) uses f32 accumulators regardless of
  input dtype (bf16-safe);
- causal masking is done with *global* positions reconstructed from
  ``axis_index``: Q block b holds rows [b*T_l, (b+1)*T_l), and at ring step
  i the resident K/V block is the one originally owned by device
  (my_index - i) mod n_dev;
- the same kernel body (``_block_attn``) runs unsharded for the single-chip
  path (``blockwise_attention``), so parity tests can diff ring vs local
  bit-for-bit semantics.

Used by :mod:`..models.sequence` when histories exceed one device's HBM
budget; exercised multi-chip in ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_attn(q, k, v, m, l, o, q_off, k_off, sm_scale, causal,
                kv_limit=None):
    """One online-softmax accumulation step against a K/V block.

    q: [B, Tq, H, D] (resident); k/v: [B, Tk, H, D] (visiting block);
    (m, l, o): running (row-max, row-sum, unnormalized out) in f32.
    q_off/k_off: global position offsets of the blocks (for causal masks).
    ``kv_limit`` masks keys at global position >= kv_limit (padding tail).
    Returns updated (m, l, o).
    """
    bq, tq, h, d = q.shape
    tk = k.shape[1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm_scale
    kpos = k_off + jnp.arange(tk, dtype=jnp.int32)
    if causal:
        qpos = q_off + jnp.arange(tq, dtype=jnp.int32)
        mask = qpos[:, None] >= kpos[None, :]  # [Tq, Tk]
        s = jnp.where(mask[None, None, :, :], s, -jnp.inf)
    if kv_limit is not None:
        s = jnp.where((kpos < kv_limit)[None, None, None, :], s, -jnp.inf)

    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # A fully-masked block (causal, future device) has m_blk = -inf; keep the
    # old statistics untouched in that case (exp(-inf - -inf) guards below).
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])  # [B, H, Tq, Tk]
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)  # rescale old
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _finalize(m, l, o, dtype):
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    return (o / denom).astype(dtype)


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_size: int = 512,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-device flash-style attention ([B, T, H, D] layout).

    The memory-bounded local form of :func:`ring_attention` — same
    recurrence, K/V blocks visited by a ``fori_loop`` instead of a ring.
    """
    b, t, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    nblk = max(1, -(-t // block_size))
    tpad = nblk * block_size
    if tpad != t:
        pad = [(0, 0), (0, tpad - t), (0, 0), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        # padded K rows must never win the softmax: mask via causal offsets
        # (qpos < kpos for the pad tail) or explicit -inf for non-causal.
    m0 = jnp.full((b, h, t), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t), dtype=jnp.float32)
    o0 = jnp.zeros((b, t, h, d), dtype=jnp.float32)

    def body(i, carry):
        m, l, o = carry
        kb = jax.lax.dynamic_slice_in_dim(k, i * block_size, block_size, 1)
        vb = jax.lax.dynamic_slice_in_dim(v, i * block_size, block_size, 1)
        k_off = i * block_size
        # Causal: padded keys sit at kpos >= t > any qpos, so the causal mask
        # already excludes them; non-causal needs the explicit kv_limit.
        m, l, o = _block_attn(
            q, kb, vb, m, l, o,
            q_off=jnp.int32(0), k_off=k_off,
            sm_scale=scale, causal=causal,
            kv_limit=None if causal else t,
        )
        return m, l, o

    m, l, o = jax.lax.fori_loop(0, nblk, body, (m0, l0, o0))
    return _finalize(m, l, o, q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention inside ``shard_map``.

    q/k/v: [B, T_local, H, D] — the LOCAL shard of a sequence sharded over
    ``axis_name`` (global T = n_dev * T_local, device i owning rows
    [i*T_local, (i+1)*T_local)). Returns the local output shard.

    Ring schedule: at step i, this device attends its resident Q against the
    K/V block originally owned by device (idx - i) mod n_dev, then passes its
    current K/V to the next device ((idx + 1) mod n_dev) via ``ppermute`` —
    n_dev steps visit every block with only nearest-neighbor ICI traffic.
    """
    n_dev = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, tl, h, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    q_off = idx * tl

    m0 = jnp.full((b, h, tl), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, tl), dtype=jnp.float32)
    o0 = jnp.zeros((b, tl, h, d), dtype=jnp.float32)
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def body(i, carry):
        m, l, o, kb, vb = carry
        src = jnp.remainder(idx - i, n_dev)  # owner of the visiting block
        m, l, o = _block_attn(
            q, kb, vb, m, l, o,
            q_off=q_off, k_off=src * tl,
            sm_scale=scale, causal=causal,
        )
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return m, l, o, kb, vb

    m, l, o, _, _ = jax.lax.fori_loop(0, n_dev, body, (m0, l0, o0, k, v))
    return _finalize(m, l, o, q.dtype)


def _make_seq_sharded_attn(kernel, mesh: Mesh, axis: str):
    """Shared wrapper for the sequence-parallel attention forms: global
    [B, T, H, D] arrays with T sharded over ``axis``; returns
    ``fn(q, k, v) -> out`` sharded like q. The caller's arrays may live
    anywhere; jit inserts the resharding collectives. One factory keeps
    the ring and Ulysses contracts drop-in interchangeable."""
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    spec = P(None, axis, None, None)
    return jax.jit(compat_shard_map(kernel, mesh, (spec, spec, spec),
                                    spec))


def make_ring_attention_sharded(
    mesh: Mesh,
    axis: str = "data",
    causal: bool = True,
):
    """Ring form of the sequence-parallel attention wrapper (see
    :func:`_make_seq_sharded_attn`)."""
    return _make_seq_sharded_attn(
        partial(ring_attention, axis_name=axis, causal=causal), mesh, axis)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool = True,
    block_size: int = 512,
) -> jnp.ndarray:
    """All-to-all sequence parallelism (the DeepSpeed-Ulysses form).

    The complementary long-context layout to :func:`ring_attention`
    (SURVEY's "ring attention or all-to-all sequence/context
    parallelism"): instead of rotating K/V blocks around the ring while
    the sequence stays sharded, two ``all_to_all`` collectives (one
    stacked q/k/v exchange in, one out) re-shard the tensors from
    sequence-sharded to HEAD-sharded for the attention itself — each
    device then holds the FULL sequence for H/n heads and runs an
    ordinary (here: flash/blockwise) causal attention with zero inner
    communication, before the inverse exchange restores the
    sequence-sharded layout.

    Trade-off vs the ring: 2 all-to-alls of activation size (bandwidth,
    all-at-once) vs n_dev ppermute hops (latency, overlapped with
    compute); Ulysses needs ``n_heads % n_dev == 0`` while the ring
    shards any head count. Both are exact (same online-softmax math) —
    parity is test-pinned against :func:`blockwise_attention`.

    Local view: q/k/v [B, T_local, H, D] with the global sequence
    device-major over the axis; returns the same layout.
    """
    n = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses_attention needs n_heads ({h}) divisible by the "
            f"mesh axis size ({n}); use ring_attention otherwise")
    # sequence-sharded -> head-sharded: split heads, gather sequence
    # (device order along T = global order, since T blocks are
    # device-major). q/k/v ride ONE stacked exchange — a collective
    # launch is latency-bound on a real mesh, so one [3, ...] all_to_all
    # beats three.
    qkv = jnp.stack((q, k, v))  # [3, B, T_local, H, D]
    qh, kh, vh = jax.lax.all_to_all(
        qkv, axis_name, split_axis=3, concat_axis=2, tiled=True)
    out = blockwise_attention(qh, kh, vh, block_size=block_size,
                              causal=causal)
    # head-sharded -> sequence-sharded (inverse exchange)
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def make_ulysses_attention_sharded(
    mesh: Mesh,
    axis: str = "data",
    causal: bool = True,
    block_size: int = 512,
):
    """Ulysses form of the sequence-parallel attention wrapper — same
    contract as :func:`make_ring_attention_sharded` (see
    :func:`_make_seq_sharded_attn`), so the two forms are drop-in
    interchangeable."""
    return _make_seq_sharded_attn(
        partial(ulysses_attention, axis_name=axis, causal=causal,
                block_size=block_size), mesh, axis)
